"""iraces/: per-class lock-set inference over ``self.<field>`` accesses.

The lock-*order* rules (ilocks/) prove the locks compose; nothing proved
the locks are *used*.  This pass closes that gap in the style of lock-set
race detection (Eraser) and compositional ownership reasoning (RacerD):

1. **Field access sites.** callgraph's scanner records every
   ``self.<field>`` read, rebind, and in-place container mutation with
   the lock tokens held lexically at the site.

2. **Entry lock-sets.** Locks held at a *call site* protect the callee's
   body too (``_drain_dead`` is only ever called under ``_lock``), so a
   fixpoint over the call graph computes, per function, the set of
   possible held-at-entry lock sets from every observed caller.  A
   function nobody in the project calls is assumed externally callable
   with nothing held; a ``*_locked`` function is credited its class's
   guarding lock (the convention ilocks/ enforces).

3. **Thread roots.** A class is only racy if more than one thread can
   touch it.  Roots are functions handed to ``threading.Thread(target=)``,
   ``Timer``, executor ``.submit``, metric collector/callback-gauge
   registrations, weakref death callbacks, ``__del__``, and RPC service
   handlers; everything reachable from a root runs off the constructing
   thread.  Classes carrying a ``@guarded_by`` declaration
   (utils/locking.py) are shared by assertion and always checked.

Rules:

- ``iraces/unguarded-shared-write`` — a write site where some path holds
  none of the class's locks, while the field is declared ``@guarded_by``
  or written under a lock elsewhere.
- ``iraces/inconsistent-lock-set`` — every access is locked, but the
  intersection of the lock sets is empty (``_a`` here, ``_b`` there).
- ``iraces/guarded-read-unguarded-write`` — readers take a lock the
  writers bypass (no declaration, no locked write anywhere).
- ``iraces/callback-into-locked-state`` — a weakref/GC callback mutates
  guarded state: inline (a death-callback lambda) or by re-entering an
  RLock-guarded method, which can interleave with a critical section
  mid-iteration on the same thread — the PR-6 bug shape.

The runtime half lives in utils/locking.py: the lock witness records
(field, lock-held) observations under ``--lock_witness`` and
``--witness-check`` fails when runtime contradicts a static "guarded"
fact derived here (see :func:`static_guarded_facts`).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from yugabyte_db_tpu.analysis import callgraph
from yugabyte_db_tpu.analysis.core import (
    Violation,
    call_name,
    dotted_name,
    project_rule,
)

# Construction/serialization methods: the object is not shared yet (or
# the interpreter serializes access), so their writes are not sites.
_EXEMPT_METHODS = frozenset({
    "__init__", "__new__", "__getstate__", "__setstate__", "__del__",
})

# Bound on distinct entry lock-sets tracked per function; beyond it the
# sets collapse to their intersection (sound: never claims a lock held
# on a path that might not hold it).
_ENTRY_SET_CAP = 8

_GC_KINDS = frozenset({"weakref", "gc"})

_SYN_SUFFIX = ".<locked>"  # *_locked in a multi-lock class: held, unknown which


@dataclass
class _Access:
    attr: str
    line: int
    kind: str            # "read" | "write" | "mut"
    fn: object           # FunctionInfo
    held_always: frozenset  # own-lock tokens held on EVERY path to the site
    may_unheld: bool     # some path reaches the site with no own lock


@dataclass
class _ClassModel:
    ci: object                       # ClassInfo
    threaded: bool
    own_tokens: frozenset            # this class's lock tokens (+ synthetic)
    fields: dict                     # attr -> list[_Access]
    decl_tokens: dict                # attr -> declared lock token
    lock_short: dict                 # token -> "_lock" (attr name, messages)


class _Model:
    def __init__(self, index):
        self.index = index
        self.registrations = []      # (kind, expr_node, FunctionInfo)
        self.threaded_fns = set()
        self.gc_reachable = set()
        self.entry = {}
        self.classes = {}            # class qualname -> _ClassModel
        self._build()

    # -- thread roots --------------------------------------------------------
    def _collect_registrations(self):
        for fn in self.index.functions.values():
            node = fn.node
            if node is None or not hasattr(node, "body"):
                continue
            stack = list(node.body)
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue  # nested defs are their own FunctionInfos
                if isinstance(n, ast.Call):
                    reg = _registration(n)
                    if reg is not None:
                        self.registrations.append((reg[0], reg[1], fn))
                stack.extend(ast.iter_child_nodes(n))

    def _root_quals(self, kinds=None):
        quals = set()
        for kind, expr, fn in self.registrations:
            if kinds is not None and kind not in kinds:
                continue
            if isinstance(expr, ast.Lambda):
                # Calls inside the lambda body run in the callback context.
                for sub in ast.walk(expr.body):
                    if isinstance(sub, ast.Call):
                        quals.update(self.index.resolve_ref(
                            call_name(sub), fn))
                continue
            quals.update(self.index.resolve_ref(dotted_name(expr), fn))
        if kinds is None or "gc" in kinds:
            quals.update(f.qualname for f in self.index.functions.values()
                         if f.name == "__del__")
        return quals

    def _reachable(self, roots):
        seen = set(roots)
        stack = list(roots)
        while stack:
            fn = self.index.functions.get(stack.pop())
            if fn is None:
                continue
            for cs in fn.calls:
                for callee in cs.callees:
                    if callee not in seen:
                        seen.add(callee)
                        stack.append(callee)
        return seen

    # -- entry lock-sets -----------------------------------------------------
    def _entry_sets(self, external):
        index = self.index
        in_edges: dict[str, bool] = {}
        for fn in index.functions.values():
            for cs in fn.calls:
                for callee in cs.callees:
                    in_edges[callee] = True
        entry: dict[str, set] = {}
        for q in index.functions:
            if q in external or q not in in_edges:
                entry[q] = {frozenset()}
        # Saturated callees keep a single intersection set; intersections
        # only shrink and unsaturated sets only grow, so this terminates.
        saturated: set[str] = set()
        changed = True
        while changed:
            changed = False
            for fn in index.functions.values():
                src = entry.get(fn.qualname)
                if not src:
                    continue
                for cs in fn.calls:
                    if not cs.callees:
                        continue
                    contrib = {cs.held | e for e in src}
                    for callee in cs.callees:
                        cur = entry.setdefault(callee, set())
                        if callee in saturated:
                            new = {frozenset.intersection(*cur, *contrib)}
                        else:
                            new = cur | contrib
                            if len(new) > _ENTRY_SET_CAP:
                                saturated.add(callee)
                                new = {frozenset.intersection(*new)}
                        if new != cur:
                            entry[callee] = new
                            changed = True
        for q in index.functions:
            if not entry.get(q):
                entry[q] = {frozenset()}
        return entry

    # -- per-class field tables ----------------------------------------------
    def _build(self):
        index = self.index
        self._collect_registrations()
        handler_quals = {f.qualname for f in index.handlers()}
        all_roots = self._root_quals() | handler_quals
        self.threaded_fns = self._reachable(all_roots)
        self.gc_reachable = self._reachable(self._root_quals(_GC_KINDS))
        self.entry = self._entry_sets(external=all_roots)

        methods_by_class: dict[str, list] = {}
        for fn in index.functions.values():
            if fn.cls is not None:
                methods_by_class.setdefault(
                    f"{fn.module}.{fn.cls}", []).append(fn)

        for cq, ci in index.classes.items():
            if not ci.lock_attrs and not ci.guarded_decl:
                continue
            methods = methods_by_class.get(cq, [])
            threaded = bool(ci.guarded_decl) or any(
                m.qualname in self.threaded_fns for m in methods)
            syn = cq + _SYN_SUFFIX
            own = set()
            for attr in ci.lock_attrs:
                own.add(f"{cq}.{ci.lock_aliases.get(attr, attr)}")
            decl_tokens = {}
            for fld, lk in ci.guarded_decl.items():
                tok = f"{cq}.{ci.lock_aliases.get(lk, lk)}"
                own.add(tok)
                decl_tokens[fld] = tok
            own.add(syn)
            own = frozenset(own)
            lock_short = {tok: tok.rsplit(".", 1)[-1] for tok in own}

            # The `*_locked` convention means "caller holds the class's
            # guarding lock" — credit a specific lock only when the class
            # has exactly ONE candidate AND some call site corroborates it
            # (calls a *_locked method while holding that lock).  Without
            # corroboration the convention may refer to an EXTERNAL lock
            # (engines are serialized by the tablet's write lock), so a
            # synthetic token keeps the site non-racy without letting it
            # vouch for other sites.
            reals = [a for a, k in ci.lock_attrs.items() if k != "Condition"]
            conv = syn
            if len(reals) == 1:
                cand = f"{cq}.{reals[0]}"
                for fn in methods:
                    if any(cand in cs.held
                           and (cs.raw.rsplit(".", 1)[-1].endswith("_locked")
                                or any(self.index.functions[c].requires_lock
                                       for c in cs.callees
                                       if c in self.index.functions))
                           for cs in fn.calls):
                        conv = cand
                        break

            fields: dict[str, list] = {}
            skip_attrs = (set(ci.lock_attrs) | set(ci.lock_aliases)
                          | set(ci.guarded_decl.values()))
            for fn in methods:
                base_sets = self.entry.get(fn.qualname) or [frozenset()]
                extra = frozenset({conv}) if fn.requires_lock else frozenset()
                for attr, line, kind, held in fn.field_accesses:
                    if attr in skip_attrs:
                        continue
                    if kind == "mut" and attr not in ci.container_attrs:
                        continue
                    sets = [(e | held | extra) & own for e in base_sets]
                    fields.setdefault(attr, []).append(_Access(
                        attr=attr, line=line, kind=kind, fn=fn,
                        held_always=frozenset.intersection(*sets),
                        may_unheld=any(not s for s in sets)))
            self.classes[cq] = _ClassModel(
                ci=ci, threaded=threaded, own_tokens=own, fields=fields,
                decl_tokens=decl_tokens, lock_short=lock_short)

    # -- shared fact: is this field guarded? ---------------------------------
    def guard_token(self, cm: _ClassModel, attr: str) -> str | None:
        """The lock token the class guards ``attr`` with: the declared
        lock, else any lock some non-init write site always holds."""
        tok = cm.decl_tokens.get(attr)
        if tok is not None:
            return tok
        syn = cm.ci.qualname + _SYN_SUFFIX
        for a in cm.fields.get(attr, ()):
            real = a.held_always - {syn}
            if a.kind in ("write", "mut") and a.fn.name != "__init__" \
                    and real:
                return sorted(real)[0]
        return None


def _registration(node: ast.Call):
    """(kind, callback_expr) when ``node`` hands a callable to another
    execution context, else None."""
    raw = call_name(node)
    if not raw:
        return None
    tail = raw.rsplit(".", 1)[-1]
    kws = {k.arg: k.value for k in node.keywords if k.arg}
    args = node.args
    if tail == "Thread":
        tgt = kws.get("target")
        return ("thread", tgt) if tgt is not None else None
    if tail == "Timer":
        tgt = args[1] if len(args) > 1 else kws.get("function")
        return ("timer", tgt) if tgt is not None else None
    if tail == "submit" and "." in raw and args:
        return ("executor", args[0])
    if raw.startswith("weakref") and tail in ("ref", "finalize") \
            and len(args) > 1:
        return ("weakref", args[1])
    if tail == "add_collector" and args:
        return ("collector", args[0])
    if tail == "gauge":
        tgt = args[1] if len(args) > 1 else kws.get("fn")
        return ("collector", tgt) if tgt is not None else None
    return None


def _model(index) -> _Model:
    m = getattr(index, "_iraces_model", None)
    if m is None:
        m = index._iraces_model = _Model(index)
    return m


def _site_label(a: _Access) -> str:
    return f"{a.fn.rel}:{a.line}"


def _short(cm: _ClassModel, tokens) -> str:
    names = sorted(cm.lock_short.get(t, t) for t in tokens)
    return "/".join(names) if names else "<none>"


# -- rules --------------------------------------------------------------------

@project_rule("iraces/unguarded-shared-write")
def check_unguarded_shared_write(index):
    """A write site reachable with no class lock held, on a field the
    class elsewhere treats as lock-protected (declared or locked
    writes)."""
    model = _model(index)
    for cm in model.classes.values():
        if not cm.threaded:
            continue
        syn = cm.ci.qualname + _SYN_SUFFIX
        for attr, accesses in cm.fields.items():
            decl_tok = cm.decl_tokens.get(attr)
            sites = [a for a in accesses
                     if a.fn.name not in _EXEMPT_METHODS]
            locked_writes = [a for a in sites
                             if a.kind in ("write", "mut")
                             and a.held_always - {syn}]
            for a in sites:
                if a.kind == "read" or not a.may_unheld:
                    continue
                evidence = None
                if decl_tok is not None:
                    evidence = (f"declared @guarded_by("
                                f"\"{cm.lock_short[decl_tok]}\")")
                else:
                    others = [w for w in locked_writes if w is not a]
                    if others:
                        w = others[0]
                        evidence = (f"written under "
                                    f"`{_short(cm, w.held_always - {syn})}`"
                                    f" at {_site_label(w)}")
                if evidence is None:
                    continue
                yield Violation(
                    "iraces/unguarded-shared-write", a.fn.rel, a.line,
                    f"`self.{attr}` written without a lock on "
                    f"multi-threaded class `{cm.ci.name}` — field is "
                    f"{evidence}; take the lock or defer the mutation",
                    f"usw:{cm.ci.name}.{attr}")


@project_rule("iraces/inconsistent-lock-set")
def check_inconsistent_lock_set(index):
    """Every access is locked, but no single lock is common to all of
    them — mutual exclusion holds pairwise only by luck."""
    model = _model(index)
    for cm in model.classes.values():
        if not cm.threaded:
            continue
        syn = cm.ci.qualname + _SYN_SUFFIX
        for attr, accesses in cm.fields.items():
            shared = [a for a in accesses
                      if a.fn.name not in _EXEMPT_METHODS]
            sites = [a for a in shared
                     if not a.may_unheld and syn not in a.held_always]
            writes = [a for a in sites if a.kind in ("write", "mut")]
            if len(sites) < 2 or not writes:
                continue
            # Unguarded (non-construction) sites are the other rules'
            # findings; here every shared site holds SOME lock.
            if any(a.may_unheld for a in shared):
                continue
            common = frozenset.intersection(*[a.held_always for a in sites])
            if common:
                continue
            first = sites[0]
            other = next((a for a in sites[1:]
                          if a.held_always != first.held_always), sites[1])
            yield Violation(
                "iraces/inconsistent-lock-set", other.fn.rel, other.line,
                f"`self.{attr}` on `{cm.ci.name}` is locked everywhere "
                f"but by no common lock: `{_short(cm, first.held_always)}` "
                f"at {_site_label(first)} vs "
                f"`{_short(cm, other.held_always)}` here",
                f"ils:{cm.ci.name}.{attr}")


@project_rule("iraces/guarded-read-unguarded-write")
def check_guarded_read_unguarded_write(index):
    """Readers lock, writers don't: the lock documents an intent the
    write path silently violates (no declaration, no locked write)."""
    model = _model(index)
    for cm in model.classes.values():
        if not cm.threaded:
            continue
        syn = cm.ci.qualname + _SYN_SUFFIX
        for attr, accesses in cm.fields.items():
            if attr in cm.decl_tokens:
                continue
            sites = [a for a in accesses
                     if a.fn.name not in _EXEMPT_METHODS]
            if any(a.kind in ("write", "mut") and a.held_always - {syn}
                   for a in sites):
                continue  # iraces/unguarded-shared-write territory
            locked_reads = [a for a in sites
                            if a.kind == "read" and a.held_always - {syn}
                            and not a.may_unheld]
            if not locked_reads:
                continue
            for a in sites:
                if a.kind == "read" or not a.may_unheld:
                    continue
                r = locked_reads[0]
                yield Violation(
                    "iraces/guarded-read-unguarded-write", a.fn.rel, a.line,
                    f"`self.{attr}` written without the "
                    f"`{_short(cm, r.held_always - {syn})}` that readers hold "
                    f"(e.g. {_site_label(r)}) on multi-threaded class "
                    f"`{cm.ci.name}`",
                    f"grw:{cm.ci.name}.{attr}")


@project_rule("iraces/callback-into-locked-state")
def check_callback_into_locked_state(index):
    """Weakref death callbacks and ``__del__`` run at arbitrary
    allocation points — possibly re-entrantly on a thread already inside
    the class.  Mutating guarded state from one corrupts invariants even
    when an RLock "protects" it (re-entry succeeds mid-critical-section).
    Fix shape: enqueue into an unguarded atomic buffer, drain under the
    lock (storage/residency.py `_dead`)."""
    model = _model(index)
    # Inline lambdas registered as weakref callbacks.
    for kind, expr, fn in model.registrations:
        if kind not in _GC_KINDS or not isinstance(expr, ast.Lambda):
            continue
        cm = model.classes.get(f"{fn.module}.{fn.cls}") if fn.cls else None
        if cm is None:
            continue
        for sub in ast.walk(expr.body):
            if not isinstance(sub, ast.Call):
                continue
            parts = call_name(sub).split(".")
            if len(parts) == 3 and parts[0] == "self" \
                    and parts[2] in callgraph._MUTATOR_METHODS:
                tok = model.guard_token(cm, parts[1])
                if tok is not None:
                    yield Violation(
                        "iraces/callback-into-locked-state",
                        fn.rel, sub.lineno,
                        f"weakref callback mutates `self.{parts[1]}` "
                        f"(guarded by `{cm.lock_short.get(tok, tok)}`) on "
                        f"`{cm.ci.name}` — callbacks fire at arbitrary "
                        f"allocation points; enqueue and drain under the "
                        f"lock instead",
                        f"cbl:{cm.ci.name}.{parts[1]}")
    # Methods reachable from a GC/weakref root that write guarded state
    # under an RLock: re-entrant acquisition succeeds mid-critical-section.
    for cm in model.classes.values():
        for attr, accesses in cm.fields.items():
            for a in accesses:
                if a.kind == "read" or a.fn.name in _EXEMPT_METHODS:
                    continue
                if a.fn.qualname not in model.gc_reachable \
                        and a.fn.name != "__del__":
                    continue
                if a.fn.name == "__del__" or not a.held_always:
                    tok = model.guard_token(cm, attr)
                    if tok is None:
                        continue
                    yield Violation(
                        "iraces/callback-into-locked-state",
                        a.fn.rel, a.line,
                        f"`self.{attr}` (guarded by "
                        f"`{cm.lock_short.get(tok, tok)}`) mutated on a "
                        f"GC/weakref callback path without the lock on "
                        f"`{cm.ci.name}`",
                        f"cbl:{cm.ci.name}.{attr}")
                    continue
                rlocked = [t for t in a.held_always
                           if index.lock_kind(t) == "RLock"]
                if rlocked:
                    yield Violation(
                        "iraces/callback-into-locked-state",
                        a.fn.rel, a.line,
                        f"`self.{attr}` mutated under re-entrant "
                        f"`{_short(cm, rlocked)}` on a GC/weakref callback "
                        f"path — the callback can interleave with a "
                        f"critical section on the SAME thread "
                        f"(`{cm.ci.name}`); defer via an atomic queue",
                        f"cbl:{cm.ci.name}.{attr}")


# -- witness cross-check ------------------------------------------------------

def static_guarded_facts(index) -> dict:
    """(class simple name, field) -> declared lock attr, for every
    ``@guarded_by`` declaration in the tree.  The runtime witness keys
    observations by simple class name; declarations are rare enough
    that collisions don't arise in practice."""
    facts = {}
    for ci in index.classes.values():
        for fld, lock_attr in ci.guarded_decl.items():
            facts[(ci.name, fld)] = lock_attr
    return facts


def witness_contradictions(index, dump: dict) -> list[str]:
    """Human-readable contradiction lines: runtime saw an unheld write
    to a field the static pass calls guarded.  Empty list == consistent."""
    facts = static_guarded_facts(index)
    out = []
    for obs in dump.get("observations", ()):
        key = (obs.get("class"), obs.get("field"))
        unheld = int(obs.get("unheld", 0))
        if unheld > 0 and key in facts:
            sites = ", ".join(obs.get("unheld_sites", [])[:3]) or "?"
            out.append(
                f"{key[0]}.{key[1]}: {unheld} write(s) without "
                f"`{facts[key]}` held (declared @guarded_by) — e.g. {sites}")
    return out
