"""Interprocedural engine: project-wide call graph + per-function summaries.

One ``ProjectIndex`` is built per analysis run from every parsed
``SourceFile``. It models the whole package at function granularity:

- a **function table** (module functions, methods, nested defs) with the
  direct facts each ``i*`` rule family needs — locks acquired, exception
  types raised, blocking-RPC sites, host-sync sites, JAX-traced status,
  whether the return value carries an error channel;
- **call sites** resolved to project functions through a tiered scheme
  (self-methods, typed attributes, local/imported names, constructor
  types, and a unique-method-name fallback), each annotated with the
  locks held at the call, whether the result is discarded, whether a
  timeout is passed, and which exception types the surrounding ``try``
  catches;
- memoized **transitive summaries** (locks acquired downstream, exception
  types that can escape, error-channel returns) so rules ask questions
  like "does anything this call reaches acquire a conflicting lock?"
  without re-walking the tree.

Resolution is deliberately conservative: an ambiguous call resolves to
nothing rather than to every candidate, so interprocedural findings stay
actionable. The unique-name fallback is suppressed for method names
common enough to collide across unrelated classes.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from yugabyte_db_tpu.analysis.core import (
    PACKAGE_ROOT,
    SourceFile,
    call_name,
    dotted_name,
)

# Names too generic for the unique-method-name fallback: resolving
# `x.get()` to some random class's `get` would poison every summary.
_COMMON_METHOD_NAMES = frozenset({
    "get", "set", "put", "add", "remove", "pop", "close", "open", "start",
    "stop", "run", "send", "recv", "call", "handle", "apply", "append",
    "extend", "clear", "update", "items", "keys", "values", "join", "wait",
    "notify", "read", "write", "flush", "reset", "copy", "encode", "decode",
    "submit", "shutdown", "acquire", "release", "connect", "register",
    "unregister", "begin", "commit", "abort", "insert", "delete", "scan",
    "next", "load", "save", "sleep",
})

# Blocking RPC primitives, matched on the raw dotted call text: every
# outbound call in the tree goes through a `*.transport.send(...)` seam
# or a Proxy. (`sock.send` never matches — the chain must name the seam.)
_BLOCKING_RAW_SUFFIXES = ("transport.send",)
_BLOCKING_QUALNAME_TAILS = ("Proxy.call", "Transport.send",
                            "SocketTransport.send", "LocalTransport.send",
                            "BoundTransport.send")

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_TIMEOUT_WORDS = ("timeout", "deadline")

# Container methods that mutate the receiver in place: `self._d.pop(k)`
# is a write to self._d's state even though no attribute is rebound.
# (iraces/ treats these as write sites; the runtime witness cannot see
# them, which is exactly why the static pass must.)
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "move_to_end", "rotate",
})

# Constructors whose result is a mutable container.  A field must be
# assigned one of these (or a literal/comprehension) somewhere in its
# class before _MUTATOR_METHODS calls on it count as mutations —
# `self.session.insert(...)` and `self.clock.update(...)` are domain
# methods on objects that synchronize themselves.
_CONTAINER_CTORS = frozenset({
    "dict", "list", "set", "frozenset", "bytearray", "OrderedDict",
    "defaultdict", "deque", "Counter", "ChainMap", "WeakSet",
    "WeakValueDictionary", "WeakKeyDictionary",
})

# Tokens whose presence in a while-loop's test or body mark the loop as
# BOUNDED: either by a retry budget (deadline/attempts — the
# utils.retry discipline) or by service lifecycle (a daemon's
# `while self._running` pump retries for as long as the server lives,
# which is deliberate, not a bug). irpc/bare-retry-loop only fires on
# loops with none of these.
_LOOP_BOUND_TOKENS = ("deadline", "remaining", "expired", "attempt",
                      "retries", "budget", "policy", "monotonic",
                      "running", "stopped", "alive", "shutdown", "closed",
                      "done")
_STATUS_HELPERS = {"Status", "ok", "not_found", "invalid_argument",
                   "illegal_state", "ql_error"}
_HOST_SYNC_TAILS = (".item", ".tolist")
_HOST_TRANSFER = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "np.ascontiguousarray"}

# Device-upload primitives (ijax/unmanaged-device-put): explicit
# placement, and the implicit jnp constructors that device_put host data.
_UPLOAD_ASARRAY = {"jnp.asarray", "jnp.array", "jax.numpy.asarray",
                   "jax.numpy.array"}

# -- resource protocols (ires/) ----------------------------------------------
# Method name -> (kind, verb). The pairing token ("obj") is the receiver
# text as written; release verbs that take the resource key as their
# first argument (invalidate) pair on that argument instead, and
# key-returning acquires (add_external) pair on the assignment target.
# Tracker verbs only count on receivers that name a tracker, and probe
# verbs only on breaker receivers — `consume`/`release`/`allow` are too
# generic otherwise.
_RESOURCE_VERBS = {
    "pin": ("pin", "acquire"),
    "unpin": ("pin", "release"),
    "add_external": ("pin", "acquire"),
    "invalidate": ("pin", "release"),
    "retire": ("pin", "release"),
    "consume": ("tracker", "acquire"),
    "release": ("tracker", "release"),
    "allow": ("probe", "acquire"),
    "record_success": ("probe", "release"),
    "record_failure": ("probe", "release"),
    "trip": ("probe", "release"),
}
# Lifecycle methods OWN the protocol — a method literally named `pin`
# is the acquire primitive, not a leak.
_RESOURCE_LIFECYCLE_NAMES = frozenset(_RESOURCE_VERBS) | frozenset({
    "register", "close", "reset", "release_pins", "_release_pins",
    "detach", "invalidate_device",
})

# Blocking primitives for iholds/ (beyond the RPC seams above): the WAL
# fsync, the device fetch barrier, sleeps, and `.wait()` on
# conditions/events. `detail` carries the condition's aliased lock token
# so waiting on the SAME lock (the legal release-and-wait pattern) is
# exempt.
_BLOCKING_FETCH = {"jax.device_get", "jax.block_until_ready"}


def _upload_fact(node: ast.Call) -> tuple[int, str, str] | None:
    """(line, kind, first-arg text) when ``node`` uploads host data to
    the device, else None.  kind is "device_put" or "asarray"."""
    raw = call_name(node)
    if not raw:
        return None
    if raw == "device_put" or raw.endswith(".device_put"):
        kind = "device_put"
    elif raw in _UPLOAD_ASARRAY:
        kind = "asarray"
    else:
        return None
    arg = ""
    if node.args:
        arg = dotted_name(node.args[0])
        if not arg:
            try:
                arg = ast.unparse(node.args[0])
            except Exception:  # noqa: BLE001 — best-effort label
                arg = ""
    return (node.lineno, kind, arg)


def _contract_decorator(fn) -> tuple[str, int] | None:
    """(entry, max_compiles) from a literal @compile_contract("name",
    max_compiles=N) decorator, else None. Non-literal declarations are
    ignored — the runtime decorator rejects them anyway."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        if dotted_name(dec.func).rsplit(".", 1)[-1] != "compile_contract":
            continue
        entry = None
        budget = None
        if dec.args and isinstance(dec.args[0], ast.Constant) \
                and isinstance(dec.args[0].value, str):
            entry = dec.args[0].value
        if len(dec.args) > 1 and isinstance(dec.args[1], ast.Constant) \
                and isinstance(dec.args[1].value, int):
            budget = dec.args[1].value
        for kw in dec.keywords:
            if kw.arg == "max_compiles" \
                    and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, int):
                budget = kw.value.value
        if entry is not None and budget is not None:
            return (entry, budget)
    return None


def _direct_static_params(fn) -> set[str]:
    """Parameter names that are jit-static for a directly decorated
    function: static_argnums/static_argnames literals on the
    ``partial(jax.jit, ...)`` (or ``jax.jit(...)``) decorator."""
    from yugabyte_db_tpu.analysis import jax_hygiene

    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        name = dotted_name(dec.func).rsplit(".", 1)[-1]
        is_partial_jit = name == "partial" and any(
            dotted_name(a).rsplit(".", 1)[-1] in ("jit", "pjit")
            for a in dec.args)
        if name not in ("jit", "pjit") and not is_partial_jit:
            continue
        argnums: list = []
        argnames: list = []
        for kw in dec.keywords:
            if kw.arg == "static_argnums":
                argnums = [v for v in jax_hygiene._literal_elems(kw.value)
                           if isinstance(v, int)]
            elif kw.arg == "static_argnames":
                argnames = [v for v in jax_hygiene._literal_elems(kw.value)
                            if isinstance(v, str)]
        return jax_hygiene._static_param_names(fn, argnums, argnames)
    return set()


def _jit_factory_return(fn) -> ast.AST | None:
    """The argument of a top-level ``return jax.jit(<X>)`` in ``fn``
    (not inside a nested def), else None."""
    for sub in _walk_skip_defs(fn.body):
        if isinstance(sub, ast.Return) and isinstance(sub.value, ast.Call):
            raw = call_name(sub.value)
            if raw.rsplit(".", 1)[-1] in ("jit", "pjit") and sub.value.args:
                return sub.value.args[0]
    return None


def _unwrap_traced(expr: ast.AST, factory, depth: int = 0) -> str | None:
    """Simple name of the python function actually traced under a
    ``jax.jit(...)`` factory return: unwraps ``partial``/``vmap``/
    ``shard_map``/``pmap`` layers and follows local ``name = <call>``
    bindings inside the factory body."""
    if depth > 5 or expr is None:
        return None
    if isinstance(expr, ast.Name):
        # A name bound to a wrapper call inside the factory body.
        for sub in _walk_skip_defs(factory.body):
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call) \
                    and any(isinstance(t, ast.Name) and t.id == expr.id
                            for t in sub.targets):
                return _unwrap_traced(sub.value, factory, depth + 1)
        return expr.id
    if isinstance(expr, ast.Call):
        name = call_name(expr).rsplit(".", 1)[-1]
        if name in ("partial", "vmap", "shard_map", "pmap", "checkpoint",
                    "remat") and expr.args:
            return _unwrap_traced(expr.args[0], factory, depth + 1)
    return None


@dataclass
class CallSite:
    raw: str                       # dotted call text as written
    line: int
    callees: tuple[str, ...] = ()  # resolved project qualnames (0 or 1, usually)
    held: frozenset = frozenset()  # lock tokens held at this call
    discards: bool = False         # bare expression statement — result dropped
    timeout_arg: bool = False      # a timeout/deadline argument is passed
    caught: frozenset = frozenset()  # exception names the enclosing try catches
    caught_broad: bool = False     # enclosing try has except [Base]Exception
    retry_loop: int = 0            # line of enclosing BARE while-retry loop
    #                                (no budget/lifecycle bound), 0 if none


@dataclass
class ResourceSite:
    """One acquire/release event of a resource protocol (ires/)."""
    line: int
    kind: str              # "pin" | "tracker" | "probe"
    verb: str              # "acquire" | "release"
    obj: str               # pairing token (receiver / key arg / target)
    arm: tuple = ()        # branch-arm path — prefix-incomparable paths
    #                        are disjoint (the double-release test)
    cleanup: str = ""      # "finally" | "handler" when the site sits in a
    #                        try's cleanup region (protects acquires)
    cleanup_broad: bool = False  # handler catches [Base]Exception / bare


@dataclass
class FunctionInfo:
    qualname: str
    module: str
    cls: str | None
    name: str
    rel: str
    lineno: int
    node: object = field(repr=False, default=None)
    requires_lock: bool = False        # *_locked convention
    locks: set = field(default_factory=set)         # tokens acquired directly
    order_pairs: list = field(default_factory=list)  # (outer_tok, inner_tok, line)
    calls: list = field(default_factory=list)        # [CallSite]
    direct_raises: set = field(default_factory=set)
    host_syncs: list = field(default_factory=list)   # (line, description)
    traced: bool = False               # a JAX-traced context (intra rules own it)
    has_timeout_param: bool = False
    checks_code: bool = False          # reads resp.get("code") / resp["code"]
    returns_value: bool = False
    returns_rpc_resp: bool = False     # returns a blocking-primitive result
    returns_status: bool = False       # returns a utils.status Status
    return_calls: list = field(default_factory=list)  # raw names returned
    uploads: list = field(default_factory=list)  # (line, kind, arg text)
    # self.<attr> access sites for iraces/: (attr, line, kind, held)
    # where kind is "read" | "write" | "mut" and held the lock tokens
    # held lexically at the site (entry-context added interprocedurally
    # by analysis/fields.py).
    field_accesses: list = field(default_factory=list)
    # (target name, raw call text, line) for `x = f(...)` bindings —
    # ijit/ traces device-value provenance through these.
    assign_calls: list = field(default_factory=list)
    # Device->host transfer candidates for ijit/hot-path-transfer:
    # (line, kind, operand text) where kind is "item" | "asarray" |
    # "cast". Same sites as host_syncs, but with the operand kept so
    # the rule can ask whether a *device* value is being fetched.
    transfers: list = field(default_factory=list)
    # Jit-entry facts (None for ordinary functions): dict with kind
    # ("factory" | "direct"), line, entry/budget from a literal
    # @compile_contract decorator (None when uncontracted),
    # static_params (factory params, or jit static_argnums/argnames),
    # inner (qualname of the traced callee for factories), and
    # captures ([(kind, name, line)] with kind "self" | "global").
    jit_entry: dict | None = None
    # Resource-protocol sites for ires/: [ResourceSite] (acquire and
    # release events with their pairing token, branch-arm path, and
    # try/finally coverage).
    resources: list = field(default_factory=list)
    # Ownership-escape events for ires/: (line, name) — a local resource
    # owner stored into `self.*`/a container/another object, passed to a
    # call, or returned (= ownership transferred out of this frame).
    escapes: list = field(default_factory=list)
    # Return statements: (line, frozenset of names the returned
    # expression mentions, trivial) — trivial means bare/None/constant.
    returns: list = field(default_factory=list)
    # Blocking facts for iholds/: (line, kind, detail, held) with kind
    # "rpc" | "fsync" | "device_fetch" | "cond_wait" | "sleep" | "wait",
    # detail the waited condition's aliased lock token (cond_wait only),
    # and held the lock tokens held lexically at the site.
    blocking: list = field(default_factory=list)


@dataclass
class ClassInfo:
    qualname: str
    module: str
    name: str
    bases: list = field(default_factory=list)        # raw base names
    methods: dict = field(default_factory=dict)      # simple name -> qualname
    attr_types: dict = field(default_factory=dict)   # attr -> raw class name
    lock_attrs: dict = field(default_factory=dict)   # attr -> "Lock"|"RLock"
    lock_aliases: dict = field(default_factory=dict)  # cv attr -> lock attr
    guarded_decl: dict = field(default_factory=dict)  # field -> lock attr
    #   (from literal @guarded_by("_lock", "_f", ...) class decorators)
    container_attrs: set = field(default_factory=set)  # attrs assigned a
    #   container literal/ctor somewhere; only these can have "mut"
    #   accesses (a .insert/.update on an unknown type is a domain
    #   method, not a container mutation)


def _is_handler_name(name: str) -> bool:
    return name.startswith("_h_") or name == "handle" \
        or name.startswith("handle_")


def _timeout_in_call(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg and any(w in kw.arg for w in _TIMEOUT_WORDS):
            return True
    for arg in node.args:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) \
                    and any(w in sub.id for w in _TIMEOUT_WORDS):
                return True
            if isinstance(sub, ast.Attribute) \
                    and any(w in sub.attr for w in _TIMEOUT_WORDS):
                return True
    return False


def is_blocking_raw(raw: str) -> bool:
    return any(raw.endswith(s) for s in _BLOCKING_RAW_SUFFIXES)


def _mentions_bound_token(nodes) -> bool:
    """Any name/attribute among ``nodes`` mentioning a budget or
    lifecycle token (see _LOOP_BOUND_TOKENS)."""
    for sub in nodes:
        if isinstance(sub, ast.Name):
            ident = sub.id.lower()
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr.lower()
        else:
            continue
        if any(tok in ident for tok in _LOOP_BOUND_TOKENS):
            return True
    return False


def _walk_skip_defs(nodes: list):
    """ast.walk over statements, not descending into nested defs (they
    run on their own stack, not in the enclosing loop)."""
    stack = list(nodes)
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if not isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                stack.append(c)


def _has_retry_except(body: list) -> bool:
    """True when the loop body contains a ``try`` whose handler
    ``continue``s — the retry-on-failure shape."""
    for sub in _walk_skip_defs(body):
        if not isinstance(sub, ast.Try):
            continue
        for handler in sub.handlers:
            if any(isinstance(n, ast.Continue)
                   for hs in handler.body for n in ast.walk(hs)):
                return True
    return False


def _mentions_static_shape(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "dtype", "itemsize"):
            return True
        if isinstance(sub, ast.Call) and call_name(sub) in ("len", "range"):
            return True
    return False


class _FunctionScanner(ast.NodeVisitor):
    """Single pass over one function body collecting the direct facts.

    Tracks a held-locks stack (``with self.<lock>:`` / class-level locks)
    and a caught-exceptions stack (``try`` bodies) so each call site is
    annotated with its context. Nested function defs are skipped — they
    get their own FunctionInfo and scanner.
    """

    def __init__(self, info: FunctionInfo, cls: ClassInfo | None,
                 class_names: set):
        self.info = info
        self.cls = cls
        self.class_names = class_names  # locally visible class names (locks)
        self.held: list[str] = []
        self.caught: list[tuple[frozenset, bool]] = []
        self._expr_calls: set[int] = set()  # Call node ids that are bare stmts
        self._bare_loops: list[int] = []    # enclosing bare-retry-loop lines

    # -- lock tokens ---------------------------------------------------------
    def _lock_token(self, expr: ast.AST) -> str | None:
        """Token for a with-item that names a known lock, else None."""
        raw = dotted_name(expr)
        if not raw:
            return None
        parts = raw.split(".")
        if parts[0] == "self" and len(parts) == 2 and self.cls is not None:
            attr = self.cls.lock_aliases.get(parts[1], parts[1])
            if attr in self.cls.lock_attrs:
                return f"{self.cls.qualname}.{attr}"
        if len(parts) == 2 and parts[0] in self.class_names:
            # ClassName._class_level_lock (shared across instances)
            return f"{self.info.module}.{parts[0]}.{parts[1]}"
        return None

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            tok = self._lock_token(item.context_expr)
            if tok is not None:
                for outer in self.held:
                    self.info.order_pairs.append((outer, tok, node.lineno))
                self.info.locks.add(tok)
                self.held.append(tok)
                acquired.append(tok)
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    # -- retry loops ---------------------------------------------------------
    def visit_While(self, node: ast.While):
        """A ``while`` whose test AND body mention no budget or lifecycle
        bound, but whose body retries via except-continue, is a bare
        retry loop — every call inside is annotated with its line so
        irpc/bare-retry-loop can ask whether one reaches a blocking RPC.
        (``for`` loops are never bare: their iterator is the bound —
        the clean pattern is ``for attempt in policy.attempts()``.)"""
        bare = (not _mentions_bound_token(ast.walk(node.test))
                and not _mentions_bound_token(_walk_skip_defs(node.body))
                and _has_retry_except(node.body))
        if bare:
            self._bare_loops.append(node.lineno)
        self.generic_visit(node)
        if bare:
            self._bare_loops.pop()

    # -- try context ---------------------------------------------------------
    def visit_Try(self, node: ast.Try):
        types: set[str] = set()
        broad = False
        for h in node.handlers:
            t = h.type
            if t is None:
                broad = True
                continue
            for n in (t.elts if isinstance(t, ast.Tuple) else [t]):
                nm = dotted_name(n).rsplit(".", 1)[-1]
                if nm in ("Exception", "BaseException"):
                    broad = True
                elif nm:
                    types.add(nm)
        self.caught.append((frozenset(types), broad))
        for stmt in node.body:
            self.visit(stmt)
        self.caught.pop()
        for h in node.handlers:
            for stmt in h.body:
                self.visit(stmt)
        for stmt in node.orelse + node.finalbody:
            self.visit(stmt)

    # -- statements feeding summaries ---------------------------------------
    def visit_Expr(self, node: ast.Expr):
        if isinstance(node.value, ast.Call):
            self._expr_calls.add(id(node.value))
        self.generic_visit(node)

    def visit_Raise(self, node: ast.Raise):
        exc = node.exc
        if exc is None:
            self.info.direct_raises.add("<reraise>")
        else:
            if isinstance(exc, ast.Call):
                exc = exc.func
            nm = dotted_name(exc).rsplit(".", 1)[-1]
            self.info.direct_raises.add(nm or "<unknown>")
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return):
        if node.value is not None:
            self.info.returns_value = True
            if isinstance(node.value, ast.Call):
                raw = call_name(node.value)
                self.info.return_calls.append(raw)
                if is_blocking_raw(raw):
                    self.info.returns_rpc_resp = True
                if raw.rsplit(".", 1)[-1] in _STATUS_HELPERS:
                    self.info.returns_status = True
            elif isinstance(node.value, ast.Name):
                # `resp = self.transport.send(...); return resp` — treat a
                # returned name that was bound to a blocking call as an
                # rpc-response return (single pass: bindings seen earlier).
                if node.value.id in getattr(self, "_rpc_bound", ()):
                    self.info.returns_rpc_resp = True
        self.generic_visit(node)

    # -- self.<field> accesses (iraces/) -------------------------------------
    def _record_access(self, attr: str, line: int, kind: str) -> None:
        if self.cls is not None:
            self.info.field_accesses.append(
                (attr, line, kind, frozenset(self.held)))

    def _self_attr(self, node: ast.AST) -> str | None:
        """attr name when ``node`` is ``self.<attr>``, else None."""
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def _note_container(self, tgt: ast.AST, value: ast.AST | None) -> None:
        if self.cls is None or value is None:
            return
        attr = self._self_attr(tgt)
        if attr is None:
            return
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                              ast.SetComp, ast.DictComp)):
            self.cls.container_attrs.add(attr)
        elif isinstance(value, ast.Call):
            name = call_name(value).rsplit(".", 1)[-1]
            if name in _CONTAINER_CTORS:
                self.cls.container_attrs.add(attr)

    def _record_write_target(self, tgt: ast.AST, line: int) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._record_write_target(elt, line)
            return
        if isinstance(tgt, ast.Starred):
            self._record_write_target(tgt.value, line)
            return
        attr = self._self_attr(tgt)
        if attr is None and isinstance(tgt, ast.Subscript):
            # self._d[k] = v mutates self._d.
            attr = self._self_attr(tgt.value)
        if attr is not None:
            self._record_access(attr, line, "write")

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.ctx, ast.Load):
            attr = self._self_attr(node)
            if attr is not None:
                self._record_access(attr, node.lineno, "read")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record_write_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record_write_target(node.target, node.lineno)
            self._note_container(node.target, node.value)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for tgt in node.targets:
            self._record_write_target(tgt, node.lineno)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            self._record_write_target(tgt, node.lineno)
            self._note_container(tgt, node.value)
        if isinstance(node.value, ast.Call) \
                and is_blocking_raw(call_name(node.value)):
            bound = getattr(self, "_rpc_bound", None)
            if bound is None:
                bound = self._rpc_bound = set()
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    bound.add(tgt.id)
        if isinstance(node.value, ast.Call):
            raw = call_name(node.value)
            if raw:
                for tgt in node.targets:
                    elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                    for elt in elts:
                        if isinstance(elt, ast.Name):
                            self.info.assign_calls.append(
                                (elt.id, raw, node.lineno))
        self.generic_visit(node)

    @staticmethod
    def _operand_text(node: ast.AST) -> str:
        text = dotted_name(node)
        if text:
            return text
        try:
            return ast.unparse(node)
        except Exception:  # noqa: BLE001 — best-effort label
            return ""

    def visit_Call(self, node: ast.Call):
        fact = _upload_fact(node)
        if fact is not None:
            self.info.uploads.append(fact)
        raw = call_name(node)
        if raw:
            mut_parts = raw.split(".")
            if len(mut_parts) == 3 and mut_parts[0] == "self" \
                    and mut_parts[2] in _MUTATOR_METHODS:
                self._record_access(mut_parts[1], node.lineno, "mut")
            if raw.endswith(_HOST_SYNC_TAILS):
                self.info.host_syncs.append(
                    (node.lineno,
                     f"`{raw.rsplit('.', 1)[-1]}()` host sync"))
                self.info.transfers.append(
                    (node.lineno, "item", raw.rsplit(".", 1)[0]))
            elif raw in _HOST_TRANSFER:
                self.info.host_syncs.append(
                    (node.lineno, f"`{raw}(...)` host transfer"))
                if node.args:
                    self.info.transfers.append(
                        (node.lineno, "asarray",
                         self._operand_text(node.args[0])))
            elif raw in ("float", "int", "bool") and node.args \
                    and not isinstance(node.args[0], ast.Constant) \
                    and not _mentions_static_shape(node.args[0]):
                self.info.host_syncs.append(
                    (node.lineno, f"`{raw}(...)` concretizing cast"))
                self.info.transfers.append(
                    (node.lineno, "cast",
                     self._operand_text(node.args[0])))
            if raw.endswith('.get') and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value == "code":
                self.info.checks_code = True
            caught: set[str] = set()
            broad = False
            for types, b in self.caught:
                caught |= types
                broad = broad or b
            self.info.calls.append(CallSite(
                raw=raw, line=node.lineno,
                held=frozenset(self.held),
                discards=id(node) in self._expr_calls,
                timeout_arg=_timeout_in_call(node),
                caught=frozenset(caught), caught_broad=broad,
                retry_loop=self._bare_loops[-1] if self._bare_loops else 0))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if isinstance(node.slice, ast.Constant) and node.slice.value == "code":
            self.info.checks_code = True
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        pass  # nested defs are scanned as their own functions

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        # Lambda bodies are otherwise opaque to summaries, but an upload
        # hidden in `jax.tree.map(lambda a: jax.device_put(a, ...), t)`
        # is exactly what ijax/unmanaged-device-put exists to catch.
        for sub in ast.walk(node.body):
            if isinstance(sub, ast.Call):
                fact = _upload_fact(sub)
                if fact is not None:
                    self.info.uploads.append(fact)


# Call tails that cannot realistically raise — excluded from the
# "raise-capable point" test between an acquire and its release.
_NO_RAISE_TAILS = frozenset({
    "append", "add", "extend", "len", "isinstance", "monotonic", "time",
    "debug", "info", "warning", "error", "get", "items", "keys", "values",
    "frozenset", "set", "list", "dict", "tuple", "min", "max", "sorted",
    "range", "enumerate", "zip", "id", "repr", "str", "int", "bool",
})


def _handler_is_broad(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    for n in (t.elts if isinstance(t, ast.Tuple) else [t]):
        if dotted_name(n).rsplit(".", 1)[-1] in ("Exception",
                                                 "BaseException"):
            return True
    return False


class _ResourceScanner(ast.NodeVisitor):
    """Second pass per function: resource-protocol sites (ires/),
    ownership escapes, return shapes, and blocking facts (iholds/).

    Kept separate from _FunctionScanner because the lifecycle facts need
    context the main scanner has no use for: a branch-arm path (the
    double-release disjointness test) and the enclosing try's cleanup
    region (a release in a ``finally`` or a broad handler protects the
    matching acquire). Nested defs are skipped as usual.
    """

    def __init__(self, info: FunctionInfo, cls: ClassInfo | None,
                 class_names: set):
        self.info = info
        self.cls = cls
        self.class_names = class_names
        self.held: list[str] = []
        self.arm: list[str] = []
        # ("finally", True) / ("handler", broad) region stack
        self.cleanup: list[tuple[str, bool]] = []
        # Call-node ids whose acquire obj is the assignment target
        # (add_external / acquire(pin=True) return the resource key).
        self._assign_obj: dict[int, str] = {}

    _lock_token = _FunctionScanner._lock_token

    # -- context stacks ------------------------------------------------------
    def visit_With(self, node: ast.With):
        acquired = 0
        for item in node.items:
            tok = self._lock_token(item.context_expr)
            if tok is not None:
                self.held.append(tok)
                acquired += 1
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(acquired):
            self.held.pop()

    def visit_If(self, node: ast.If):
        self.visit(node.test)
        self.arm.append(f"if{node.lineno}t")
        for stmt in node.body:
            self.visit(stmt)
        self.arm[-1] = f"if{node.lineno}e"
        for stmt in node.orelse:
            self.visit(stmt)
        self.arm.pop()

    def _visit_loop(self, node):
        self.arm.append(f"loop{node.lineno}")
        self.generic_visit(node)
        self.arm.pop()

    visit_While = _visit_loop
    visit_For = _visit_loop

    def visit_Try(self, node: ast.Try):
        self.arm.append(f"try{node.lineno}")
        for stmt in node.body:
            self.visit(stmt)
        self.arm.pop()
        for i, h in enumerate(node.handlers):
            self.arm.append(f"exc{node.lineno}.{i}")
            self.cleanup.append(("handler", _handler_is_broad(h)))
            for stmt in h.body:
                self.visit(stmt)
            self.cleanup.pop()
            self.arm.pop()
        for stmt in node.orelse:
            self.visit(stmt)
        self.cleanup.append(("finally", True))
        for stmt in node.finalbody:
            self.visit(stmt)
        self.cleanup.pop()

    def visit_FunctionDef(self, node):
        pass  # nested defs are scanned as their own functions

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # -- escapes and returns -------------------------------------------------
    def _escape_names(self, expr: ast.AST, line: int) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name):
                self.info.escapes.append((line, sub.id))

    def visit_Assign(self, node: ast.Assign):
        if isinstance(node.value, ast.Call) and len(node.targets) == 1:
            raw = call_name(node.value)
            tail = raw.rsplit(".", 1)[-1] if raw else ""
            pin_kw = any(kw.arg == "pin"
                         and isinstance(kw.value, ast.Constant)
                         and kw.value.value is True
                         for kw in node.value.keywords)
            if tail == "add_external" or (tail == "acquire" and pin_kw):
                tgt = dotted_name(node.targets[0])
                if tgt:
                    self._assign_obj[id(node.value)] = tgt
        # Storing into an attribute/subscript hands the names in the
        # value to another object's lifetime — an ownership escape.
        for tgt in node.targets:
            elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) \
                else [tgt]
            if any(isinstance(e, (ast.Attribute, ast.Subscript))
                   for e in elts):
                self._escape_names(node.value, node.lineno)
                break
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return):
        names = frozenset(
            sub.id for sub in ast.walk(node.value)
            if isinstance(sub, ast.Name)) if node.value is not None \
            else frozenset()
        trivial = node.value is None \
            or isinstance(node.value, ast.Constant)
        self.info.returns.append((node.lineno, names, trivial))
        self.generic_visit(node)

    def visit_Yield(self, node: ast.Yield):
        if node.value is not None:
            self._escape_names(node.value, node.lineno)
        self.generic_visit(node)

    # -- resource + blocking facts -------------------------------------------
    def _blocking_fact(self, node: ast.Call, raw: str) -> None:
        tail = raw.rsplit(".", 1)[-1]
        kind = detail = None
        if is_blocking_raw(raw):
            kind = "rpc"
        elif raw == "os.fsync":
            kind = "fsync"
        elif raw in _BLOCKING_FETCH:
            kind = "device_fetch"
        elif tail == "sleep":
            kind = "sleep"
        elif tail == "wait" and "." in raw:
            recv = raw.rsplit(".", 1)[0]
            kind, detail = "wait", ""
            parts = recv.split(".")
            if parts[0] == "self" and len(parts) == 2 \
                    and self.cls is not None:
                attr = parts[1]
                if self.cls.lock_attrs.get(attr) == "Condition":
                    # Waiting on a condition releases its (aliased) lock
                    # — only OTHER held locks stay held across the wait.
                    kind = "cond_wait"
                    lock = self.cls.lock_aliases.get(attr, attr)
                    detail = f"{self.cls.qualname}.{lock}"
        if kind is not None:
            self.info.blocking.append(
                (node.lineno, kind, detail or "", frozenset(self.held)))

    def _resource_fact(self, node: ast.Call, raw: str) -> None:
        tail = raw.rsplit(".", 1)[-1]
        recv = raw.rsplit(".", 1)[0] if "." in raw else ""
        obj = None
        if tail in ("add_external", "acquire"):
            # Key-returning acquires pair on the assignment target; a
            # discarded add_external is immediately unreleasable.
            obj = self._assign_obj.get(id(node))
            if obj is None and tail == "add_external":
                obj = f"<discarded@{node.lineno}>"
            if obj is None:
                return
            kind, verb = "pin", "acquire"
        elif tail == "invalidate":
            # Release-by-key: hbm_cache().invalidate(key).
            obj = dotted_name(node.args[0]) if node.args else recv
            kind, verb = "pin", "release"
        elif tail in _RESOURCE_VERBS:
            kind, verb = _RESOURCE_VERBS[tail]
            if kind == "tracker" and "tracker" not in recv.lower():
                return
            if kind == "probe" and "breaker" not in recv.lower():
                return
            obj = recv
        else:
            return
        if not obj:
            return
        region = self.cleanup[-1] if self.cleanup else ("", False)
        self.info.resources.append(ResourceSite(
            line=node.lineno, kind=kind, verb=verb, obj=obj,
            arm=tuple(self.arm),
            cleanup=region[0], cleanup_broad=region[1]))

    def visit_Call(self, node: ast.Call):
        raw = call_name(node)
        if raw:
            self._blocking_fact(node, raw)
            self._resource_fact(node, raw)
            # Any name passed as an argument escapes this frame's
            # ownership (containers, constructors, helper calls alike).
            for sub in list(node.args) + [kw.value for kw in node.keywords]:
                self._escape_names(sub, node.lineno)
        self.generic_visit(node)


class _ModuleModel:
    """Per-module symbol tables used during call resolution."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.module = src.module
        self.imports: dict[str, str] = {}       # alias -> dotted target
        self.classes: dict[str, ClassInfo] = {}  # simple name -> ClassInfo
        self.functions: dict[str, str] = {}      # simple name -> qualname
        self.mutable_globals: set[str] = set()   # names in `global` stmts


class ProjectIndex:
    """The whole-program model. Build once; query from project rules."""

    def __init__(self, srcs: list[SourceFile]):
        self.modules: dict[str, _ModuleModel] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.lock_kinds: dict[str, str] = {}     # token -> "Lock"|"RLock"
        self._method_name_index: dict[str, list[str]] = {}
        self._local_types_memo: dict[str, dict[str, str]] = {}
        self._trans_locks: dict[str, frozenset] = {}
        self._trans_raises: dict[str, frozenset] = {}
        self._error_channel: dict[str, bool] = {}
        for src in srcs:
            if src.module:
                self._index_module(src)
        self._resolve_attr_types()
        for src in srcs:
            if src.module:
                self._resolve_calls(src)
        self._mark_traced(srcs)
        self._mark_jit_entries()

    # -- pass A: symbol tables + raw function facts --------------------------
    def _index_module(self, src: SourceFile) -> None:
        mod = _ModuleModel(src)
        self.modules[src.module] = mod
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.startswith(PACKAGE_ROOT):
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
            elif isinstance(node, ast.Global):
                # A `global X` declaration anywhere makes X a rebindable
                # module global — a jitted closure capturing it bakes in
                # whichever value was live at trace time (ijit/).
                mod.mutable_globals.update(node.names)

        def index_scope(body, prefix, cls: ClassInfo | None):
            for stmt in body:
                if isinstance(stmt, ast.ClassDef):
                    ci = ClassInfo(qualname=f"{src.module}.{stmt.name}",
                                   module=src.module, name=stmt.name,
                                   bases=[dotted_name(b) for b in stmt.bases])
                    mod.classes[stmt.name] = ci
                    self.classes[ci.qualname] = ci
                    for dec in stmt.decorator_list:
                        if not isinstance(dec, ast.Call):
                            continue
                        if dotted_name(dec.func).rsplit(".", 1)[-1] \
                                != "guarded_by":
                            continue
                        lits = [a.value for a in dec.args
                                if isinstance(a, ast.Constant)
                                and isinstance(a.value, str)]
                        if len(lits) >= 2:
                            for fld in lits[1:]:
                                ci.guarded_decl[fld] = lits[0]
                    self._collect_class_attrs(stmt, ci)
                    index_scope(stmt.body, f"{prefix}.{stmt.name}"
                                if prefix else stmt.name, ci)
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{src.module}.{prefix}.{stmt.name}" if prefix \
                        else f"{src.module}.{stmt.name}"
                    if qual in self.functions:
                        qual = f"{qual}@{stmt.lineno}"
                    info = FunctionInfo(
                        qualname=qual, module=src.module,
                        cls=cls.name if cls else None, name=stmt.name,
                        rel=src.rel, lineno=stmt.lineno, node=stmt,
                        requires_lock=stmt.name.endswith("_locked"),
                        has_timeout_param=any(
                            any(w in a.arg for w in _TIMEOUT_WORDS)
                            for a in stmt.args.posonlyargs + stmt.args.args
                            + stmt.args.kwonlyargs))
                    self.functions[qual] = info
                    if cls is not None and stmt.name not in cls.methods:
                        cls.methods[stmt.name] = qual
                        if stmt.name not in _COMMON_METHOD_NAMES:
                            self._method_name_index.setdefault(
                                stmt.name, []).append(qual)
                    elif cls is None and stmt.name not in mod.functions:
                        mod.functions[stmt.name] = qual
                    scanner = _FunctionScanner(info, cls, set(mod.classes))
                    for s in stmt.body:
                        scanner.visit(s)
                    rscan = _ResourceScanner(info, cls, set(mod.classes))
                    for s in stmt.body:
                        rscan.visit(s)
                    index_scope(stmt.body, f"{prefix}.{stmt.name}"
                                if prefix else stmt.name, cls)

        index_scope(src.tree.body, "", None)

    def _collect_class_attrs(self, cls_node: ast.ClassDef,
                             ci: ClassInfo) -> None:
        """Lock attrs, Condition aliases, and attr -> type-name hints from
        class-body and ``self.x = ...`` assignments."""
        # Class-scope locks (shared across instances).
        for stmt in cls_node.body:
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call):
                kind = call_name(stmt.value).rsplit(".", 1)[-1]
                if kind in _LOCK_FACTORIES:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            ci.lock_attrs[tgt.id] = kind
                            self.lock_kinds[
                                f"{ci.module}.{ci.name}.{tgt.id}"] = kind
        # Param annotations feed attr typing: `def __init__(self, c: YBClient)`
        # plus `self.client = c` types self.client.
        for meth in cls_node.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            ann: dict[str, str] = {}
            for a in meth.args.posonlyargs + meth.args.args \
                    + meth.args.kwonlyargs:
                if a.annotation is not None:
                    t = dotted_name(a.annotation)
                    if not t and isinstance(a.annotation, ast.Constant) \
                            and isinstance(a.annotation.value, str):
                        t = a.annotation.value.strip('"')
                    if t:
                        ann[a.arg] = t
            for node in ast.walk(meth):
                if isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Attribute) \
                        and isinstance(node.target.value, ast.Name) \
                        and node.target.value.id == "self":
                    t = dotted_name(node.annotation)
                    if t:
                        ci.attr_types.setdefault(node.target.attr, t)
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    if isinstance(node.value, ast.Call):
                        raw = call_name(node.value)
                        kind = raw.rsplit(".", 1)[-1]
                        if kind in _LOCK_FACTORIES:
                            ci.lock_attrs[tgt.attr] = kind
                            self.lock_kinds[
                                f"{ci.module}.{ci.name}.{tgt.attr}"] = kind
                            if kind == "Condition" and node.value.args:
                                inner = dotted_name(node.value.args[0])
                                if inner.startswith("self."):
                                    ci.lock_aliases[tgt.attr] = \
                                        inner.split(".", 1)[1]
                        else:
                            ci.attr_types.setdefault(tgt.attr, raw)
                    elif isinstance(node.value, ast.Name) \
                            and node.value.id in ann:
                        ci.attr_types.setdefault(tgt.attr, ann[node.value.id])
                    elif isinstance(node.value, ast.Attribute):
                        # self.client = manager.client: type via the source
                        # object's class if resolvable later (keep raw path).
                        ci.attr_types.setdefault(
                            tgt.attr, dotted_name(node.value))

    # -- pass B: type + call resolution --------------------------------------
    def _resolve_class_name(self, raw: str, mod: _ModuleModel) -> str | None:
        """Project ClassInfo qualname for a raw type name, or None."""
        if not raw:
            return None
        raw = raw.strip("\"'")
        # Optional[...] / "YBClient | None" style annotations: first token.
        raw = raw.split("|")[0].strip().split("[")[0].strip()
        head, _, tail = raw.partition(".")
        if head in mod.classes and not tail:
            return mod.classes[head].qualname
        target = mod.imports.get(head)
        if target is None:
            return None
        if not tail and target in self.classes:
            return target
        if tail and f"{target}.{tail}" in self.classes:
            return f"{target}.{tail}"
        candidate = f"{target}.{tail}" if tail else target
        # Imported module alias: mod.Class
        if candidate in self.classes:
            return candidate
        return None

    def _resolve_attr_types(self) -> None:
        for ci in self.classes.values():
            mod = self.modules[ci.module]
            resolved = {}
            for attr, raw in ci.attr_types.items():
                # `manager.client` chains: follow one hop through an
                # already-typed attribute of a project class.
                qn = self._resolve_class_name(raw, mod)
                if qn is None and "." in raw:
                    base, _, rest = raw.partition(".")
                    base_t = ci.attr_types.get(base) if base != "self" \
                        else None
                    if base_t:
                        base_qn = self._resolve_class_name(base_t, mod)
                        if base_qn and "." not in rest:
                            inner = self.classes[base_qn].attr_types.get(rest)
                            if inner:
                                qn = self._resolve_class_name(
                                    inner, self.modules[base_qn.rsplit(
                                        ".", 1)[0]])
                if qn:
                    resolved[attr] = qn
            ci.attr_types = {**ci.attr_types, **resolved}

    def _class_for(self, info: FunctionInfo) -> ClassInfo | None:
        if info.cls is None:
            return None
        return self.classes.get(f"{info.module}.{info.cls}")

    def _method_on(self, class_qn: str, name: str,
                   depth: int = 0) -> str | None:
        ci = self.classes.get(class_qn)
        if ci is None or depth > 3:
            return None
        if name in ci.methods:
            return ci.methods[name]
        mod = self.modules.get(ci.module)
        for base_raw in ci.bases:
            base_qn = self._resolve_class_name(base_raw, mod) if mod else None
            if base_qn:
                found = self._method_on(base_qn, name, depth + 1)
                if found:
                    return found
        return None

    def _resolve_calls(self, src: SourceFile) -> None:
        mod = self.modules[src.module]
        for info in self.functions.values():
            if info.module != src.module:
                continue
            local_types = self._local_var_types(info, mod)
            for cs in info.calls:
                cs.callees = tuple(self._resolve_one(
                    cs.raw, info, mod, local_types))

    def _local_var_types(self, info: FunctionInfo,
                         mod: _ModuleModel) -> dict[str, str]:
        """var -> class qualname from annotations and constructor calls.
        Memoized: resolve_ref re-enters per reference and the AST walk
        dominates analysis wall time otherwise."""
        cached = self._local_types_memo.get(info.qualname)
        if cached is not None:
            return cached
        out: dict[str, str] = {}
        self._local_types_memo[info.qualname] = out
        fn = info.node
        if fn is None:
            return out
        for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
            if a.annotation is not None:
                qn = self._resolve_class_name(dotted_name(a.annotation), mod)
                if qn:
                    out[a.arg] = qn
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                qn = self._resolve_class_name(call_name(node.value), mod)
                if qn:
                    out[node.targets[0].id] = qn
        return out

    def _resolve_one(self, raw: str, info: FunctionInfo, mod: _ModuleModel,
                     local_types: dict[str, str]):
        parts = raw.split(".")
        cls = self._class_for(info)
        # self.method() / self.attr.method()
        if parts[0] == "self" and cls is not None:
            if len(parts) == 2:
                found = self._method_on(cls.qualname, parts[1])
                return [found] if found else []
            if len(parts) == 3:
                attr_qn = cls.attr_types.get(parts[1])
                if attr_qn in self.classes:
                    found = self._method_on(attr_qn, parts[2])
                    if found:
                        return [found]
            return self._fallback(parts[-1])
        # bare name: nested defs of enclosing scope, module fn, imported fn
        if len(parts) == 1:
            name = parts[0]
            scope_prefix = info.qualname.rsplit(".", 1)[0]
            nested = f"{scope_prefix}.{info.name}.{name}"
            if nested in self.functions:
                return [nested]
            if cls is not None and name in cls.methods:
                return []  # bare ref to a method name is not a self-call
            if name in mod.functions:
                return [mod.functions[name]]
            if name in mod.classes:  # constructor
                found = self._method_on(mod.classes[name].qualname,
                                        "__init__")
                return [found] if found else []
            target = mod.imports.get(name)
            if target and target in self.functions:
                return [target]
            if target and target in self.classes:
                found = self._method_on(target, "__init__")
                return [found] if found else []
            return []
        # alias.fn() / alias.Class(), var.method()
        head, rest = parts[0], parts[1:]
        if head in local_types and len(rest) == 1:
            found = self._method_on(local_types[head], rest[0])
            if found:
                return [found]
            return self._fallback(rest[0])
        target = mod.imports.get(head)
        if target is not None and len(rest) == 1:
            cand = f"{target}.{rest[0]}"
            if cand in self.functions:
                return [cand]
            if cand in self.classes:
                found = self._method_on(cand, "__init__")
                return [found] if found else []
        if head in mod.classes and len(rest) == 1:
            found = self._method_on(mod.classes[head].qualname, rest[0])
            return [found] if found else []
        return self._fallback(parts[-1])

    def _fallback(self, name: str):
        """Unique-method-name resolution: safe only when one project class
        defines the method and the name is not generic."""
        cands = self._method_name_index.get(name, ())
        return [cands[0]] if len(cands) == 1 else []

    def _mark_traced(self, srcs: list[SourceFile]) -> None:
        from yugabyte_db_tpu.analysis import jax_hygiene
        by_key = {(f.rel, f.lineno): f for f in self.functions.values()}
        for src in srcs:
            if not src.module:
                continue
            for fn in jax_hygiene._iter_traced_functions(src):
                info = by_key.get((src.rel, fn.lineno))
                if info is not None:
                    info.traced = True

    # -- jit-entry facts (ijit/) ---------------------------------------------
    def _mark_jit_entries(self) -> None:
        """Attach ``jit_entry`` facts to every compiled entry point: a
        function directly decorated ``@jax.jit`` (or via ``partial``),
        or a factory whose body ``return``s ``jax.jit(...)``. Records
        the static parameters (every factory param IS a compile key;
        ``static_argnums``/``static_argnames`` for direct jits), the
        literal ``@compile_contract`` declaration when present, the
        traced inner function, and its closure captures."""
        from yugabyte_db_tpu.analysis import jax_hygiene

        for info in list(self.functions.values()):
            node = info.node
            if node is None:
                continue
            mod = self.modules.get(info.module)
            if mod is None:
                continue
            contract = _contract_decorator(node)
            if jax_hygiene._jit_decorated(node):
                static = _direct_static_params(node)
                info.jit_entry = {
                    "kind": "direct", "line": node.lineno,
                    "entry": contract[0] if contract else None,
                    "budget": contract[1] if contract else None,
                    "static_params": tuple(sorted(static)),
                    "inner": info.qualname,
                    "captures": self._captures(node, node, mod),
                }
                continue
            ret = _jit_factory_return(node)
            if ret is None:
                continue
            inner_name = _unwrap_traced(ret, node)
            inner_qual = None
            inner_node = None
            if inner_name:
                cand = f"{info.qualname}.{inner_name}"
                if cand in self.functions:
                    inner_qual = cand
                elif inner_name in mod.functions:
                    inner_qual = mod.functions[inner_name]
                if inner_qual:
                    inner_node = self.functions[inner_qual].node
            factory_params = tuple(
                a.arg for a in node.args.posonlyargs + node.args.args
                + node.args.kwonlyargs)
            info.jit_entry = {
                "kind": "factory", "line": node.lineno,
                "entry": contract[0] if contract else None,
                "budget": contract[1] if contract else None,
                "static_params": factory_params,
                "inner": inner_qual,
                "captures": self._captures(inner_node, node, mod)
                if inner_node is not None else [],
            }

    def _captures(self, traced_node, enclosing, mod) -> list:
        """(kind, name, line) facts for names the traced function reads
        from outside its own scope: ``self`` attribute state and module
        globals rebound via ``global`` elsewhere. Enclosing-factory
        params/locals and module constants are static per compile and
        not captures."""
        if traced_node is None:
            return []
        bound = {a.arg for a in traced_node.args.posonlyargs
                 + traced_node.args.args + traced_node.args.kwonlyargs}
        for extra in (traced_node.args.vararg, traced_node.args.kwarg):
            if extra is not None:
                bound.add(extra.arg)
        for sub in ast.walk(traced_node):
            if isinstance(sub, ast.Name) \
                    and isinstance(sub.ctx, (ast.Store, ast.Del)):
                bound.add(sub.id)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(sub.name)
                bound.update(a.arg for a in sub.args.posonlyargs
                             + sub.args.args + sub.args.kwonlyargs)
            elif isinstance(sub, ast.Lambda):
                bound.update(a.arg for a in sub.args.posonlyargs
                             + sub.args.args + sub.args.kwonlyargs)
        out = []
        seen: set[tuple] = set()
        for sub in ast.walk(traced_node):
            if isinstance(sub, ast.Attribute) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id == "self" \
                    and isinstance(sub.ctx, ast.Load):
                key = ("self", sub.attr)
                if key not in seen:
                    seen.add(key)
                    out.append(("self", sub.attr, sub.lineno))
            elif isinstance(sub, ast.Name) \
                    and isinstance(sub.ctx, ast.Load) \
                    and sub.id in mod.mutable_globals \
                    and sub.id not in bound:
                key = ("global", sub.id)
                if key not in seen:
                    seen.add(key)
                    out.append(("global", sub.id, sub.lineno))
        return out

    def jit_entries(self) -> list[FunctionInfo]:
        """Every function carrying a jit_entry fact."""
        return [f for f in self.functions.values()
                if f.jit_entry is not None]

    # -- transitive summaries ------------------------------------------------
    def trans_locks(self, qualname: str) -> frozenset:
        """Lock tokens acquired by the function or anything it calls."""
        memo = self._trans_locks
        if qualname in memo:
            return memo[qualname]
        memo[qualname] = frozenset()  # cycle guard
        info = self.functions.get(qualname)
        if info is None:
            return frozenset()
        out = set(info.locks)
        for cs in info.calls:
            for callee in cs.callees:
                out |= self.trans_locks(callee)
        for a, b, _line in info.order_pairs:
            out.add(a)
            out.add(b)
        result = frozenset(out)
        memo[qualname] = result
        return result

    def trans_raises(self, qualname: str) -> frozenset:
        """Exception type names that can escape the function: direct raises
        plus callee raises not caught at the call site."""
        memo = self._trans_raises
        if qualname in memo:
            return memo[qualname]
        memo[qualname] = frozenset()  # cycle guard
        info = self.functions.get(qualname)
        if info is None:
            return frozenset()
        out = {r for r in info.direct_raises if r != "<reraise>"}
        for cs in info.calls:
            if cs.caught_broad:
                continue
            for callee in cs.callees:
                out |= self.trans_raises(callee) - cs.caught
        result = frozenset(out)
        memo[qualname] = result
        return result

    def error_channel(self, qualname: str) -> bool:
        """True when the function's RETURN VALUE is the error channel: it
        hands back an RPC response or Status whose failure code the caller
        must inspect (the function neither checks the code itself nor
        converts failures to raises)."""
        memo = self._error_channel
        if qualname in memo:
            return memo[qualname]
        memo[qualname] = False  # cycle guard
        info = self.functions.get(qualname)
        if info is None:
            return False
        result = False
        if info.returns_status:
            result = True
        elif info.returns_rpc_resp and not info.checks_code:
            result = True
        elif not info.checks_code:
            # Propagate through thin wrappers: `return inner(...)` where
            # inner's return is an error channel.
            for raw in info.return_calls:
                mod = self.modules.get(info.module)
                if mod is None:
                    continue
                for callee in self._resolve_one(raw, info, mod, {}):
                    if self.error_channel(callee):
                        result = True
        memo[qualname] = result
        return result

    # -- misc queries --------------------------------------------------------
    def resolve_ref(self, raw: str, info: FunctionInfo) -> list[str]:
        """Project qualnames for a dotted callable REFERENCE written
        inside ``info`` (a Thread target, an executor-submit argument, a
        weakref death callback) — same tiers as call resolution."""
        mod = self.modules.get(info.module)
        if mod is None or not raw:
            return []
        return list(self._resolve_one(raw, info, mod,
                                      self._local_var_types(info, mod)))

    def handlers(self):
        """Service-handler entry points (`_h_*` / `handle*` methods)."""
        return [f for f in self.functions.values()
                if f.cls is not None and _is_handler_name(f.name)]

    def lock_kind(self, token: str) -> str:
        return self.lock_kinds.get(token, "Lock")


def build_index(srcs: list[SourceFile]) -> ProjectIndex:
    return ProjectIndex(srcs)
