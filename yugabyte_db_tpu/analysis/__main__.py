"""CLI: ``python -m yugabyte_db_tpu.analysis [options] [paths...]``.

Exit status: 0 when no non-baselined, non-suppressed violations; 2 when
violations remain; 1 on usage errors.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from yugabyte_db_tpu.analysis import core, reporting


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m yugabyte_db_tpu.analysis",
        description="yb-lint: layer-map, JAX-hygiene, lock- and "
                    "error-discipline static analysis")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the "
                         "yugabyte_db_tpu package)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only in files changed vs. git "
                         "HEAD (staged, unstaged, and untracked); the "
                         "whole tree is still analyzed so interprocedural "
                         "summaries stay whole-program")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered violations too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current tree "
                         "instead of reporting")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--witness-check", metavar="DUMP", default=None,
                    help="cross-check a runtime witness dump against the "
                         "tree's static facts — a lock-witness dump "
                         "(utils/locking.py, --lock_witness) against "
                         "@guarded_by, a compile-witness dump "
                         "(utils/jitting.py, --compile_witness) against "
                         "@compile_contract, or a resource-witness dump "
                         "(utils/resources.py, --pin_witness) against the "
                         "resource-protocol facts; exits 2 on any "
                         "contradiction")
    args = ap.parse_args(argv)

    rules = core.all_rules()
    if args.list_rules:
        for name in sorted(set(rules) | set(core.all_project_rules())):
            print(name)
        return 0

    paths = args.paths
    if not paths:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]

    if args.witness_check:
        return _witness_check(args.witness_check, paths)

    baseline = None
    if not args.no_baseline and not args.write_baseline:
        baseline = core.load_baseline(args.baseline)

    report_only = None
    if args.changed_only:
        report_only = _changed_files(core._find_repo_root(paths))
        if report_only is None:
            print("yb-lint: --changed-only requires a git checkout",
                  file=sys.stderr)
            return 1

    result = core.run_analysis(paths, baseline=baseline, rules=rules,
                               report_only=report_only)

    if args.write_baseline:
        path = core.write_baseline(result.violations, args.baseline)
        print(f"yb-lint: wrote {len(result.violations)} grandfathered "
              f"violation(s) to {path}")
        return 0

    render = {"json": reporting.render_json,
              "sarif": reporting.render_sarif,
              "text": reporting.render_text}[args.format]
    print(render(result))
    return 0 if result.ok else 2


def _witness_check(dump_path: str, paths: list[str]) -> int:
    """Compare a runtime witness dump against the tree's static facts —
    a lock-witness dump against @guarded_by (analysis/fields.py), a
    compile-witness dump against @compile_contract (analysis/ijit.py),
    or a resource-witness dump against the resource-protocol facts
    (analysis/ires.py + iholds.py), dispatched on the dump's ``kind``.
    Exit 0 when consistent, 2 on contradiction, 1 on an unreadable or
    unrecognized dump."""
    import json

    from yugabyte_db_tpu.analysis import fields, ijit, ires
    from yugabyte_db_tpu.analysis.callgraph import build_index
    from yugabyte_db_tpu.utils.jitting import load_compile_witness_dump
    from yugabyte_db_tpu.utils.locking import load_witness_dump
    from yugabyte_db_tpu.utils.resources import load_resource_witness_dump

    try:
        with open(dump_path, "r", encoding="utf-8") as f:
            kind = json.load(f).get("kind")
    except (OSError, ValueError) as e:
        print(f"yb-lint: {e}", file=sys.stderr)
        return 1
    try:
        if kind == "yb-compile-witness":
            dump = load_compile_witness_dump(dump_path)
        elif kind == "yb-resource-witness":
            dump = load_resource_witness_dump(dump_path)
        else:
            dump = load_witness_dump(dump_path)
    except (OSError, ValueError) as e:
        print(f"yb-lint: {e}", file=sys.stderr)
        return 1
    repo_root = core._find_repo_root(paths)
    srcs = []
    for path, rel in core.iter_python_files(paths, repo_root):
        try:
            with open(path, "r", encoding="utf-8") as f:
                srcs.append(core.SourceFile(path, rel, f.read()))
        except (OSError, SyntaxError, ValueError):
            continue
    index = build_index(srcs)
    if kind == "yb-compile-witness":
        problems = ijit.compile_contradictions(index, dump)
        n_facts = len(ijit.static_compile_facts(index))
        fact_desc = "static @compile_contract fact(s)"
        n_obs = len(dump.get("observations", ()))
    elif kind == "yb-resource-witness":
        problems = ires.resource_contradictions(index, dump)
        n_facts = len(ires.static_resource_facts(index))
        fact_desc = "static resource-protocol fact(s)"
        # A resource dump carries leak records and hold observations, not
        # a flat observation list like the other two kinds.
        n_obs = len(dump.get("leaks", ())) + len(dump.get("holds", ()))
    else:
        problems = fields.witness_contradictions(index, dump)
        n_facts = len(fields.static_guarded_facts(index))
        fact_desc = "static @guarded_by fact(s)"
        n_obs = len(dump.get("observations", ()))
    if problems:
        print(f"yb-lint witness-check: {len(problems)} contradiction(s) "
              f"across {n_obs} observation(s) / {n_facts} static fact(s):")
        for p in problems:
            print(f"  {p}")
        return 2
    print(f"yb-lint witness-check: OK — {n_obs} observation(s) consistent "
          f"with {n_facts} {fact_desc}")
    return 0


def _changed_files(repo_root: str) -> set[str] | None:
    """Repo-relative paths changed vs. HEAD (staged + unstaged +
    untracked), or None when git is unavailable."""
    try:
        out = subprocess.run(
            ["git", "-C", repo_root, "status", "--porcelain", "-z",
             "--untracked-files=all"],
            capture_output=True, text=True, timeout=30, check=True).stdout
    except (OSError, subprocess.SubprocessError):
        return None
    changed: set[str] = set()
    for entry in out.split("\0"):
        if len(entry) < 4:
            continue
        # "XY path" (a rename adds a second NUL-separated entry that is
        # just the old path — shorter than 4 chars won't catch those, so
        # only keep entries that carry a status prefix).
        if entry[2] != " ":
            continue
        changed.add(entry[3:])
    return changed


if __name__ == "__main__":
    sys.exit(main())
