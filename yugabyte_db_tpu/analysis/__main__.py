"""CLI: ``python -m yugabyte_db_tpu.analysis [options] [paths...]``.

Exit status: 0 when no non-baselined, non-suppressed violations; 2 when
violations remain; 1 on usage errors.
"""

from __future__ import annotations

import argparse
import os
import sys

from yugabyte_db_tpu.analysis import core, reporting


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m yugabyte_db_tpu.analysis",
        description="yb-lint: layer-map, JAX-hygiene, lock- and "
                    "error-discipline static analysis")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the "
                         "yugabyte_db_tpu package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered violations too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current tree "
                         "instead of reporting")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    rules = core.all_rules()
    if args.list_rules:
        for name in sorted(rules):
            print(name)
        return 0

    paths = args.paths
    if not paths:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]

    baseline = None
    if not args.no_baseline and not args.write_baseline:
        baseline = core.load_baseline(args.baseline)

    result = core.run_analysis(paths, baseline=baseline, rules=rules)

    if args.write_baseline:
        path = core.write_baseline(result.violations, args.baseline)
        print(f"yb-lint: wrote {len(result.violations)} grandfathered "
              f"violation(s) to {path}")
        return 0

    out = (reporting.render_json(result) if args.format == "json"
           else reporting.render_text(result))
    print(out)
    return 0 if result.ok else 2


if __name__ == "__main__":
    sys.exit(main())
