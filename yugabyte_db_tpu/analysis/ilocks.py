"""Rule family 5 — interprocedural lock discipline.

The per-class rule in ``locks.py`` sees one method at a time; real
deadlocks hide in call chains. From the project index we build a GLOBAL
lock-order graph: an edge A -> B whenever B is acquired while A is held,
either by direct nesting (``with self._a: with self._b:``) or through a
call — a function called with A held whose transitive summary acquires
B. Two rules consume it:

- ``ilocks/abba-cycle`` — a cycle in the global order graph where at
  least one edge is call-mediated (pure same-class cycles are already
  ``locks/inconsistent-order``). Thread 1 runs one chain, thread 2 the
  other, and both block forever.
- ``ilocks/recursive-lock`` — a call made while holding a
  non-reentrant ``Lock`` into code whose summary re-acquires the same
  lock: self-deadlock on the spot (the ``*_locked`` convention exists
  precisely so helpers called under the lock do not re-acquire it).
"""

from __future__ import annotations

from yugabyte_db_tpu.analysis.core import Violation, project_rule

RULE_ABBA = "ilocks/abba-cycle"
RULE_RECURSIVE = "ilocks/recursive-lock"


def _short(token: str) -> str:
    """Class.attr tail of a lock token, for messages."""
    return ".".join(token.rsplit(".", 2)[-2:])


def _order_edges(index):
    """(A, B) -> (rel, line, description, call_mediated) for the global
    lock-order graph; first site seen wins."""
    edges: dict[tuple[str, str], tuple] = {}
    for fn in index.functions.values():
        for a, b, line in fn.order_pairs:
            if a != b:
                edges.setdefault((a, b), (fn.rel, line,
                                          f"{fn.qualname} nests "
                                          f"{_short(b)} under {_short(a)}",
                                          False))
        for cs in fn.calls:
            if not cs.held or not cs.callees:
                continue
            for callee in cs.callees:
                for tok in index.trans_locks(callee):
                    for held in cs.held:
                        if held != tok:
                            edges.setdefault(
                                (held, tok),
                                (fn.rel, cs.line,
                                 f"{fn.qualname} holds {_short(held)} while "
                                 f"calling {cs.raw} which acquires "
                                 f"{_short(tok)}", True))
    return edges


@project_rule(RULE_ABBA)
def check_abba_cycles(index):
    edges = _order_edges(index)
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    # Two-lock cycles carry the report (longer cycles always contain one
    # in practice here; SCC machinery would over-engineer 4 rules).
    reported: set[frozenset] = set()
    for (a, b), (rel, line, desc, mediated) in sorted(edges.items()):
        if (b, a) not in edges:
            continue
        pair = frozenset((a, b))
        if pair in reported:
            continue
        reported.add(pair)
        back_rel, back_line, back_desc, back_mediated = edges[(b, a)]
        if not (mediated or back_mediated):
            continue  # same-function nesting both ways: locks/* owns it
        yield Violation(
            RULE_ABBA, rel, line,
            f"cross-function ABBA deadlock: {desc}; but "
            f"{back_desc} ({back_rel}:{back_line}) — two threads running "
            f"these chains concurrently deadlock",
            f"abba:{'-'.join(sorted(_short(t) for t in pair))}")


@project_rule(RULE_RECURSIVE)
def check_recursive_acquire(index):
    for fn in sorted(index.functions.values(), key=lambda f: f.qualname):
        for cs in fn.calls:
            if not cs.held or not cs.callees:
                continue
            for callee in cs.callees:
                again = cs.held & index.trans_locks(callee)
                for tok in sorted(again):
                    if index.lock_kind(tok) != "Lock":
                        continue  # RLock re-entry is legal
                    yield Violation(
                        RULE_RECURSIVE, fn.rel, cs.line,
                        f"{fn.qualname} calls {cs.raw} while holding "
                        f"{_short(tok)}, and that call path re-acquires the "
                        f"same non-reentrant Lock — self-deadlock (use the "
                        f"*_locked convention for helpers called under the "
                        f"lock)",
                        f"recursive:{fn.name}:{_short(tok)}")
