"""Rule family 3 — lock discipline.

For every class that creates a ``threading.Lock``/``RLock`` attribute,
infer the set of instance attributes ever written under a ``with
self.<lock>:`` block; any write to one of those attributes outside every
lock (``__init__`` excepted — the object is not shared yet) is a data
race the test suite only catches probabilistically. Also reports lock
pairs acquired in opposite nesting orders in different methods (ABBA
deadlock shape).

Two conventions are honored (both mirror the reference tree):
- ``Condition(self._lock)`` attributes are lock-aliases — ``with
  self._cv:`` holds the underlying lock;
- a method named ``*_locked`` asserts "caller holds the lock" (the
  REQUIRES() annotation of src/yb/util/thread_annotations.h), so its
  writes count as guarded.
"""

from __future__ import annotations

import ast

from yugabyte_db_tpu.analysis.core import SourceFile, Violation, call_name, rule

RULE_UNGUARDED = "locks/unguarded-write"
RULE_ORDER = "locks/inconsistent-order"

_EXEMPT_METHODS = {"__init__", "__new__", "__getstate__", "__setstate__"}


def _lock_attrs(cls: ast.ClassDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(node.value,
                                                              ast.Call):
            continue
        name = call_name(node.value)
        if name.rsplit(".", 1)[-1] not in ("Lock", "RLock", "Condition"):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                out.add(tgt.attr)
    return out


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _written_attr(target: ast.AST) -> str | None:
    """Attribute name for `self.X = ..` / `self.X[k] = ..` targets."""
    attr = _self_attr(target)
    if attr is not None:
        return attr
    if isinstance(target, ast.Subscript):
        return _self_attr(target.value)
    return None


class _MethodScan(ast.NodeVisitor):
    """Collect (attr, line, frozenset(held locks)) writes and the nested
    lock-acquisition order pairs for one method."""

    def __init__(self, lock_attrs: set[str]):
        self.lock_attrs = lock_attrs
        self.held: list[str] = []
        self.writes: list[tuple[str, int, frozenset]] = []
        self.order_pairs: list[tuple[str, str, int]] = []

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.lock_attrs:
                for outer in self.held:
                    self.order_pairs.append((outer, attr, node.lineno))
                self.held.append(attr)
                acquired.append(attr)
        for stmt in node.body:
            self.visit(stmt)
        for attr in reversed(acquired):
            self.held.pop()

    def _record(self, target: ast.AST, line: int) -> None:
        attr = _written_attr(target)
        if attr is not None and attr not in self.lock_attrs:
            self.writes.append((attr, line, frozenset(self.held)))

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            if isinstance(tgt, ast.Tuple):
                for el in tgt.elts:
                    self._record(el, node.lineno)
            else:
                self._record(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for tgt in node.targets:
            self._record(tgt, node.lineno)
        self.generic_visit(node)

    # Nested defs run on other stacks (thread targets, callbacks): their
    # writes are analyzed with an empty held-set only if they acquire no
    # lock themselves — keep it simple and scan them with the current
    # (almost always empty) stack, which matches the common closure case.


@rule(RULE_UNGUARDED)
def check_lock_discipline(src: SourceFile):
    if not src.module:
        return
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        scans: list[tuple[str, _MethodScan]] = []
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scan = _MethodScan(locks)
            if meth.name.endswith("_locked"):
                # REQUIRES(lock) convention: the caller holds the lock
                # for the whole body.
                scan.held.append("<caller-held>")
            for stmt in meth.body:
                scan.visit(stmt)
            scans.append((meth.name, scan))

        # Attributes considered lock-guarded: written at least once with a
        # lock held, outside __init__.
        guarded: dict[str, set[str]] = {}
        for name, scan in scans:
            if name in _EXEMPT_METHODS:
                continue
            for attr, _line, held in scan.writes:
                if held:
                    guarded.setdefault(attr, set()).update(held)

        for name, scan in scans:
            if name in _EXEMPT_METHODS:
                continue
            for attr, line, held in scan.writes:
                if attr in guarded and not held:
                    yield Violation(
                        RULE_UNGUARDED, src.rel, line,
                        f"{cls.name}.{name} writes self.{attr} without a "
                        f"lock, but it is elsewhere written under "
                        f"{sorted(guarded[attr])}",
                        f"{cls.name}.{attr}")

        # ABBA: both (A before B) and (B before A) nesting observed.
        orders: dict[tuple[str, str], int] = {}
        for _name, scan in scans:
            for a, b, line in scan.order_pairs:
                orders.setdefault((a, b), line)
        reported: set[frozenset] = set()
        for (a, b), line in orders.items():
            pair = frozenset((a, b))
            if (b, a) in orders and pair not in reported:
                reported.add(pair)
                yield Violation(
                    RULE_ORDER, src.rel, line,
                    f"{cls.name} acquires {a} and {b} in both orders "
                    f"(lines {line} and {orders[(b, a)]}) — ABBA deadlock",
                    f"{cls.name}.{min(a, b)}-{max(a, b)}")
