"""iholds/: locks held across blocking calls.

The traffic-sweep SLO item dies first at a lock held across a blocking
call: every other thread that needs the lock eats the block's full
latency, so one fsync under ``Log._lock`` turns a p50 write into a p99
stall.  The reference tree polices this by review convention ("no fsync
under the lock" — see consensus/raft.py's group-commit pipeline, which
moves durability outside ``_lock`` behind a dedicated ``_sync_lock``);
this pass mechanizes the convention.

Blocking facts (callgraph's ``_ResourceScanner``):

- ``rpc`` — the ``transport.send`` seam (every outbound call);
- ``fsync`` — ``os.fsync`` (the WAL/metadata durability point);
- ``device_fetch`` — ``jax.device_get`` / ``jax.block_until_ready``
  (the host blocks until the device round-trip completes);
- ``cond_wait`` — ``Condition.wait``; the condition's aliased lock is
  RELEASED for the duration, so waiting while holding only that lock is
  the legal pattern — waiting while holding any OTHER lock is not;
- ``wait`` — ``Event.wait``/joins (nothing is released);
- ``sleep`` — ``time.sleep``.

A lock is "held" at a fact through either the lexical ``with`` context
or the ``iraces/`` entry lock-set fixpoint (the intersection of every
observed caller's held-set — ``_flush_locked`` helpers inherit their
caller's lock).  One interprocedural hop is reported at the call site
too: calling a function whose transitive summary reaches a blocking
fact while holding a lock the callee's entry-set does NOT already
account for (otherwise the callee's own site reports it).

The runtime half: utils/resources.py records per-lock hold durations
into ``yb_lock_hold_seconds{cls}`` and flags locks observed held across
:func:`~yugabyte_db_tpu.utils.resources.note_blocking` seams;
``--witness-check`` fails when runtime observes a (class, blocking-kind)
pair the static pass does not know (see :func:`static_hold_facts`).
"""

from __future__ import annotations

from yugabyte_db_tpu.analysis import fields
from yugabyte_db_tpu.analysis.core import Violation, project_rule

_KIND_LABEL = {
    "rpc": "a blocking RPC (`transport.send`)",
    "fsync": "`os.fsync`",
    "device_fetch": "a device fetch barrier",
    "cond_wait": "`Condition.wait` on a DIFFERENT lock's condition",
    "wait": "a wait that releases nothing",
    "sleep": "`time.sleep`",
}

_TRANS_DEPTH = 40  # callgraph diameter bound for the blocking summary


def _must_entry(model, qual: str) -> frozenset:
    """Locks held on EVERY observed path into ``qual`` (the iraces/
    entry-set intersection)."""
    sets = model.entry.get(qual)
    if not sets:
        return frozenset()
    return frozenset.intersection(*sets)


def _trans_blocking(index, qual: str, _depth: int = 0) -> frozenset:
    """(kind, detail) blocking facts reachable from ``qual``, memoized
    on the index with a cycle guard."""
    memo = getattr(index, "_iholds_trans", None)
    if memo is None:
        memo = index._iholds_trans = {}
    if qual in memo:
        return memo[qual]
    if _depth > _TRANS_DEPTH:
        return frozenset()
    memo[qual] = frozenset()  # cycle guard: in-progress -> empty
    info = index.functions.get(qual)
    if info is None:
        return frozenset()
    facts = {(kind, detail) for _, kind, detail, _ in info.blocking}
    for cs in info.calls:
        for callee in cs.callees:
            facts |= _trans_blocking(index, callee, _depth + 1)
    memo[qual] = frozenset(facts)
    return memo[qual]


def _exempt(kind: str, detail: str, tok: str) -> bool:
    # Waiting on a condition releases its own lock for the duration.
    return kind == "cond_wait" and tok == detail


def _lock_short(tok: str) -> str:
    return tok.rsplit(".", 1)[-1]


def _hold_sites(index):
    """Every hold-across-blocking site: (info, line, kind, tok,
    via_call_raw) — ``via_call_raw`` is None for direct facts, else the
    raw text of the call whose transitive summary blocks."""
    model = fields._model(index)
    for info in sorted(index.functions.values(), key=lambda f: f.qualname):
        must = _must_entry(model, info.qualname)
        for line, kind, detail, held in info.blocking:
            for tok in sorted(held | must):
                if _exempt(kind, detail, tok):
                    continue
                yield info, line, kind, tok, None
        for cs in info.calls:
            if not cs.held:
                continue
            for callee in cs.callees:
                callee_must = _must_entry(model, callee)
                for kind, detail in sorted(_trans_blocking(index, callee)):
                    for tok in sorted(cs.held):
                        if _exempt(kind, detail, tok):
                            continue
                        if tok in callee_must:
                            continue  # the callee's own site reports it
                        yield info, cs.line, kind, tok, cs.raw


@project_rule("iholds/lock-across-blocking")
def check_lock_across_blocking(index):
    seen = set()
    for info, line, kind, tok, via in _hold_sites(index):
        key = (info.qualname, line, kind, tok)
        if key in seen:
            continue
        seen.add(key)
        how = f"`{via}(...)` reaches {_KIND_LABEL[kind]}" if via \
            else _KIND_LABEL[kind]
        yield Violation(
            "iholds/lock-across-blocking", info.rel, line,
            f"`{_lock_short(tok)}` is held across {how} — every "
            f"contender eats the block's full latency; move the blocking "
            f"call outside the critical section (the raft group-commit "
            f"shape: snapshot under the lock, block outside)",
            f"lab:{info.qualname}:{_lock_short(tok)}:{kind}")


# -- witness cross-check ------------------------------------------------------

def static_hold_facts(index) -> list:
    """Every (lock class simple name, blocking kind, qualname) hold
    site the static pass can see — INCLUDING sites carrying a justified
    inline suppression (suppression is applied downstream by the
    runner).  The runtime witness keys its hold observations by the
    lock owner's class name; a runtime pair absent from this set means
    the static pass missed a path."""
    facts = []
    for info, line, kind, tok, _ in _hold_sites(index):
        cls = tok.rsplit(".", 2)[-2] if tok.count(".") >= 2 else tok
        facts.append((cls, kind, info.qualname))
    return sorted(set(facts))
