"""ires/: resource-lifecycle leak detection over the protocol facts.

The reference tree makes resource lifetimes structurally leak-free with
C++ RAII (ScopedPendingOperation, ScopedTrackedConsumption); Python has
no such guarantee, and the PR-6 review cycle caught three real pin-leak
bugs by hand.  This family mechanizes that review: callgraph's
``_ResourceScanner`` records every acquire/release site of the project's
resource protocols —

- **pin**: ``TpuRun.pin/unpin/retire``, ``HbmCache.add_external/
  invalidate`` (key-returning acquire / release-by-key), and
  ``acquire(..., pin=True)``;
- **tracker**: ``MemTracker.consume/release`` on receivers naming a
  tracker;
- **probe**: the circuit breaker's half-open probe token
  (``allow`` admits it; ``record_success/record_failure/trip`` retire it)

— plus ownership-escape facts (the resource stored into ``self.*``/a
container, passed to a call, or returned = ownership transferred out of
the frame) and the try/finally/except coverage of each site.  The rules
then ask the RAII question per function and pairing token: does every
path from an acquire reach a release or an escape?

- ``ires/leak-on-raise`` — releases exist but none sits in a ``finally``
  or a broad handler, and a raise-capable point sits between the acquire
  and the release: any exception leaks the resource.
- ``ires/leak-on-early-return`` — a ``return`` between the acquire and
  the release skips the release (or no path releases at all).
- ``ires/double-release`` — two sequential releases of the same token
  with no re-acquire between them (prefix-comparable branch arms; a
  release in each arm of an ``if`` is fine).
- ``ires/unbalanced-tracker`` — the same path logic applied to
  ``MemTracker`` debits: a path that net-debits the tracker.

Instance-held resources (``self._key = cache.add_external(...)``) are
exempt: their lifetime spans methods and ``close``/``__del__`` own the
release.  Protocol-owning methods (a method literally named ``pin`` is
the acquire primitive) are exempt by name.  Probe tokens are special
both ways: the receiver is ``self.breaker`` yet the token is
per-dispatch, so it IS checked — and a non-trivial ``return`` counts as
its escape (the probe rides the returned batch's ``finish()``).

The runtime half lives in utils/resources.py: under ``--pin_witness``
every residency acquire/release is attributed to an owner site and
thread, and ``--witness-check`` fails when runtime contradicts the
static clean bill (see :func:`resource_contradictions`).
"""

from __future__ import annotations

import ast

from yugabyte_db_tpu.analysis import callgraph
from yugabyte_db_tpu.analysis.core import Violation, project_rule

_KIND_NOUN = {"pin": "pin", "tracker": "tracker debit",
              "probe": "breaker probe"}


def _fp_obj(obj: str) -> str:
    # "<discarded@123>" carries a line; fingerprints must not.
    return obj.partition("@")[0]


def _iter_groups(index):
    """(info, kind, obj, sites) per function and pairing token, with
    protocol-owning methods exempted by name."""
    for info in index.functions.values():
        if info.name in callgraph._RESOURCE_LIFECYCLE_NAMES:
            continue
        groups: dict[tuple, list] = {}
        for s in info.resources:
            groups.setdefault((s.kind, s.obj), []).append(s)
        for (kind, obj), sites in sorted(groups.items()):
            yield info, kind, obj, sites


def _params(info) -> frozenset:
    node = info.node
    if node is None or not hasattr(node, "args"):
        return frozenset()
    a = node.args
    return frozenset(p.arg for p in a.posonlyargs + a.args + a.kwonlyargs)


def _raise_point(info, lo: int, hi: int, own_lines: set):
    """(line, label) of the first raise-capable point strictly between
    ``lo`` and ``hi``, else None."""
    for cs in info.calls:
        if not (lo < cs.line < hi) or cs.line in own_lines:
            continue
        tail = cs.raw.rsplit(".", 1)[-1]
        if tail in callgraph._NO_RAISE_TAILS \
                or tail in callgraph._RESOURCE_VERBS:
            continue
        return cs.line, f"`{cs.raw}(...)`"
    if info.node is not None:
        for sub in callgraph._walk_skip_defs(info.node.body):
            if isinstance(sub, ast.Raise) and lo < sub.lineno < hi:
                return sub.lineno, "`raise`"
    return None


def _disjoint(p1: tuple, p2: tuple) -> bool:
    """Branch-arm paths that are not prefix-comparable sit in disjoint
    arms — both cannot execute in one pass through the function."""
    n = min(len(p1), len(p2))
    return p1[:n] != p2[:n]


def _findings(index) -> list:
    """All (variant, kind, info, line, obj, message) findings, memoized
    on the index — four rules share one walk."""
    cached = getattr(index, "_ires_findings", None)
    if cached is not None:
        return cached
    out = []
    for info, kind, obj, sites in _iter_groups(index):
        noun = _KIND_NOUN[kind]
        if (obj == "self" or obj.startswith("self.")) and kind != "probe":
            # Instance-held: lifetime spans methods; close/__del__ own it.
            continue
        if kind == "tracker" and obj.split(".", 1)[0] in _params(info):
            # Debiting a tracker reachable from a parameter charges THAT
            # object's lifetime (`e.tracker.consume(...)` belongs to the
            # entry), not this frame's.
            continue
        acq = sorted((s for s in sites if s.verb == "acquire"),
                     key=lambda s: s.line)
        rel = sorted((s for s in sites if s.verb == "release"),
                     key=lambda s: s.line)
        for i in range(1, len(rel)):
            r1, r2 = rel[i - 1], rel[i]
            if r2.line == r1.line or _disjoint(r1.arm, r2.arm):
                continue
            if any(r1.line < a.line < r2.line for a in acq):
                continue
            if r1.cleanup == "handler" or r2.cleanup == "handler":
                continue  # the handler runs instead of, not after, the body
            out.append((
                "double", kind, info, r2.line, obj,
                f"`{obj}` {noun} released here and already released at "
                f"line {r1.line} with no re-acquire between — "
                f"double-release corrupts the refcount"))
        if not acq:
            continue
        first = acq[0]
        base = obj.split(".", 1)[0].split("(", 1)[0]
        escaped = any(nm == base and line >= first.line
                      for line, nm in info.escapes)
        escaped = escaped or any(base in names and line >= first.line
                                 for line, names, _ in info.returns)
        if escaped and kind != "probe":
            continue  # ownership transferred out of this frame
        protected_raise = any(
            r.cleanup == "finally"
            or (r.cleanup == "handler" and r.cleanup_broad) for r in rel)
        protected_return = any(r.cleanup == "finally" for r in rel)
        if not rel:
            if kind == "probe" and any(not trivial
                                       for _, _, trivial in info.returns):
                continue  # probe rides the returned value's finish()
            out.append((
                "early-return", kind, info, first.line, obj,
                f"`{obj}` {noun} acquired here is never released and "
                f"never escapes this frame — every path leaks it"))
            continue
        last_rel = rel[-1].line
        if not protected_raise:
            hazard = _raise_point(info, first.line, last_rel,
                                  {s.line for s in sites})
            if hazard is not None:
                narrow = "; the handler that releases it catches only "\
                    "specific types" if any(r.cleanup == "handler"
                                            for r in rel) else ""
                out.append((
                    "raise", kind, info, hazard[0], obj,
                    f"{hazard[1]} can raise while `{obj}` {noun} "
                    f"(acquired line {first.line}) is unreleased, and no "
                    f"finally/broad-handler releases it{narrow} — "
                    f"an exception leaks the {noun}"))
        if not protected_return and kind != "probe":
            # Probes are exempt from the early-return variant both ways:
            # a non-trivial return carries the probe out (the batch's
            # finish() retires it) and the `if not allow(): return` guard
            # is the NOT-admitted path — no probe exists there.
            for rline, names, trivial in info.returns:
                if not (first.line < rline < last_rel) or base in names:
                    continue
                out.append((
                    "early-return", kind, info, rline, obj,
                    f"returning here skips the release of `{obj}` {noun} "
                    f"acquired at line {first.line} (released at line "
                    f"{last_rel}, not in a finally)"))
                break
    index._ires_findings = out
    return out


def _emit(index, variant: str, rule: str, want_tracker: bool):
    for v, kind, info, line, obj, msg in _findings(index):
        if v != variant or (kind == "tracker") != want_tracker:
            continue
        abbr = rule.rsplit("/", 1)[-1][:3]
        yield Violation(rule, info.rel, line, msg,
                        f"{abbr}:{info.qualname}:{_fp_obj(obj)}")


@project_rule("ires/leak-on-raise")
def check_leak_on_raise(index):
    yield from _emit(index, "raise", "ires/leak-on-raise", False)


@project_rule("ires/leak-on-early-return")
def check_leak_on_early_return(index):
    yield from _emit(index, "early-return", "ires/leak-on-early-return",
                     False)


@project_rule("ires/double-release")
def check_double_release(index):
    yield from _emit(index, "double", "ires/double-release", False)


@project_rule("ires/unbalanced-tracker")
def check_unbalanced_tracker(index):
    """MemTracker debits get one rule for every variant: any path that
    net-debits the tracker (leaks the charge) or net-credits it
    (double release) skews the HBM/memstore budget silently."""
    for v, kind, info, line, obj, msg in _findings(index):
        if kind != "tracker":
            continue
        yield Violation("ires/unbalanced-tracker", info.rel, line, msg,
                        f"ubt:{info.qualname}:{_fp_obj(obj)}")


# -- witness cross-check ------------------------------------------------------

def static_resource_facts(index) -> list:
    """Every protocol site the static pass models, as (qualname, kind,
    verb, obj) — the denominator for the witness-check report."""
    facts = []
    for info in index.functions.values():
        for s in info.resources:
            facts.append((info.qualname, s.kind, s.verb, _fp_obj(s.obj)))
    return facts


def resource_contradictions(index, dump: dict) -> list[str]:
    """Human-readable contradictions between a resource-witness dump
    (utils/resources.py) and the static clean bill.  Two shapes:

    - a pin still outstanding at dump time: the tree is statically
      leak-free, so any runtime leak contradicts the pass — attributed
      to its acquire site and thread;
    - a lock observed held across a blocking call on a (class, kind)
      pair the static pass does NOT know as a hold site (known sites
      are either findings to fix or carry a justified suppression; an
      unknown one means the static pass missed a path).
    """
    from yugabyte_db_tpu.analysis import iholds

    out = []
    for leak in dump.get("leaks", ()):
        out.append(
            f"leaked pin `{leak.get('key')}`: acquired at "
            f"{leak.get('site', '?')} on thread "
            f"{leak.get('thread', '?')}, never released")
    sanctioned = iholds.static_hold_facts(index)
    sanctioned_pairs = {(cls, kind) for cls, kind, _ in sanctioned}
    for obs in dump.get("holds", ()):
        pair = (obs.get("cls"), obs.get("blocking"))
        if pair not in sanctioned_pairs:
            out.append(
                f"lock `{pair[0]}` held across `{pair[1]}` "
                f"{int(obs.get('count', 0))} time(s) (e.g. "
                f"{obs.get('site', '?')}) — no static hold site sanctions "
                f"this pair")
    return out
