"""Framework: file model, rule registry, suppression, baseline, runner.

A rule is a callable ``check(src: SourceFile) -> Iterable[Violation]``
registered under a ``family/rule-id`` name. The runner parses each file
once, hands the same ``SourceFile`` to every rule, then filters the
stream through inline suppressions and the committed baseline.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

_SUPPRESS_RE = re.compile(r"#\s*yb-lint:\s*disable=([\w/,\- ]+)")

PACKAGE_ROOT = "yugabyte_db_tpu"


@dataclass(frozen=True)
class Violation:
    rule: str          # e.g. "layering/upward-import"
    file: str          # repo-relative posix path
    line: int
    message: str
    # Line-number-free key used for baseline matching so grandfathered
    # entries survive unrelated edits to the same file.
    fingerprint: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"

    def baseline_key(self) -> str:
        return f"{self.file}::{self.rule}::{self.fingerprint}"


class SourceFile:
    """One parsed Python file plus the comment-level suppression map."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        # Dotted module name when the file belongs to the package
        # (yugabyte_db_tpu/storage/engine.py -> yugabyte_db_tpu.storage.engine),
        # else None (tests, bench, fixtures).
        self.module: str | None = None
        parts = rel[:-3].split("/") if rel.endswith(".py") else []
        if PACKAGE_ROOT in parts:
            parts = parts[parts.index(PACKAGE_ROOT):]
            if parts[-1] == "__init__":
                parts = parts[:-1]
            self.module = ".".join(parts)
        self._suppressions: dict[int, set[str]] | None = None

    # -- suppressions --------------------------------------------------------
    def _parse_suppressions(self) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(i, set()).update(rules)
            if line.lstrip().startswith("#"):
                # A standalone suppression comment covers the next line.
                out.setdefault(i + 1, set()).update(rules)
        return out

    def is_suppressed(self, rule: str, line: int) -> bool:
        if self._suppressions is None:
            self._suppressions = self._parse_suppressions()
        rules = self._suppressions.get(line)
        if not rules:
            return False
        family = rule.split("/", 1)[0]
        return rule in rules or family in rules or "all" in rules


# -- registry ---------------------------------------------------------------
_RULES: dict[str, object] = {}
_PROJECT_RULES: dict[str, object] = {}


def rule(name: str):
    """Register ``check(src) -> Iterable[Violation]`` under ``name``."""

    def deco(fn):
        _RULES[name] = fn
        fn.rule_name = name
        return fn

    return deco


def project_rule(name: str):
    """Register an interprocedural ``check(index) -> Iterable[Violation]``
    that runs once per analysis over the whole-program ProjectIndex."""

    def deco(fn):
        _PROJECT_RULES[name] = fn
        fn.rule_name = name
        return fn

    return deco


def all_rules() -> dict[str, object]:
    _load_rule_modules()
    return dict(_RULES)


def all_project_rules() -> dict[str, object]:
    _load_rule_modules()
    return dict(_PROJECT_RULES)


_LOADED = False


def _load_rule_modules() -> None:
    global _LOADED
    if _LOADED:
        return
    _LOADED = True
    from yugabyte_db_tpu.analysis import (  # noqa: F401
        error_discipline,
        fields,
        ierrors,
        iholds,
        ijax,
        ijit,
        ilocks,
        ires,
        irpc,
        jax_hygiene,
        layering,
        locks,
    )


# -- baseline ---------------------------------------------------------------
def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str | None = None) -> dict[str, int]:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {str(k): int(v) for k, v in data.get("violations", {}).items()}


def write_baseline(violations: list[Violation], path: str | None = None) -> str:
    path = path or default_baseline_path()
    counts: dict[str, int] = {}
    for v in violations:
        counts[v.baseline_key()] = counts.get(v.baseline_key(), 0) + 1
    payload = {
        "comment": "Grandfathered yb-lint violations. Burn down; never add. "
                   "Regenerate with python -m yugabyte_db_tpu.analysis "
                   "--write-baseline only after deliberate review.",
        "violations": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    return path


def apply_baseline(violations: list[Violation],
                   baseline: dict[str, int]) -> tuple[list[Violation], int]:
    """Split into (fresh, n_baselined). Within one baseline key the
    grandfather budget absorbs the first N occurrences in line order;
    anything beyond the budget is fresh (the file grew new ones)."""
    groups: dict[str, list[Violation]] = {}
    for v in violations:
        groups.setdefault(v.baseline_key(), []).append(v)
    fresh: list[Violation] = []
    n_baselined = 0
    for key, group in groups.items():
        budget = baseline.get(key, 0)
        group.sort(key=lambda v: v.line)
        n_baselined += min(budget, len(group))
        fresh.extend(group[budget:])
    fresh.sort(key=lambda v: (v.file, v.line, v.rule))
    return fresh, n_baselined


# -- runner -----------------------------------------------------------------
@dataclass
class AnalysisResult:
    violations: list[Violation] = field(default_factory=list)  # actionable
    baselined: int = 0
    suppressed: int = 0
    files_checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations


_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".venv"}


def iter_python_files(paths: list[str], repo_root: str) -> list[tuple[str, str]]:
    """Expand paths to (abs, repo-relative) .py files, sorted."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in sorted(dirnames) if d not in _SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    uniq = sorted(set(out))
    return [(p, os.path.relpath(p, repo_root).replace(os.sep, "/"))
            for p in uniq]


def run_analysis(paths: list[str], repo_root: str | None = None,
                 baseline: dict[str, int] | None = None,
                 rules: dict[str, object] | None = None,
                 project_rules: dict[str, object] | None = None,
                 report_only: set[str] | None = None) -> AnalysisResult:
    """Parse every file once, run per-file rules, then build the
    whole-program index and run the interprocedural rules. ``report_only``
    (repo-relative paths) filters REPORTED violations without narrowing
    the files analyzed — summaries always see the whole program."""
    repo_root = repo_root or _find_repo_root(paths)
    rules = rules if rules is not None else all_rules()
    project_rules = (project_rules if project_rules is not None
                     else all_project_rules())
    result = AnalysisResult()
    raw: list[Violation] = []
    srcs: list[SourceFile] = []
    for path, rel in iter_python_files(paths, repo_root):
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            src = SourceFile(path, rel, text)
        except (OSError, SyntaxError, ValueError) as e:
            raw.append(Violation("parse/error", rel,
                                 getattr(e, "lineno", 0) or 0,
                                 f"cannot analyze: {e}", "parse"))
            continue
        srcs.append(src)
        result.files_checked += 1
        for name, check in rules.items():
            for v in check(src):
                if src.is_suppressed(v.rule, v.line):
                    result.suppressed += 1
                else:
                    raw.append(v)
    if project_rules:
        from yugabyte_db_tpu.analysis.callgraph import build_index
        index = build_index(srcs)
        by_rel = {s.rel: s for s in srcs}
        for name, check in project_rules.items():
            for v in check(index):
                src = by_rel.get(v.file)
                if src is not None and src.is_suppressed(v.rule, v.line):
                    result.suppressed += 1
                else:
                    raw.append(v)
    if report_only is not None:
        raw = [v for v in raw if v.file in report_only]
    if baseline:
        result.violations, result.baselined = apply_baseline(raw, baseline)
    else:
        raw.sort(key=lambda v: (v.file, v.line, v.rule))
        result.violations = raw
    return result


def _find_repo_root(paths: list[str]) -> str:
    """Nearest ancestor of the first path that contains the package (so
    relative file names in reports match the repo layout)."""
    p = os.path.abspath(paths[0] if paths else os.getcwd())
    if os.path.isfile(p):
        p = os.path.dirname(p)
    while True:
        if os.path.isdir(os.path.join(p, PACKAGE_ROOT)):
            return p
        parent = os.path.dirname(p)
        if parent == p:
            return os.path.abspath(paths[0] if paths else os.getcwd())
        p = parent


# -- shared AST helpers ------------------------------------------------------
def call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('jax.jit', 'self._lock.acquire')."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        inner = dotted_name(node.func)
        parts.append(f"{inner}()" if inner else "()")
    else:
        return ""
    return ".".join(reversed(parts))
