"""The eight-layer map, as data.

This file is the single authority the layering rule reads; re-layering
the tree is a one-line diff here. Order follows the paper's dependency
spine: util -> rpc -> storage -> docdb -> tablet/consensus -> daemons ->
client -> YQL (reference: src/yb/{util,rpc,rocksdb,docdb,tablet,
consensus,master,tserver,client,yql}). A package may import its own
layer or any layer below it; everything else is a violation unless the
edge appears in ALLOWED_EXTRA.
"""

from __future__ import annotations

# (layer name, top-level packages / modules of yugabyte_db_tpu.* in it),
# bottom (most foundational) first.
LAYERS: list[tuple[str, list[str]]] = [
    # util: leaf primitives + device kernels. ops/ and utils/ import
    # nothing above this line — kernels must stay hoistable to any engine.
    ("util", ["utils", "models", "native", "ops", "fs", "auth"]),
    ("rpc", ["rpc"]),
    ("storage", ["storage"]),
    # docdb: document-level services composed over the storage engine.
    ("docdb", ["index", "parallel"]),
    ("tablet_consensus", ["tablet", "consensus", "txn"]),
    ("daemons", ["master", "tserver", "server"]),
    ("client", ["client", "drivers", "tools"]),
    ("yql", ["yql"]),
    # harness: test/tooling surfaces allowed to see everything.
    ("harness", ["integration", "analysis"]),
]

# Edges forbidden even though they point downward: the paper's one
# sanctioned seam between query execution and storage is the engine
# interface (storage.engine / YQLStorageIf analog) — YQL never reaches
# around it to the device kernels.
FORBIDDEN: dict[tuple[str, str], str] = {
    ("yql", "ops"): "yql reaches storage only via the engine seam "
                    "(storage.engine), never the device kernels",
    ("client", "ops"): "client code never touches device kernels",
    ("drivers", "ops"): "wire drivers never touch device kernels",
}

# Sanctioned upward edges (each one documented; add sparingly).
ALLOWED_EXTRA: dict[tuple[str, str], str] = {}

_RANK: dict[str, int] = {}
_LAYER_OF: dict[str, str] = {}
for _i, (_name, _pkgs) in enumerate(LAYERS):
    for _p in _pkgs:
        _RANK[_p] = _i
        _LAYER_OF[_p] = _name


def rank(pkg: str) -> int | None:
    return _RANK.get(pkg)


def layer_of(pkg: str) -> str | None:
    return _LAYER_OF.get(pkg)


def check_edge(src_pkg: str, dst_pkg: str) -> str | None:
    """None if the import is legal, else a human-readable reason."""
    if (src_pkg, dst_pkg) in FORBIDDEN:
        return FORBIDDEN[(src_pkg, dst_pkg)]
    if (src_pkg, dst_pkg) in ALLOWED_EXTRA:
        return None
    rs, rd = _RANK.get(src_pkg), _RANK.get(dst_pkg)
    if rs is None:
        return (f"package '{src_pkg}' is not in the layer map "
                f"(analysis/layers.py) — add it to a layer")
    if rd is None:
        return (f"imported package '{dst_pkg}' is not in the layer map "
                f"(analysis/layers.py) — add it to a layer")
    if rd > rs:
        return (f"layer '{_LAYER_OF[src_pkg]}' may not import layer "
                f"'{_LAYER_OF[dst_pkg]}' ({src_pkg} -> {dst_pkg} points "
                f"up the stack)")
    return None
