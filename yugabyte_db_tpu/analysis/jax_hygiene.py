"""Rule family 2 — JAX hygiene: host-sync and retrace hazards.

Traced context = a function that jax will trace: decorated with
``@jax.jit`` (directly or via ``partial``), passed by name into
``jax.jit(...)`` / ``jax.vmap(...)`` / ``pl.pallas_call(...)``, or
defined inside such a function. Host syncs inside a traced context
either fail at trace time (``.item()`` on a tracer) or, worse, silently
force a device round-trip per call; retrace hazards (unhashable /
mutable-default static args) recompile on every invocation.

Module-scope ``jnp`` calls are flagged everywhere in the package: they
allocate on the default backend at import time, which breaks
``JAX_PLATFORMS=cpu`` test runs and multi-process device pinning.
"""

from __future__ import annotations

import ast

from yugabyte_db_tpu.analysis.core import (
    SourceFile,
    Violation,
    call_name,
    dotted_name,
    rule,
)

RULE_ITEM = "jax/host-sync-item"
RULE_CAST = "jax/host-sync-cast"
RULE_TRANSFER = "jax/host-transfer"
RULE_BLOCK = "jax/block-until-ready"
RULE_MODULE_JNP = "jax/module-scope-jnp"
RULE_STATIC = "jax/unhashable-static-arg"

_TRACING_CALLS = ("jit", "vmap", "pmap", "pallas_call", "shard_map", "scan",
                  "while_loop", "fori_loop", "cond", "checkpoint", "remat",
                  "custom_vjp", "custom_jvp", "grad", "value_and_grad")
_HOST_ARRAY_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                     "numpy.array", "np.ascontiguousarray"}


def _is_tracing_call(node: ast.Call) -> bool:
    name = call_name(node)
    last = name.rsplit(".", 1)[-1]
    return last in _TRACING_CALLS


def _jit_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
        last = name.rsplit(".", 1)[-1]
        if last in ("jit", "pjit"):
            return True
        if last == "partial" and isinstance(dec, ast.Call):
            for arg in dec.args:
                inner = dotted_name(arg)
                if inner.rsplit(".", 1)[-1] in ("jit", "pjit"):
                    return True
    return False


def _collect_traced_names(tree: ast.AST) -> set[str]:
    """Function names passed (possibly through partial/vmap nesting) to a
    tracing entry point anywhere in the module."""
    traced: set[str] = set()

    def harvest(node: ast.AST) -> None:
        # Bare names and names nested in partial(...)/vmap(...) wrappers.
        if isinstance(node, ast.Name):
            traced.add(node.id)
        elif isinstance(node, ast.Call):
            for a in list(node.args) + [kw.value for kw in node.keywords
                                        if kw.arg in (None, "fun", "f",
                                                      "kernel", "target")]:
                harvest(a)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_tracing_call(node):
            for a in node.args:
                harvest(a)
            for kw in node.keywords:
                if kw.arg in ("fun", "f", "kernel", "body_fun", "cond_fun"):
                    harvest(kw.value)
    return traced


def _iter_traced_functions(src: SourceFile):
    """Yield every FunctionDef considered a traced context (including
    functions nested inside one)."""
    traced_names = _collect_traced_names(src.tree)

    def walk(node: ast.AST, inside_traced: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                is_traced = (inside_traced or _jit_decorated(child)
                             or child.name in traced_names)
                if is_traced:
                    yield child
                yield from walk(child, is_traced)
            else:
                yield from walk(child, inside_traced)

    yield from walk(src.tree, False)


def _mentions_static_shape(node: ast.AST) -> bool:
    """True if the expression reads static metadata (shape/dtype math is
    host math even inside a trace — not a sync)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "dtype", "itemsize"):
            return True
        if isinstance(sub, ast.Call) and call_name(sub) in ("len", "range"):
            return True
    return False


def _is_bench_file(rel: str) -> bool:
    return (rel.startswith("tests/") or "/tests/" in rel
            or rel.split("/")[-1].startswith(("bench", "test_"))
            or "/tools/" in rel)


@rule("jax/traced-context")
def check_traced_contexts(src: SourceFile):
    if not src.module:
        return
    seen: set[int] = set()
    for fn in _iter_traced_functions(src):
        for node in ast.walk(fn):
            if id(node) in seen or isinstance(node, (ast.FunctionDef,
                                                     ast.AsyncFunctionDef)):
                continue
            seen.add(id(node))
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name.endswith(".item") or name.endswith(".tolist"):
                yield Violation(
                    RULE_ITEM, src.rel, node.lineno,
                    f"host sync `{name.rsplit('.', 1)[-1]}()` inside traced "
                    f"function `{fn.name}` — fails on tracers / forces a "
                    f"device round-trip", f"item:{fn.name}")
            elif name in ("float", "int", "bool") and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) or _mentions_static_shape(arg):
                    continue
                yield Violation(
                    RULE_CAST, src.rel, node.lineno,
                    f"`{name}(...)` on a traced value inside `{fn.name}` "
                    f"concretizes the tracer (host sync); keep it as an "
                    f"array or hoist to the host side", f"cast:{fn.name}")
            elif name in _HOST_ARRAY_CALLS:
                yield Violation(
                    RULE_TRANSFER, src.rel, node.lineno,
                    f"`{name}(...)` inside traced function `{fn.name}` "
                    f"copies device values to host; use jnp inside traces",
                    f"transfer:{fn.name}")


@rule(RULE_BLOCK)
def check_block_until_ready(src: SourceFile):
    if not src.module or _is_bench_file(src.rel):
        return
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call) \
                and call_name(node).endswith("block_until_ready"):
            yield Violation(
                RULE_BLOCK, src.rel, node.lineno,
                "block_until_ready outside bench/test code serializes the "
                "dispatch pipeline; rely on the blocking fetch at the "
                "result boundary instead", "block")


@rule(RULE_MODULE_JNP)
def check_module_scope_jnp(src: SourceFile):
    if not src.module:
        return

    def scan(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.If, ast.Try, ast.With, ast.For,
                                 ast.While)):
                for field in ("body", "orelse", "finalbody"):
                    yield from scan(getattr(stmt, field, []) or [])
                continue
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    if name.startswith(("jnp.", "jax.numpy.")) \
                            or name.startswith("jax.device_put"):
                        yield node

    for node in scan(src.tree.body):
        yield Violation(
            RULE_MODULE_JNP, src.rel, node.lineno,
            f"`{call_name(node)}(...)` at module import scope allocates on "
            f"the default backend at import time; build constants lazily "
            f"inside the kernel factory", "module-jnp")


_MUTABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp)


def _static_param_names(fn: ast.FunctionDef, static_argnums, static_argnames):
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    names: set[str] = set()
    for n in static_argnums:
        if isinstance(n, int) and 0 <= n < len(params):
            names.add(params[n])
    names.update(static_argnames)
    return names


def _literal_elems(node: ast.AST):
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [getattr(e, "value", getattr(e, "id", None))
                for e in node.elts if isinstance(e, (ast.Constant, ast.Name))]
    if isinstance(node, ast.Constant):
        return [node.value]
    return []


@rule(RULE_STATIC)
def check_static_args(src: SourceFile):
    if not src.module:
        return
    # Local function defs by name, for jax.jit(fn, static_...) resolution.
    defs: dict[str, ast.FunctionDef] = {
        n.name: n for n in ast.walk(src.tree)
        if isinstance(n, ast.FunctionDef)}
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node).rsplit(".", 1)[-1] not in ("jit", "pjit"):
            continue
        argnums, argnames = [], []
        for kw in node.keywords:
            if kw.arg == "static_argnums":
                argnums = [v for v in _literal_elems(kw.value)
                           if isinstance(v, int)]
            elif kw.arg == "static_argnames":
                argnames = [v for v in _literal_elems(kw.value)
                            if isinstance(v, str)]
        if not argnums and not argnames:
            continue
        target = None
        if node.args and isinstance(node.args[0], ast.Name):
            target = defs.get(node.args[0].id)
        if target is None:
            continue
        static_names = _static_param_names(target, argnums, argnames)
        pos = target.args.posonlyargs + target.args.args
        defaults = target.args.defaults
        with_default = pos[len(pos) - len(defaults):]
        for param, default in zip(with_default, defaults):
            if param.arg in static_names \
                    and isinstance(default, _MUTABLE_DEFAULTS):
                yield Violation(
                    RULE_STATIC, src.rel, default.lineno,
                    f"static arg `{param.arg}` of `{target.name}` has a "
                    f"mutable (unhashable) default — jit raises on it and "
                    f"every fresh object retraces; use a tuple/frozen value",
                    f"static:{target.name}.{param.arg}")
        for param, default in zip(target.args.kwonlyargs,
                                  target.args.kw_defaults):
            if default is not None and param.arg in static_names \
                    and isinstance(default, _MUTABLE_DEFAULTS):
                yield Violation(
                    RULE_STATIC, src.rel, default.lineno,
                    f"static arg `{param.arg}` of `{target.name}` has a "
                    f"mutable (unhashable) default — jit raises on it and "
                    f"every fresh object retraces; use a tuple/frozen value",
                    f"static:{target.name}.{param.arg}")
