"""Rule family 7 — RPC deadline propagation.

Reference discipline: every YugaByte RPC carries a deadline derived from
the inbound call's; a handler that fans out with NO deadline inherits
whatever default the transport picked, which can exceed the caller's
budget and pin a service-pool worker long after the client gave up
(worker-pool starvation is how one slow tablet takes out a tserver).

``irpc/handler-no-deadline`` walks every service handler (``_h_*`` /
``handle*`` methods) through the call graph to each blocking
``transport.send``/``Proxy.call`` site it can reach, and fires when the
blocking call passes no timeout/deadline argument — neither an explicit
value nor a forwarded ``timeout_s``-style parameter.

``irpc/bare-retry-loop`` flags the other half of the discipline: a
``while`` loop that retries on exception (except-continue) with no
budget in sight — no deadline/attempt bound in the test or body, no
service-lifecycle flag — when something inside the loop reaches a
blocking RPC. Such a loop retries forever against a dead peer,
pinning its thread past any caller's budget; the fix is
``utils.retry.RetryPolicy.attempts()`` (or an explicit Deadline check).
"""

from __future__ import annotations

from yugabyte_db_tpu.analysis.core import Violation, project_rule
from yugabyte_db_tpu.analysis.callgraph import is_blocking_raw

RULE_NO_DEADLINE = "irpc/handler-no-deadline"
RULE_BARE_RETRY = "irpc/bare-retry-loop"

_MAX_DEPTH = 8


@project_rule(RULE_NO_DEADLINE)
def check_handler_deadlines(index):
    reported: set[tuple[str, int]] = set()
    for handler in sorted(index.handlers(), key=lambda f: f.qualname):
        # BFS from the handler; remember one arrival chain per function
        # for the message.
        queue: list[tuple[str, tuple[str, ...]]] = [
            (handler.qualname, (handler.qualname,))]
        seen = {handler.qualname}
        while queue:
            qualname, chain = queue.pop(0)
            fn = index.functions.get(qualname)
            if fn is None or len(chain) > _MAX_DEPTH:
                continue
            for cs in fn.calls:
                if is_blocking_raw(cs.raw) and not cs.timeout_arg:
                    key = (fn.rel, cs.line)
                    if key in reported:
                        continue
                    reported.add(key)
                    via = " -> ".join(c.rsplit(".", 2)[-1] for c in chain)
                    yield Violation(
                        RULE_NO_DEADLINE, fn.rel, cs.line,
                        f"blocking {cs.raw} reachable from service handler "
                        f"{handler.qualname} (via {via}) passes no "
                        f"timeout/deadline — the transport default can "
                        f"outlive the caller's budget and pin a service "
                        f"worker; propagate a deadline",
                        f"nodeadline:{fn.name}")
                for callee in cs.callees:
                    if callee not in seen:
                        seen.add(callee)
                        queue.append((callee, chain + (callee,)))


def _reaches_blocking(index, callees) -> str | None:
    """BFS through the call graph from ``callees``: the raw text of the
    first blocking RPC primitive reachable, or None."""
    queue = [(c, 1) for c in callees]
    seen = set(callees)
    while queue:
        qualname, depth = queue.pop(0)
        fn = index.functions.get(qualname)
        if fn is None or depth > _MAX_DEPTH:
            continue
        for cs in fn.calls:
            if is_blocking_raw(cs.raw):
                return cs.raw
            for callee in cs.callees:
                if callee not in seen:
                    seen.add(callee)
                    queue.append((callee, depth + 1))
    return None


@project_rule(RULE_BARE_RETRY)
def check_bare_retry_loops(index):
    reported: set[tuple[str, int]] = set()
    for fn in sorted(index.functions.values(), key=lambda f: f.qualname):
        for cs in fn.calls:
            if not cs.retry_loop:
                continue
            key = (fn.rel, cs.retry_loop)
            if key in reported:
                continue
            if is_blocking_raw(cs.raw):
                blocking = cs.raw
            else:
                blocking = _reaches_blocking(index, cs.callees)
            if blocking is None:
                continue
            reported.add(key)
            yield Violation(
                RULE_BARE_RETRY, fn.rel, cs.retry_loop,
                f"unbudgeted retry loop in {fn.qualname} reaches blocking "
                f"{blocking} — an except-continue while loop with no "
                f"deadline or attempt bound retries a dead peer forever; "
                f"drive it with utils.retry.RetryPolicy.attempts() or an "
                f"explicit Deadline",
                f"bareretry:{fn.name}")
