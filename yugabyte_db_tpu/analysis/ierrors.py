"""Rule family 6 — interprocedural error propagation.

The per-file rule catches ``except: pass``; this one catches the quieter
failure mode the RocksDB "always check your Status" discipline targets:
a caller that DISCARDS the return value of a function whose summary says
the return value IS the error channel.

A function has an error-channel return when it hands back an RPC
response dict (the ``{"code": ...}`` wire contract) or a ``Status``
without inspecting the code itself — its callers must look at the code
or the failure vanishes. ``tablet_rpc``-style helpers that check the
code and convert failures to raises are NOT error-channel: discarding
their return is safe, the exception path carries the error.

``ierrors/dropped-error-result`` fires on a bare expression-statement
call to such a function (direct ``*.transport.send(...)`` included), so
``self.transport.send(replica, "ts.delete_tablet", ...)`` with no look
at the response is a finding — the replica may have answered
``{"code": "not_found"}`` forever and nobody will ever know.
"""

from __future__ import annotations

from yugabyte_db_tpu.analysis.core import Violation, project_rule
from yugabyte_db_tpu.analysis.callgraph import is_blocking_raw

RULE_DROPPED = "ierrors/dropped-error-result"


@project_rule(RULE_DROPPED)
def check_dropped_error_results(index):
    for fn in sorted(index.functions.values(), key=lambda f: f.qualname):
        for cs in fn.calls:
            if not cs.discards:
                continue
            if is_blocking_raw(cs.raw):
                yield Violation(
                    RULE_DROPPED, fn.rel, cs.line,
                    f"{fn.qualname} discards the response of {cs.raw} — "
                    f"the peer's status code (not_leader/not_found/error) "
                    f"is the only failure signal and it is dropped; check "
                    f"resp.get('code') or log/count the failure",
                    f"dropped:{fn.name}:{cs.raw.rsplit('.', 1)[-1]}")
                continue
            for callee in cs.callees:
                if index.error_channel(callee):
                    yield Violation(
                        RULE_DROPPED, fn.rel, cs.line,
                        f"{fn.qualname} discards the result of {cs.raw}, "
                        f"but {callee} returns an error-channel value "
                        f"(RPC response / Status) that nothing now "
                        f"inspects — the failure is silently lost",
                        f"dropped:{fn.name}:{cs.raw.rsplit('.', 1)[-1]}")
                    break
