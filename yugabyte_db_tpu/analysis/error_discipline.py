"""Rule family 4 — error discipline.

Three shapes of silently-lost failure:
- ``except [Exception]: pass`` — the error vanishes with no trace;
- RPC/service handlers (``_h_*`` methods and ``handle`` dispatchers)
  with a code path that falls off the end — the peer gets ``None``
  where the wire contract promises a response/Status dict;
- daemon-thread targets whose body has no top-level exception guard —
  the thread dies silently and the subsystem it drove just stops.
"""

from __future__ import annotations

import ast

from yugabyte_db_tpu.analysis.core import SourceFile, Violation, call_name, rule

RULE_SWALLOW = "errors/swallowed-exception"
RULE_HANDLER = "errors/handler-returns-none"
RULE_THREAD = "errors/unguarded-daemon-thread"

_BROAD = {None, "Exception", "BaseException"}


def _handler_types(handler: ast.ExceptHandler) -> set[str | None]:
    t = handler.type
    if t is None:
        return {None}
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    out: set[str | None] = set()
    for n in nodes:
        name = ""
        if isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        out.add(name)
    return out


def _is_broad(handler: ast.ExceptHandler) -> bool:
    return bool(_handler_types(handler) & _BROAD)


def _enclosing_functions(tree: ast.AST):
    """Yield (func_node, qualname-ish) for every function."""
    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{prefix}.{child.name}" if prefix else child.name
                yield child, name
                yield from walk(child, name)
            elif isinstance(child, ast.ClassDef):
                name = f"{prefix}.{child.name}" if prefix else child.name
                yield from walk(child, name)
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


@rule(RULE_SWALLOW)
def check_swallowed(src: SourceFile):
    if not src.module:
        return
    funcs = list(_enclosing_functions(src.tree))

    def owner(line: int) -> str:
        best = "<module>"
        for fn, name in funcs:
            if fn.lineno <= line <= max(fn.lineno,
                                        getattr(fn, "end_lineno", fn.lineno)):
                best = name
        return best

    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        body_real = [s for s in node.body
                     if not (isinstance(s, ast.Expr)
                             and isinstance(s.value, ast.Constant))]
        only_pass = all(isinstance(s, (ast.Pass, ast.Continue))
                        for s in body_real)
        if only_pass and _is_broad(node):
            yield Violation(
                RULE_SWALLOW, src.rel, node.lineno,
                "blanket `except Exception: pass` swallows the error with "
                "no trace — log it, narrow the type, or count it in "
                "metrics", f"swallow:{owner(node.lineno)}")


# -- handler return analysis -------------------------------------------------
def _always_exits(stmts: list[ast.stmt]) -> bool:
    """Conservative: True if this statement list can never fall through
    to the next statement without returning a value or raising."""
    for i, stmt in enumerate(stmts):
        if isinstance(stmt, ast.Return):
            return True  # bare `return` is reported separately
        if isinstance(stmt, ast.Raise):
            return True
        if isinstance(stmt, ast.If):
            if stmt.orelse and _always_exits(stmt.body) \
                    and _always_exits(stmt.orelse):
                return True
        elif isinstance(stmt, ast.Try):
            handlers_exit = all(_always_exits(h.body) for h in stmt.handlers)
            body_exit = _always_exits(stmt.body + (stmt.orelse or []))
            if stmt.finalbody and _always_exits(stmt.finalbody):
                return True
            if body_exit and (handlers_exit or not stmt.handlers):
                return True
        elif isinstance(stmt, ast.With):
            if _always_exits(stmt.body):
                return True
        elif isinstance(stmt, ast.While):
            # `while True:` with no break never falls through.
            if isinstance(stmt.test, ast.Constant) and stmt.test.value:
                if not any(isinstance(n, ast.Break) for n in ast.walk(stmt)):
                    return True
        elif isinstance(stmt, ast.Match):
            cases = stmt.cases
            exhaustive = any(
                isinstance(c.pattern, ast.MatchAs) and c.pattern.pattern
                is None for c in cases)
            if exhaustive and all(_always_exits(c.body) for c in cases):
                return True
    return False


def _bare_returns(fn: ast.AST):
    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue  # nested defs return on their own behalf
            if isinstance(child, ast.Return) and child.value is None:
                yield child
            yield from walk(child)

    yield from walk(fn)


@rule(RULE_HANDLER)
def check_handler_returns(src: SourceFile):
    if not src.module:
        return
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for meth in cls.body:
            if not isinstance(meth, ast.FunctionDef):
                continue
            if not meth.name.startswith("_h_"):
                continue
            fingerprint = f"{cls.name}.{meth.name}"
            for node in _bare_returns(meth):
                yield Violation(
                    RULE_HANDLER, src.rel, node.lineno,
                    f"service handler {fingerprint} has a bare `return` — "
                    f"the RPC peer receives None instead of a response "
                    f"dict/Status", fingerprint)
            if not _always_exits(meth.body):
                yield Violation(
                    RULE_HANDLER, src.rel, meth.lineno,
                    f"service handler {fingerprint} can fall off the end — "
                    f"the RPC peer receives None instead of a response "
                    f"dict/Status", fingerprint)


# -- daemon thread guards ----------------------------------------------------
def _thread_guarded(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    """A broad try/except at the top level of the body, or at the top
    level of a top-level loop/with, counts as a guard."""

    def tops(stmts, depth):
        for stmt in stmts:
            yield stmt
            if depth > 0 and isinstance(stmt, (ast.While, ast.For, ast.With)):
                yield from tops(stmt.body, depth - 1)
            if depth > 0 and isinstance(stmt, ast.Try) and stmt.finalbody:
                yield from tops(stmt.body, depth - 1)

    for stmt in tops(fn.body, 2):
        if isinstance(stmt, ast.Try) and any(_is_broad(h)
                                             for h in stmt.handlers):
            return True
    return False


@rule(RULE_THREAD)
def check_daemon_threads(src: SourceFile):
    if not src.module:
        return
    # Local + method function defs, keyed by simple name.
    defs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node).rsplit(".", 1)[-1] != "Thread":
            continue
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None:
            continue
        name = None
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            name = target.attr
        fn = defs.get(name) if name else None
        if fn is None:
            continue  # unresolvable target: out of scope for this pass
        if not _thread_guarded(fn):
            yield Violation(
                RULE_THREAD, src.rel, node.lineno,
                f"thread target `{name}` has no top-level exception guard "
                f"— an unexpected error kills the thread silently and its "
                f"subsystem stalls", f"thread:{name}")
