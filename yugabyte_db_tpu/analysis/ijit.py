"""Rule family 10 — interprocedural compile discipline (``ijit/``).

The one failure mode no other family catches is the classic silent perf
killer of a JAX serving stack: unintended retracing and host<->device
round-trips on the hot path. A jitted entry point recompiles whenever a
static argument, a closure capture, or an array shape changes — each
recompile is tens-to-hundreds of milliseconds of XLA work charged to
whichever request was unlucky enough to trigger it.

The pass is anchored on the ``@compile_contract`` declarations of
``utils/jitting.py`` (the compile analog of ``@guarded_by``): the
callgraph records a ``jit_entry`` fact per compiled entry point —
decorator site, static parameters, contract budget, the traced inner
function and its closure captures — and four rules walk the serve paths
(``scan_batch_async`` / ``point_serve`` / flush / compaction dispatch)
to every jit boundary:

- ``ijit/unstable-static-arg`` — a per-request value (request fields,
  fresh mutable literals, clock/rng reads) flows into a static position
  of a jitted entry: one recompile per distinct value.
- ``ijit/mutable-closure-capture`` — the traced function reads ``self``
  state or a ``global``-rebindable module name: traces silently bake in
  whichever value was live at trace time.
- ``ijit/shape-from-data`` — a ``len(...)``/``.shape`` row count
  reaches a static position without passing a sanctioned bucketing
  helper (``*bucket*``, ``safe_window_blocks``, ``*pow2*``, ...):
  shape-polymorphic recompile storms.
- ``ijit/hot-path-transfer`` — an implicit ``np.asarray`` / ``.item()``
  / concretizing cast on a *device* value (the result of a compiled
  dispatch) reachable from a serve path. Each one is a blocking
  device fetch; the sanctioned shape is one explicit batched
  ``jax.device_get`` per dispatch (see tpu_engine's round-1 fetch).

The runtime compile witness (``--compile_witness``) cross-validates:
:func:`compile_contradictions` fails a witness dump when any entry
exceeded its declared budget or an entry this pass proved stable
recompiled in steady state.
"""

from __future__ import annotations

import ast
import re

from yugabyte_db_tpu.analysis.core import (
    Violation,
    call_name,
    dotted_name,
    project_rule,
)

RULE_UNSTABLE = "ijit/unstable-static-arg"
RULE_CLOSURE = "ijit/mutable-closure-capture"
RULE_SHAPE = "ijit/shape-from-data"
RULE_TRANSFER = "ijit/hot-path-transfer"

_MAX_DEPTH = 8

# Serve-path roots: every function with one of these names (the batch
# scan issue path and its finish()-side fetch half — batch objects are
# reached through constructors the callgraph cannot follow — the
# point-read path, the sharded serve APIs, flush, and compaction
# dispatch). Walks are cheap and firing requires a jit-entry or
# device-value fact, so over-approximating roots adds no noise.
_HOT_ROOT_NAMES = frozenset({
    "scan_batch_async", "finish", "point_serve",
    "sharded_row_page", "sharded_aggregate",
    "flush", "compact", "maybe_compact",
})

# A call through any of these (substring on the last path component)
# sanctifies a data-derived size: the result is drawn from a bounded
# bucket ladder, so the compile-key space stays bounded.
_BUCKET_TOKENS = ("bucket", "pow2", "pad_to", "round_up")
# Exact helper names sanctioned even when no token matches.
# ``pow2_bucket`` (ops/encodings.py) is the dictionary-width ladder the
# plane encoder draws capacities from — a dict capacity reaching a jit
# static position through it is bounded by construction, same standing
# as the window-count ladder.
_BUCKET_NAMES = frozenset({"safe_window_blocks", "pow2_bucket"})

# Parameters whose attributes are per-request state when read directly
# in a static position.
_REQUEST_PARAMS = frozenset({"spec", "req", "request", "query", "op",
                             "payload", "row", "rows", "batch"})

_CLOCK_RNG = frozenset({"time", "monotonic", "perf_counter",
                        "process_time", "random", "randrange", "randint",
                        "uniform", "choice", "getrandbits"})

_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


# -- serve-path reachability --------------------------------------------------

def _hot_reachable(index) -> dict:
    """qualname -> call chain (tuple of qualnames) for every function
    reachable from a serve-path root, roots included."""
    roots = [f for f in index.functions.values()
             if f.name in _HOT_ROOT_NAMES]
    out: dict = {}
    for root in sorted(roots, key=lambda f: f.qualname):
        queue = [(root.qualname, (root.qualname,))]
        while queue:
            qual, chain = queue.pop(0)
            if qual in out or len(chain) > _MAX_DEPTH:
                continue
            out[qual] = chain
            fn = index.functions.get(qual)
            if fn is None:
                continue
            for cs in fn.calls:
                for callee in cs.callees:
                    if callee not in out:
                        queue.append((callee, chain + (callee,)))
    return out


# -- static-argument classification -------------------------------------------

def _is_sanctioned(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            tail = call_name(sub).rsplit(".", 1)[-1]
            if tail in _BUCKET_NAMES \
                    or any(t in tail for t in _BUCKET_TOKENS):
                return True
    return False


def _assigned_expr(name: str, fn_node) -> ast.AST | None:
    """The value expression of a top-level ``name = ...`` binding in the
    function body (last one wins), skipping nested defs."""
    from yugabyte_db_tpu.analysis.callgraph import _walk_skip_defs

    found = None
    for sub in _walk_skip_defs(fn_node.body):
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    found = sub.value
    return found


def _classify_static(expr: ast.AST, fn_node,
                     depth: int = 0) -> tuple[str, str] | None:
    """("unstable"|"shape", reason) when ``expr`` is a per-request
    compile key, else None. Sanctioned bucketing anywhere in the
    expression (or its one-hop provenance) clears it."""
    if depth > 3 or expr is None:
        return None
    if _is_sanctioned(expr):
        return None
    if isinstance(expr, ast.Constant):
        return None
    if isinstance(expr, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        kind = type(expr).__name__.replace("Comp", " comprehension") \
            .lower()
        return ("unstable", f"fresh mutable {kind} literal — a new "
                            f"object per call is a new (or unhashable) "
                            f"jit cache key")
    if isinstance(expr, ast.Tuple):
        for elt in expr.elts:
            got = _classify_static(elt, fn_node, depth + 1)
            if got:
                return got
        return None
    if isinstance(expr, ast.Call):
        tail = call_name(expr).rsplit(".", 1)[-1]
        head = call_name(expr).split(".", 1)[0]
        if tail == "len":
            return ("shape", "a `len(...)` row count")
        if tail in _CLOCK_RNG or head in ("time", "random"):
            return ("unstable", f"a per-call `{call_name(expr)}()` value")
        for sub in list(expr.args) + [kw.value for kw in expr.keywords]:
            got = _classify_static(sub, fn_node, depth + 1)
            if got:
                return got
        return None
    if isinstance(expr, (ast.Attribute, ast.Subscript)):
        text = dotted_name(expr)
        if not text:
            try:
                text = ast.unparse(expr)
            except Exception:  # noqa: BLE001 — best-effort label
                text = ""
        if ".shape" in text or (isinstance(expr, ast.Subscript)
                                and ".shape" in dotted_name(expr.value)):
            # Mesh.shape is the device-axis map — cluster topology, a
            # per-process constant, not a data-derived array shape.
            if "mesh.shape" not in text:
                return ("shape", f"an array shape read (`{text}`)")
            return None
        headm = _IDENT_RE.match(text)
        if headm and headm.group(0) in _REQUEST_PARAMS \
                and _is_param(headm.group(0), fn_node):
            return ("unstable", f"the per-request field `{text}`")
        return None
    if isinstance(expr, ast.BinOp):
        for side in (expr.left, expr.right):
            got = _classify_static(side, fn_node, depth + 1)
            if got:
                return got
        return None
    if isinstance(expr, ast.Name):
        if _is_param(expr.id, fn_node):
            return None  # caller's own (already-static) parameter
        return _classify_static(_assigned_expr(expr.id, fn_node), fn_node,
                                depth + 1)
    return None


def _is_param(name: str, fn_node) -> bool:
    args = fn_node.args
    every = args.posonlyargs + args.args + args.kwonlyargs
    if any(a.arg == name for a in every):
        return True
    return (args.vararg is not None and args.vararg.arg == name) \
        or (args.kwarg is not None and args.kwarg.arg == name)


def _entry_label(callee_info) -> str:
    fact = callee_info.jit_entry
    return (fact.get("entry") or callee_info.name) if fact else \
        callee_info.name


def _static_args_at(call: ast.Call, callee_info) -> list:
    """(param name, expr) for every argument landing in a static
    position of the jit entry ``callee_info``."""
    fact = callee_info.jit_entry
    node = callee_info.node
    params = [a.arg for a in node.args.posonlyargs + node.args.args]
    out = []
    if fact["kind"] == "factory":
        # Every factory argument is a compile key.
        for i, a in enumerate(call.args):
            out.append((params[i] if i < len(params) else f"arg{i}", a))
        for kw in call.keywords:
            if kw.arg:
                out.append((kw.arg, kw.value))
        return out
    static = set(fact["static_params"])
    for i, a in enumerate(call.args):
        if i < len(params) and params[i] in static:
            out.append((params[i], a))
    for kw in call.keywords:
        if kw.arg and kw.arg in static:
            out.append((kw.arg, kw.value))
    return out


def _iter_static_arg_findings(index):
    """(entry label, rule, Violation) for every per-request value in a
    static position of a jit entry called on a serve path."""
    from yugabyte_db_tpu.analysis.callgraph import _walk_skip_defs

    hot = _hot_reachable(index)
    seen: set[tuple] = set()
    for qual in sorted(hot):
        fn = index.functions.get(qual)
        if fn is None or fn.node is None or fn.traced:
            continue
        for sub in _walk_skip_defs(fn.node.body):
            if not isinstance(sub, ast.Call):
                continue
            raw = call_name(sub)
            if not raw:
                continue
            for callee_qual in index.resolve_ref(raw, fn):
                callee = index.functions.get(callee_qual)
                if callee is None or callee.jit_entry is None:
                    continue
                entry = _entry_label(callee)
                for param, expr in _static_args_at(sub, callee):
                    got = _classify_static(expr, fn.node)
                    if not got:
                        continue
                    cls, why = got
                    rule = RULE_SHAPE if cls == "shape" else RULE_UNSTABLE
                    key = (fn.rel, getattr(expr, "lineno", sub.lineno),
                           rule, param)
                    if key in seen:
                        continue
                    seen.add(key)
                    line = getattr(expr, "lineno", sub.lineno)
                    if rule == RULE_SHAPE:
                        msg = (f"{why} reaches static parameter "
                               f"`{param}` of jit entry `{entry}` from "
                               f"serve path {hot[qual][0].rsplit('.', 1)[-1]}"
                               f" — every distinct row count compiles a "
                               f"new program; route the size through a "
                               f"bucketing helper in ops/ "
                               f"(safe_window_blocks, *_bucket) first")
                    else:
                        msg = (f"{why} flows into static parameter "
                               f"`{param}` of jit entry `{entry}` from "
                               f"serve path {hot[qual][0].rsplit('.', 1)[-1]}"
                               f" — jit recompiles per distinct value; "
                               f"hoist it to a traced argument or a "
                               f"bounded config key")
                    yield entry, rule, Violation(
                        rule, fn.rel, line, msg,
                        f"ijit:{entry}:{fn.name}:{param}")


def _iter_capture_findings(index):
    for info in sorted(index.jit_entries(), key=lambda f: f.qualname):
        fact = info.jit_entry
        entry = _entry_label(info)
        for kind, name, line in fact.get("captures", ()):
            if kind == "self":
                msg = (f"jit entry `{entry}` closes over instance state "
                       f"`self.{name}` — the first trace bakes the "
                       f"value in and later rebinds are silently "
                       f"ignored (or force a retrace per object); pass "
                       f"it as an explicit argument")
            else:
                msg = (f"jit entry `{entry}` closes over module global "
                       f"`{name}`, which is rebound via `global` "
                       f"elsewhere — traces bake in whichever value "
                       f"was live at trace time; pass it as an "
                       f"explicit argument")
            yield entry, RULE_CLOSURE, Violation(
                RULE_CLOSURE, info.rel, line, msg,
                f"ijit:{entry}:capture:{name}")


# -- the registered rules -----------------------------------------------------

@project_rule(RULE_UNSTABLE)
def check_unstable_static_arg(index):
    for _entry, rule, v in _iter_static_arg_findings(index):
        if rule == RULE_UNSTABLE:
            yield v


@project_rule(RULE_SHAPE)
def check_shape_from_data(index):
    for _entry, rule, v in _iter_static_arg_findings(index):
        if rule == RULE_SHAPE:
            yield v


@project_rule(RULE_CLOSURE)
def check_mutable_closure_capture(index):
    for _entry, _rule, v in _iter_capture_findings(index):
        yield v


@project_rule(RULE_TRANSFER)
def check_hot_path_transfer(index):
    """Implicit device->host fetches on serve paths.

    A name bound to the result of a compiled dispatch (directly, or
    through a factory-built callable) is a device value; `np.asarray` /
    `.item()` / concretizing casts on it are one blocking transfer
    each. The sanctioned shape is a single explicit `jax.device_get`
    per dispatch — it batches every output in one fetch and makes the
    sync visible. Suppress deliberate single-value fetches inline."""
    hot = _hot_reachable(index)
    for qual in sorted(hot):
        fn = index.functions.get(qual)
        if fn is None or fn.traced or not fn.transfers:
            continue
        device = _device_names(fn, index)
        for line, kind, operand in fn.transfers:
            headm = _IDENT_RE.match(operand)
            head = headm.group(0) if headm else ""
            if head not in device and ".dev." not in operand \
                    and not operand.endswith(".dev"):
                continue
            what = {"item": f"`.item()` on `{operand}`",
                    "asarray": f"implicit `np.asarray({operand})`",
                    "cast": f"concretizing cast of `{operand}`"}[kind]
            via = " -> ".join(c.rsplit(".", 1)[-1] for c in hot[qual])
            yield Violation(
                RULE_TRANSFER, fn.rel, line,
                f"{what} fetches a device value on the serve path "
                f"(via {via}) — each implicit transfer is a blocking "
                f"round-trip; fetch every output of the dispatch in "
                f"one explicit `jax.device_get`",
                f"ijit:transfer:{fn.name}:{head or kind}")


def _device_names(fn, index) -> set[str]:
    """Local names in ``fn`` bound to device values: results of direct
    jit-entry calls, or of callables returned by jit-entry factories.
    A name later re-fetched via ``jax.device_get`` is host again."""
    factories: set[str] = set()
    for target, raw, _line in fn.assign_calls:
        for q in index.resolve_ref(raw, fn):
            info = index.functions.get(q)
            if info is not None and info.jit_entry is not None \
                    and info.jit_entry["kind"] == "factory":
                factories.add(target)
    device: set[str] = set()
    fetched: set[str] = set()
    for target, raw, _line in fn.assign_calls:
        head = raw.split(".", 1)[0]
        if raw.rsplit(".", 1)[-1] == "device_get":
            fetched.add(target)
            continue
        if head in factories:
            device.add(target)
            continue
        for q in index.resolve_ref(raw, fn):
            info = index.functions.get(q)
            if info is not None and info.jit_entry is not None:
                device.add(target)
    return device - fetched


# -- witness cross-validation -------------------------------------------------

def static_compile_facts(index) -> dict:
    """entry -> {budget, rel, line, qualname, kind} for every literal
    @compile_contract declaration in the tree."""
    out: dict = {}
    for info in index.jit_entries():
        fact = info.jit_entry
        if fact.get("entry") is None:
            continue
        out[fact["entry"]] = {
            "budget": fact["budget"], "rel": info.rel,
            "line": fact["line"], "qualname": info.qualname,
            "kind": fact["kind"],
        }
    return out


def _unstable_entries(index) -> set[str]:
    """Entries the static pass could NOT prove stable: any ijit finding
    (suppressed or not) against them weakens the steady-state
    guarantee."""
    out = {e for e, _r, _v in _iter_static_arg_findings(index)}
    out |= {e for e, _r, _v in _iter_capture_findings(index)}
    return out


def compile_contradictions(index, dump: dict) -> list[str]:
    """Runtime compile-witness observations that contradict the static
    compile contracts: an uncontracted entry, a budget overrun, or a
    steady-state recompile of an entry the static pass proved stable."""
    facts = static_compile_facts(index)
    unstable = _unstable_entries(index)
    problems = []
    for obs in dump.get("observations", ()):
        entry = obs.get("entry")
        compiles = int(obs.get("compiles", 0))
        steady = int(obs.get("steady", 0))
        fact = facts.get(entry)
        if fact is None:
            problems.append(
                f"entry `{entry}`: observed {compiles} compile(s) at "
                f"runtime but the tree declares no @compile_contract "
                f"for it")
            continue
        if compiles > fact["budget"]:
            sites = ", ".join(obs.get("sites", ())[:3]) or "?"
            problems.append(
                f"entry `{entry}`: {compiles} compile(s) exceed the "
                f"declared budget max_compiles={fact['budget']} "
                f"({fact['rel']}:{fact['line']}; first sites: {sites})")
            continue
        if steady > 0 and entry not in unstable:
            problems.append(
                f"entry `{entry}`: statically proven stable, but "
                f"recompiled {steady} time(s) after steady-state mark "
                f"— a compile key varies at runtime that the static "
                f"pass cannot see")
    return problems
