"""Rule family 8 — interprocedural JAX hygiene.

``jax_hygiene`` flags host syncs INSIDE a traced function; it cannot see
a hazard hiding one call away — a jitted kernel calling a module-level
helper whose body does ``x.item()`` fails at trace time just the same,
but the helper's body is, textually, an innocent plain function.

``ijax/reachable-host-sync`` walks the call graph from every traced
entry point (``@jax.jit``-style decorations, functions passed into
``jit``/``vmap``/``pallas_call``/``lax`` control flow — the same
detection the intra rule uses) and reports host-sync sites
(``.item()``/``.tolist()``, concretizing ``float/int/bool`` casts,
``np.asarray``-family transfers) in any reachable helper that is not
itself a traced context (those are already the intra rule's findings).
"""

from __future__ import annotations

from yugabyte_db_tpu.analysis.core import Violation, project_rule

RULE_REACHABLE = "ijax/reachable-host-sync"

_MAX_DEPTH = 8


@project_rule(RULE_REACHABLE)
def check_reachable_host_sync(index):
    entries = [f for f in index.functions.values() if f.traced]
    reported: set[tuple[str, int]] = set()
    for entry in sorted(entries, key=lambda f: f.qualname):
        queue: list[tuple[str, tuple[str, ...]]] = [
            (entry.qualname, (entry.qualname,))]
        seen = {entry.qualname}
        while queue:
            qualname, chain = queue.pop(0)
            fn = index.functions.get(qualname)
            if fn is None or len(chain) > _MAX_DEPTH:
                continue
            if fn.qualname != entry.qualname and not fn.traced:
                for line, desc in fn.host_syncs:
                    key = (fn.rel, line)
                    if key in reported:
                        continue
                    reported.add(key)
                    via = " -> ".join(c.rsplit(".", 1)[-1] for c in chain)
                    yield Violation(
                        RULE_REACHABLE, fn.rel, line,
                        f"{desc} in {fn.qualname}, which is transitively "
                        f"reachable from traced entry point "
                        f"{entry.qualname} (via {via}) — fails on tracers "
                        f"or forces a device round-trip at trace time",
                        f"ijax:{fn.name}")
            if fn.traced and fn.qualname != entry.qualname:
                continue  # a traced callee starts its own walk
            for cs in fn.calls:
                for callee in cs.callees:
                    if callee not in seen:
                        seen.add(callee)
                        queue.append((callee, chain + (callee,)))
