"""Rule family 8 — interprocedural JAX hygiene.

``jax_hygiene`` flags host syncs INSIDE a traced function; it cannot see
a hazard hiding one call away — a jitted kernel calling a module-level
helper whose body does ``x.item()`` fails at trace time just the same,
but the helper's body is, textually, an innocent plain function.

``ijax/reachable-host-sync`` walks the call graph from every traced
entry point (``@jax.jit``-style decorations, functions passed into
``jit``/``vmap``/``pallas_call``/``lax`` control flow — the same
detection the intra rule uses) and reports host-sync sites
(``.item()``/``.tolist()``, concretizing ``float/int/bool`` casts,
``np.asarray``-family transfers) in any reachable helper that is not
itself a traced context (those are already the intra rule's findings).
"""

from __future__ import annotations

from yugabyte_db_tpu.analysis.core import Violation, project_rule

RULE_REACHABLE = "ijax/reachable-host-sync"
RULE_UNMANAGED = "ijax/unmanaged-device-put"

_MAX_DEPTH = 8

# The residency manager and the upload primitive it owns: the only
# modules allowed to move run planes to the device directly.
_UPLOAD_ALLOWLIST = ("storage/residency.py", "ops/device_run.py")

# Argument-text tokens marking an upload as run-plane data. A bare
# jnp.asarray of a scalar or an index vector is fine; re-uploading a
# plane group bypasses the --tpu_hbm_budget_bytes accounting.
_PLANE_TOKENS = ("valid", "group_start", "tomb", "live", "ht_hi", "ht_lo",
                 "exp_hi", "exp_lo", "cmp_planes", "key_planes", "arrays",
                 "set_", "isnull", "arith")


@project_rule(RULE_REACHABLE)
def check_reachable_host_sync(index):
    entries = [f for f in index.functions.values() if f.traced]
    reported: set[tuple[str, int]] = set()
    for entry in sorted(entries, key=lambda f: f.qualname):
        queue: list[tuple[str, tuple[str, ...]]] = [
            (entry.qualname, (entry.qualname,))]
        seen = {entry.qualname}
        while queue:
            qualname, chain = queue.pop(0)
            fn = index.functions.get(qualname)
            if fn is None or len(chain) > _MAX_DEPTH:
                continue
            if fn.qualname != entry.qualname and not fn.traced:
                for line, desc in fn.host_syncs:
                    key = (fn.rel, line)
                    if key in reported:
                        continue
                    reported.add(key)
                    via = " -> ".join(c.rsplit(".", 1)[-1] for c in chain)
                    yield Violation(
                        RULE_REACHABLE, fn.rel, line,
                        f"{desc} in {fn.qualname}, which is transitively "
                        f"reachable from traced entry point "
                        f"{entry.qualname} (via {via}) — fails on tracers "
                        f"or forces a device round-trip at trace time",
                        f"ijax:{fn.name}")
            if fn.traced and fn.qualname != entry.qualname:
                continue  # a traced callee starts its own walk
            for cs in fn.calls:
                for callee in cs.callees:
                    if callee not in seen:
                        seen.add(callee)
                        queue.append((callee, chain + (callee,)))


@project_rule(RULE_UNMANAGED)
def check_unmanaged_device_put(index):
    """Run-plane uploads must go through the residency manager.

    ``jax.device_put`` outside storage/residency.py and ops/device_run.py
    is always flagged (explicit placement is the residency manager's
    job); implicit ``jnp.asarray``/``jnp.array`` uploads are flagged only
    when the argument text names run-plane data (_PLANE_TOKENS), so
    scalar and index-vector staging stays legal. Suppress deliberate
    exceptions inline (``# yb-lint: disable=ijax/unmanaged-device-put``)
    — e.g. the sharded mesh placement, which is accounted separately via
    ``HbmCache.add_external``."""
    for fn in sorted(index.functions.values(), key=lambda f: f.qualname):
        if fn.rel.endswith(_UPLOAD_ALLOWLIST):
            continue
        for line, kind, arg in fn.uploads:
            if kind == "asarray" and not any(
                    tok in arg for tok in _PLANE_TOKENS):
                continue
            what = ("explicit jax.device_put" if kind == "device_put"
                    else f"implicit jnp.asarray upload of `{arg}`")
            yield Violation(
                RULE_UNMANAGED, fn.rel, line,
                f"{what} in {fn.qualname} bypasses the HBM residency "
                f"manager (storage/residency.py) — plane uploads must be "
                f"demand-paged through HbmCache.acquire so "
                f"--tpu_hbm_budget_bytes and /memz device accounting "
                f"stay truthful",
                f"upload:{fn.name}:{kind}")
