"""yb-lint: repo-native static analysis for the eight-layer map.

The paper's structural claim — util -> rpc -> storage -> docdb ->
tablet/consensus -> daemons -> client -> YQL, with one sanctioned seam
between query execution and storage — is enforced here mechanically,
along with the JAX-hygiene, lock-discipline, and error-discipline
invariants the test suite cannot see (they only bite under real
concurrency or on a real TPU).

Reference analog: the reference tree pins the same invariants with
clang-tidy plugins and iwyu mappings (src/yb/tools/); here the checks
are AST visitors over the Python tree so they run anywhere in <30s.

Usage:
    python -m yugabyte_db_tpu.analysis [--format=json] [paths...]

Suppression: append ``# yb-lint: disable=<rule-id>[,<rule-id>...]`` to
the offending line (or the line directly above it). Grandfathered
violations live in ``baseline.json`` next to this file; regenerate with
``--write-baseline`` after deliberate changes, and burn entries down
over time (ROADMAP "Open items").
"""

from yugabyte_db_tpu.analysis.core import (  # noqa: F401
    AnalysisResult,
    Violation,
    all_project_rules,
    all_rules,
    default_baseline_path,
    load_baseline,
    run_analysis,
)
