"""Rule family 1 — layering: enforce the eight-layer import order.

Walks every ``import yugabyte_db_tpu...`` / ``from yugabyte_db_tpu...``
(including relative imports resolved against the module) and checks the
(importer package -> imported package) edge against the table in
``layers.py``. Lazy in-function imports are treated exactly like
top-level ones: a cycle hidden behind laziness is still a layering bug.
"""

from __future__ import annotations

import ast

from yugabyte_db_tpu.analysis import layers
from yugabyte_db_tpu.analysis.core import PACKAGE_ROOT, SourceFile, Violation, rule

RULE_UPWARD = "layering/upward-import"
RULE_FORBIDDEN = "layering/forbidden-import"


def _self_package(src: SourceFile) -> str | None:
    if not src.module:
        return None
    parts = src.module.split(".")
    return parts[1] if len(parts) > 1 else None


def _resolve_relative(src: SourceFile, node: ast.ImportFrom) -> str | None:
    """Absolute dotted target of a relative import, or None."""
    if not src.module:
        return None
    base = src.module.split(".")
    # A module's level-1 base is its package; __init__ modules already
    # dropped their trailing component in SourceFile.module.
    if not src.rel.endswith("__init__.py"):
        base = base[:-1]
    if node.level > 1:
        if node.level - 1 >= len(base):
            return None
        base = base[:-(node.level - 1)]
    return ".".join(base + ([node.module] if node.module else []))


def _is_type_checking_block(node: ast.AST) -> bool:
    if not isinstance(node, ast.If):
        return False
    test = node.test
    return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or \
        (isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING")


def _walk_runtime(tree: ast.AST):
    """ast.walk, pruning `if TYPE_CHECKING:` bodies — those imports never
    execute, so they create no runtime layering edge."""
    stack = [tree]
    while stack:
        node = stack.pop()
        if _is_type_checking_block(node):
            stack.extend(node.orelse)
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _imported_packages(src: SourceFile):
    """Yield (top-level package imported, line)."""
    for node in _walk_runtime(src.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                parts = alias.name.split(".")
                if parts[0] == PACKAGE_ROOT and len(parts) > 1:
                    yield parts[1], node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                target = _resolve_relative(src, node)
                if target is None:
                    continue
                parts = target.split(".")
                if parts[0] != PACKAGE_ROOT:
                    continue
                if len(parts) > 1:
                    yield parts[1], node.lineno
                else:
                    # `from . import X` at the package root: each name is
                    # a top-level package.
                    for alias in node.names:
                        yield alias.name, node.lineno
            elif node.module:
                parts = node.module.split(".")
                if parts[0] != PACKAGE_ROOT:
                    continue
                if len(parts) > 1:
                    yield parts[1], node.lineno
                else:
                    # `from yugabyte_db_tpu import X`
                    for alias in node.names:
                        yield alias.name, node.lineno


@rule(RULE_UPWARD)
def check_layering(src: SourceFile):
    src_pkg = _self_package(src)
    if src_pkg is None:
        return
    for dst_pkg, line in _imported_packages(src):
        if dst_pkg == src_pkg:
            continue
        reason = layers.check_edge(src_pkg, dst_pkg)
        if reason is None:
            continue
        rule_id = (RULE_FORBIDDEN
                   if (src_pkg, dst_pkg) in layers.FORBIDDEN else RULE_UPWARD)
        yield Violation(rule_id, src.rel, line,
                        f"{src_pkg} -> {dst_pkg}: {reason}",
                        f"{src_pkg}->{dst_pkg}")
