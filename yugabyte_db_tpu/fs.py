"""FsManager: data-directory identity.

Reference analog: src/yb/fs/fs_manager.cc + fs.proto's
InstanceMetadataPB — every data directory carries an instance-metadata
record naming the server that owns it, written once at format time and
verified on every open. A data dir restored from the wrong machine, or
two daemons pointed at one directory, is detected instead of silently
serving another server's tablets.
"""

from __future__ import annotations

import os
import uuid as uuid_mod

from yugabyte_db_tpu.utils import codec
from yugabyte_db_tpu.utils.status import IllegalState

INSTANCE_FILE = "instance"
_MAGIC = "ybtpu-instance-v1"


class FsMismatch(IllegalState):
    """The data directory belongs to a different server instance."""


def format_or_open(data_dir: str, server_uuid: str) -> dict:
    """First open formats the directory (writes instance metadata);
    later opens verify the owning server uuid. Returns the metadata
    dict {server_uuid, instance_uuid, format_time_us}."""
    os.makedirs(data_dir, exist_ok=True)
    path = os.path.join(data_dir, INSTANCE_FILE)
    if os.path.exists(path):
        with open(path, "rb") as f:
            rec = codec.decode(f.read())
        if not isinstance(rec, list) or len(rec) < 4 or rec[0] != _MAGIC:
            raise IllegalState(f"{path}: not an instance metadata file")
        meta = {"server_uuid": rec[1], "instance_uuid": rec[2],
                "format_time_us": rec[3]}
        if meta["server_uuid"] != server_uuid:
            raise FsMismatch(
                f"data dir {data_dir} belongs to server "
                f"{meta['server_uuid']!r}, not {server_uuid!r} "
                "(swapped or restored data directory?)")
        return meta
    import time

    meta = {"server_uuid": server_uuid,
            "instance_uuid": uuid_mod.uuid4().hex,
            "format_time_us": int(time.time() * 1e6)}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(codec.encode([_MAGIC, meta["server_uuid"],
                              meta["instance_uuid"],
                              meta["format_time_us"]]))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return meta
