"""TPC-H lineitem: schema, data generation, and Q1/Q6 (BASELINE config 3).

Money columns are SCALED INTEGERS (cents; discount/tax as integer
percents), the classic exact-decimal representation — which also makes
every Q1/Q6 aggregate an exact integer computation the device evaluates
with digit-vector sums (ops.group_agg). Final results rescale to
decimals on output.

    Q1: select l_returnflag, l_linestatus, sum(qty), sum(price),
               sum(price*(100-disc)), sum(price*(100-disc)*(100+tax)),
               avg(qty), avg(price), avg(disc), count(*)
        from lineitem where l_shipdate <= DATE - DELTA
        group by l_returnflag, l_linestatus order by 1, 2
    Q6: select sum(price * disc) from lineitem
        where l_shipdate in [DATE, DATE+1y) and disc in DISC±1 and qty < QTY
"""

from __future__ import annotations

import random

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage.expr import BinOp, Col, Const
from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.storage.scan_spec import AggSpec, Predicate, ScanSpec

LINEITEM_COLUMNS = [
    ColumnSchema("l_orderkey", DataType.INT64, ColumnKind.HASH),
    ColumnSchema("l_linenumber", DataType.INT32, ColumnKind.RANGE),
    ColumnSchema("l_quantity", DataType.INT32),       # whole units
    ColumnSchema("l_extendedprice", DataType.INT64),  # cents
    ColumnSchema("l_discount", DataType.INT8),        # percent 0..10
    ColumnSchema("l_tax", DataType.INT8),             # percent 0..8
    ColumnSchema("l_returnflag", DataType.STRING),    # 'A'|'N'|'R'
    ColumnSchema("l_linestatus", DataType.STRING),    # 'F'|'O'
    ColumnSchema("l_shipdate", DataType.INT32),       # days since epoch
]

SHIPDATE_LO = 8766    # ~1994-01-01 in days
SHIPDATE_HI = 10957   # ~1998-12-31


def lineitem_schema(table_id: str = "lineitem") -> Schema:
    return Schema(list(LINEITEM_COLUMNS), table_id=table_id)


def generate_lineitem(num_rows: int, seed: int = 42):
    """Yield (key_values, value dict) rows in the published generator's
    value distributions (scaled-integer money)."""
    rng = random.Random(seed)
    for i in range(num_rows):
        orderkey = i // 4 + 1
        line = i % 4 + 1
        qty = rng.randrange(1, 51)
        price = qty * rng.randrange(900_00, 11_000_00) // 10
        shipdate = rng.randrange(SHIPDATE_LO, SHIPDATE_HI)
        # returnflag correlates with date like the spec's generator
        if shipdate < 9496:
            flag = rng.choice("AR")
            status = "F"
        else:
            flag = "N"
            status = "O" if shipdate > 9600 else "F"
        yield {
            "l_orderkey": orderkey, "l_linenumber": line,
            "l_quantity": qty, "l_extendedprice": price,
            "l_discount": rng.randrange(0, 11),
            "l_tax": rng.randrange(0, 9),
            "l_returnflag": flag, "l_linestatus": status,
            "l_shipdate": shipdate,
        }


def load_engine(engine, schema: Schema, num_rows: int, seed: int = 42,
                batch: int = 4096) -> int:
    """Load generated rows straight into a storage engine (bench path)."""
    cid = {c.name: c.col_id for c in schema.columns}
    key_names = {c.name for c in schema.key_columns}
    ht = 100
    buf = []
    for row in generate_lineitem(num_rows, seed):
        kv = {k: row[k] for k in key_names}
        key = schema.encode_primary_key(kv, compute_hash_code(schema, kv))
        ht += 1
        buf.append(RowVersion(key, ht=ht, liveness=True, columns={
            cid[name]: v for name, v in row.items()
            if name not in key_names}))
        if len(buf) >= batch:
            engine.apply(buf)
            buf = []
    if buf:
        engine.apply(buf)
    engine.flush()
    return ht


DISC_PRICE = BinOp("*", Col("l_extendedprice"),
                   BinOp("-", Const(100), Col("l_discount")))
CHARGE = BinOp("*", DISC_PRICE, BinOp("+", Const(100), Col("l_tax")))


def q1_spec(read_ht: int, ship_cutoff: int = 10471) -> ScanSpec:
    """Q1 as one pushed-down grouped scan. avg columns lower to
    sum+count; the runner derives the averages (the reference's FDW does
    the same above the scan)."""
    return ScanSpec(
        read_ht=read_ht,
        predicates=[Predicate("l_shipdate", "<=", ship_cutoff)],
        group_by=["l_returnflag", "l_linestatus"],
        aggregates=[
            AggSpec("sum", "l_quantity", label="sum_qty"),
            AggSpec("sum", "l_extendedprice", label="sum_base_price"),
            AggSpec("sum", None, expr=DISC_PRICE, label="sum_disc_price"),
            AggSpec("sum", None, expr=CHARGE, label="sum_charge"),
            AggSpec("count", None, label="count_order"),
        ])


def q1_result(scan_result) -> list[dict]:
    """Rescale the integer partials into the Q1 output row shape."""
    out = []
    for row in scan_result.rows:
        flag, status, sum_qty, sum_price, sum_disc, sum_charge, n = row
        out.append({
            "l_returnflag": flag, "l_linestatus": status,
            "sum_qty": sum_qty,
            "sum_base_price": (sum_price or 0) / 100,
            "sum_disc_price": (sum_disc or 0) / 100 / 100,
            "sum_charge": (sum_charge or 0) / 100 / 100 / 100,
            "avg_qty": sum_qty / n if n else None,
            "avg_price": (sum_price or 0) / 100 / n if n else None,
            "count_order": n,
        })
    return out


def q6_spec(read_ht: int, date_lo: int = 9131, discount: int = 6,
            quantity: int = 24) -> ScanSpec:
    """Q6: sum(l_extendedprice * l_discount) under date/disc/qty bands."""
    return ScanSpec(
        read_ht=read_ht,
        predicates=[
            Predicate("l_shipdate", ">=", date_lo),
            Predicate("l_shipdate", "<", date_lo + 365),
            Predicate("l_discount", ">=", discount - 1),
            Predicate("l_discount", "<=", discount + 1),
            Predicate("l_quantity", "<", quantity),
        ],
        aggregates=[AggSpec(
            "sum", None, label="revenue",
            expr=BinOp("*", Col("l_extendedprice"), Col("l_discount")))])


def q6_result(scan_result) -> float:
    v = scan_result.rows[0][0]
    return (v or 0) / 100 / 100   # cents x percent -> currency


Q1_SQL = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (100 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (100 - l_discount) * (100 + l_tax)) AS sum_charge,
       avg(l_quantity) AS avg_qty,
       avg(l_extendedprice) AS avg_price,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= {cutoff}
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q6_SQL = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= {lo} AND l_shipdate < {hi}
  AND l_discount >= {dlo} AND l_discount <= {dhi}
  AND l_quantity < {qty}
"""


def q1_sql(ship_cutoff: int = 10471) -> str:
    return Q1_SQL.format(cutoff=ship_cutoff)


def q6_sql(date_lo: int = 9131, discount: int = 6,
           quantity: int = 24) -> str:
    return Q6_SQL.format(lo=date_lo, hi=date_lo + 365,
                         dlo=discount - 1, dhi=discount + 1, qty=quantity)
