"""SQL (YSQL dialect) recursive-descent parser.

Reference analog: the PostgreSQL fork's gram.y as exercised by YSQL —
here only the surface the executor lowers: CREATE/DROP TABLE,
CREATE/DROP INDEX, INSERT (multi-row VALUES), UPDATE, DELETE, SELECT
with arithmetic expressions, aggregates, GROUP BY / ORDER BY / LIMIT,
AND-conjunct WHERE with =/!=/</<=/>/>=/IN/BETWEEN, and $N bind markers.
Scalar expressions parse into storage.expr trees so aggregate arguments
lower directly onto the device GROUP BY kernel (ops.group_agg).
"""

from __future__ import annotations

import re

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.storage.expr import BinOp, Col, Const
from yugabyte_db_tpu.storage.scan_spec import AGG_FNS as _AGG_FN_TUPLE
from yugabyte_db_tpu.utils.status import InvalidArgument
from yugabyte_db_tpu.yql.pgsql import ast

AGG_FNS = frozenset(_AGG_FN_TUPLE)

_TOKEN_RE = re.compile(r"""
    \s+
  | (?P<comment>--[^\n]*)
  | (?P<string>'(?:[^']|'')*')
  | (?P<number>\d+\.\d+(?:[eE][+-]?\d+)?|\.\d+|\d+[eE][+-]?\d+|\d+)
  | (?P<param>\$\d+)
  | (?P<name>[A-Za-z_][A-Za-z_0-9]*|"(?:[^"]|"")*")
  | (?P<op>->>|->|<=|>=|<>|!=|=|<|>)
  | (?P<sym>[(),.;*+/-])
""", re.VERBOSE)

# SQL type name (first word, with optional qualifiers) -> DataType
_TYPES = {
    "TINYINT": DataType.INT8,
    "SMALLINT": DataType.INT16, "INT2": DataType.INT16,
    "INT": DataType.INT32, "INTEGER": DataType.INT32,
    "INT4": DataType.INT32,
    "BIGINT": DataType.INT64, "INT8": DataType.INT64,
    "TEXT": DataType.STRING, "VARCHAR": DataType.STRING,
    "CHAR": DataType.STRING,
    "REAL": DataType.FLOAT, "FLOAT4": DataType.FLOAT,
    "FLOAT8": DataType.DOUBLE,
    "BOOLEAN": DataType.BOOL, "BOOL": DataType.BOOL,
    "BYTEA": DataType.BINARY,
    "JSONB": DataType.JSONB, "JSON": DataType.JSONB,
    "TIMESTAMP": DataType.TIMESTAMP,  # microseconds since epoch (int64)
    "NUMERIC": DataType.DECIMAL, "DECIMAL": DataType.DECIMAL,
    "UUID": DataType.UUID,
    "INET": DataType.INET,
    "DATE": DataType.DATE,
    "TIME": DataType.TIME,
}


class Token:
    __slots__ = ("kind", "text", "pos")

    def __init__(self, kind, text, pos=0):
        self.kind = kind
        self.text = text
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.text}"


def tokenize(sql: str) -> list[Token]:
    out, pos = [], 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise InvalidArgument(
                f"SQL syntax error near {sql[pos:pos + 20]!r}")
        pos = m.end()
        for kind in ("string", "number", "param", "name", "op", "sym"):
            text = m.group(kind)
            if text is not None:
                out.append(Token(kind, text, m.start(kind)))
                break
    return out


class Parser:
    def __init__(self, sql: str):
        self.raw = sql
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self) -> Token | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Token:
        t = self.peek()
        if t is None:
            raise InvalidArgument("unexpected end of statement")
        self.i += 1
        return t

    def at_kw(self, *kws) -> bool:
        t = self.peek()
        return (t is not None and t.kind == "name"
                and t.text.upper() in kws)

    def take_kw(self, *kws) -> bool:
        if self.at_kw(*kws):
            self.i += 1
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.take_kw(kw):
            raise InvalidArgument(f"expected {kw}, got {self.peek()}")

    def at_sym(self, s: str) -> bool:
        t = self.peek()
        return t is not None and t.kind in ("sym", "op") and t.text == s

    def take_sym(self, s: str) -> bool:
        if self.at_sym(s):
            self.i += 1
            return True
        return False

    def expect_sym(self, s: str) -> None:
        if not self.take_sym(s):
            raise InvalidArgument(f"expected {s!r}, got {self.peek()}")

    def ident(self) -> str:
        t = self.next()
        if t.kind != "name":
            raise InvalidArgument(f"expected identifier, got {t}")
        if t.text.startswith('"'):
            return t.text[1:-1].replace('""', '"')
        return t.text.lower()

    def literal(self):
        neg = self.take_sym("-")
        t = self.next()
        if t.kind == "param":
            if neg:
                raise InvalidArgument("cannot negate a bind marker")
            idx = int(t.text[1:])
            if idx < 1:  # $0 would alias params[-1] via negative indexing
                raise InvalidArgument(
                    f"bind markers are 1-based: {t.text}")
            return ast.BindMarker(idx - 1)
        if t.kind == "string":
            if neg:
                raise InvalidArgument("cannot negate a string")
            return t.text[1:-1].replace("''", "'")
        if t.kind == "number":
            v = (float(t.text) if any(c in t.text for c in ".eE")
                 else int(t.text))
            return -v if neg else v
        if t.kind == "name" and not neg:
            up = t.text.upper()
            if up in ("NEXTVAL", "CURRVAL") and self.at_sym("("):
                self.i -= 1  # re-read the function name
                return self._seq_func()
            if up == "TRUE":
                return True
            if up == "FALSE":
                return False
            if up == "NULL":
                return None
        raise InvalidArgument(f"expected literal, got {t}")

    # -- statements --------------------------------------------------------
    def parse(self):
        t = self.peek()
        if t is None:
            raise InvalidArgument("empty statement")
        head = t.text.upper()
        if head == "CREATE":
            self.next()
            if self.at_kw("TABLE"):
                return self._create_table()
            if self.at_kw("INDEX", "UNIQUE"):
                return self._create_index()
            if self.take_kw("OR"):
                self.expect_kw("REPLACE")
                self.expect_kw("VIEW")
                return self._create_view(replace=True)
            if self.take_kw("VIEW"):
                return self._create_view(replace=False)
            if self.take_kw("SEQUENCE"):
                ine = False
                if self.take_kw("IF"):
                    self.expect_kw("NOT")
                    self.expect_kw("EXISTS")
                    ine = True
                return ast.CreateSequence(self.ident(), ine)
            raise InvalidArgument(f"cannot CREATE {self.peek()}")
        if head == "DROP":
            self.next()
            if self.take_kw("TABLE"):
                return ast.DropTable(*self._name_if_exists())
            if self.take_kw("INDEX"):
                return ast.DropIndex(*self._name_if_exists())
            if self.take_kw("VIEW"):
                return ast.DropView(*self._name_if_exists())
            if self.take_kw("SEQUENCE"):
                return ast.DropSequence(*self._name_if_exists())
            raise InvalidArgument(f"cannot DROP {self.peek()}")
        if head in ("BEGIN", "START"):
            self.next()
            if head == "START":
                self.expect_kw("TRANSACTION")
            else:
                self.take_kw("TRANSACTION", "WORK")
            self.take_sym(";")
            return ast.TxnControl("begin")
        if head == "COMMIT":
            self.next()
            self.take_kw("TRANSACTION", "WORK")
            self.take_sym(";")
            return ast.TxnControl("commit")
        if head in ("ROLLBACK", "ABORT"):
            self.next()
            if self.take_kw("TO"):
                self.take_kw("SAVEPOINT")
                name = self.ident()
                self.take_sym(";")
                return ast.TxnControl("rollback_to", name)
            self.take_kw("TRANSACTION", "WORK")
            self.take_sym(";")
            return ast.TxnControl("rollback")
        if head == "SAVEPOINT":
            self.next()
            name = self.ident()
            self.take_sym(";")
            return ast.TxnControl("savepoint", name)
        if head == "RELEASE":
            self.next()
            self.take_kw("SAVEPOINT")
            name = self.ident()
            self.take_sym(";")
            return ast.TxnControl("release", name)
        if head == "ALTER":
            return self._alter_table()
        if head == "INSERT":
            return self._insert()
        if head == "UPDATE":
            return self._update()
        if head == "DELETE":
            return self._delete()
        if head == "SELECT":
            return self._select_entry()
        if head == "WITH":
            return self._with_select()
        raise InvalidArgument(f"unsupported statement {head}")

    def _select_entry(self):
        """SELECT possibly followed by UNION / EXCEPT / INTERSECT
        [ALL] chains; INTERSECT binds tighter (PG precedence) and the
        trailing ORDER BY/LIMIT/OFFSET binds to the whole chain."""
        branches = [self._select()]
        seps: list[tuple] = []
        while True:
            if self.take_kw("UNION"):
                kind = "union"
            elif self.take_kw("EXCEPT"):
                kind = "except"
            elif self.take_kw("INTERSECT"):
                kind = "intersect"
            else:
                break
            seps.append((kind, bool(self.take_kw("ALL"))))
            branches.append(self._select())
        if not seps:
            return branches[0]
        for b in branches[:-1]:
            if b.order_by or b.limit is not None or b.offset is not None:
                raise InvalidArgument(
                    "ORDER BY/LIMIT is only supported after the last "
                    "branch of a set operation (it applies to the "
                    "whole result)")
        import dataclasses as _dc

        last = branches[-1]
        order_by, limit, offset = last.order_by, last.limit, last.offset
        branches[-1] = _dc.replace(last, order_by=[], limit=None,
                                   offset=None)

        def joint(a, kind, alln, b):
            return ast.Union([a, b], [alln], kinds=[kind])

        # Precedence pass 1: fold INTERSECT joints into their left
        # neighbor; pass 2: left-fold the remaining UNION/EXCEPT.
        vals = [branches[0]]
        ops: list[tuple] = []
        for (kind, alln), b in zip(seps, branches[1:]):
            if kind == "intersect":
                vals[-1] = joint(vals[-1], kind, alln, b)
            else:
                ops.append((kind, alln))
                vals.append(b)
        acc = vals[0]
        for (kind, alln), b in zip(ops, vals[1:]):
            acc = joint(acc, kind, alln, b)
        return _dc.replace(acc, order_by=order_by, limit=limit,
                           offset=offset)

    def _with_select(self):
        """WITH name AS (select) [, name AS (select)]* SELECT ... — CTEs
        (reference capability: stock PG CTE scans above the FDW,
        src/postgres/src/backend/executor/nodeCtescan.c)."""
        self.expect_kw("WITH")
        if self.at_kw("RECURSIVE"):
            raise InvalidArgument("WITH RECURSIVE is not supported")
        ctes = []
        while True:
            name = self.ident()
            self.expect_kw("AS")
            self.expect_sym("(")
            sel = self._select_entry()
            self.expect_sym(")")
            ctes.append((name, sel))
            if not self.take_sym(","):
                break
        body = self._select_entry()
        body.ctes = ctes
        return body

    def _name_if_exists(self):
        if_exists = False
        if self.take_kw("IF"):
            self.expect_kw("EXISTS")
            if_exists = True
        name = self.ident()
        self.take_sym(";")
        return name, if_exists

    # -- DDL ---------------------------------------------------------------
    def _type(self) -> DataType:
        name = self.ident().upper()
        if name == "DOUBLE":
            self.take_kw("PRECISION")
            return DataType.DOUBLE
        if name == "FLOAT":
            return DataType.DOUBLE  # SQL FLOAT defaults to float8
        dt = _TYPES.get(name)
        if dt is None:
            raise InvalidArgument(f"unknown type {name}")
        if self.take_sym("("):  # VARCHAR(n) / NUMERIC(p,s): args ignored
            self.literal()
            if self.take_sym(","):
                self.literal()
            self.expect_sym(")")
        return dt

    def _create_table(self) -> ast.CreateTable:
        self.expect_kw("TABLE")
        if_not_exists = False
        if self.take_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            if_not_exists = True
        name = self.ident()
        self.expect_sym("(")
        columns: list[ast.ColumnDef] = []
        hash_keys: list[str] = []
        range_keys: list[str] = []
        while True:
            if self.take_kw("PRIMARY"):
                self.expect_kw("KEY")
                self.expect_sym("(")
                # YSQL shape: PRIMARY KEY ((h1, h2), r1, r2 [ASC|DESC]).
                # A plain list makes the FIRST column the hash column
                # (YSQL's default for the leading PK column).
                if self.take_sym("("):
                    while not self.take_sym(")"):
                        hash_keys.append(self.ident())
                        self.take_sym(",")
                else:
                    hash_keys.append(self.ident())
                    self.take_kw("HASH")
                while self.take_sym(","):
                    range_keys.append(self.ident())
                    self.take_kw("ASC") or self.take_kw("DESC")
                self.expect_sym(")")
            else:
                cname = self.ident()
                dtype = self._type()
                columns.append(ast.ColumnDef(cname, dtype))
                if self.take_kw("PRIMARY"):
                    self.expect_kw("KEY")
                    hash_keys.append(cname)
                self.take_kw("NOT") and self.expect_kw("NULL")
            if not self.take_sym(","):
                break
        self.expect_sym(")")
        num_tablets = None
        if self.take_kw("SPLIT"):
            self.expect_kw("INTO")
            num_tablets = int(self.literal())
            self.expect_kw("TABLETS")
        self.take_sym(";")
        if not hash_keys:
            raise InvalidArgument("table has no primary key")
        return ast.CreateTable(name, columns, hash_keys, range_keys,
                               if_not_exists, num_tablets)

    def _alter_table(self) -> ast.AlterTable:
        self.expect_kw("ALTER")
        self.expect_kw("TABLE")
        name = self.ident()
        if self.take_kw("ADD"):
            self.take_kw("COLUMN")
            col = self.ident()
            dtype = self._type()
            self.take_sym(";")
            return ast.AlterTable(name, "add", col, dtype)
        if self.take_kw("DROP"):
            self.take_kw("COLUMN")
            col = self.ident()
            self.take_sym(";")
            return ast.AlterTable(name, "drop", col)
        if self.take_kw("RENAME"):
            self.take_kw("COLUMN")
            old = self.ident()
            self.expect_kw("TO")
            new = self.ident()
            self.take_sym(";")
            return ast.AlterTable(name, "rename", old, new_name=new)
        raise InvalidArgument(
            f"expected ADD/DROP/RENAME, got {self.peek()}")

    def _create_index(self) -> ast.CreateIndex:
        self.take_kw("UNIQUE")  # accepted, enforced as a plain index
        self.expect_kw("INDEX")
        if_not_exists = False
        if self.take_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            if_not_exists = True
        name = self.ident()
        self.expect_kw("ON")
        table = self.ident()
        self.expect_sym("(")
        column = self.ident()
        self.expect_sym(")")
        self.take_sym(";")
        return ast.CreateIndex(name, table, column, if_not_exists)

    # -- DML ---------------------------------------------------------------
    def _insert(self) -> ast.Insert:
        self.expect_kw("INSERT")
        self.expect_kw("INTO")
        table = self.ident()
        self.expect_sym("(")
        columns = [self.ident()]
        while self.take_sym(","):
            columns.append(self.ident())
        self.expect_sym(")")
        self.expect_kw("VALUES")
        rows = []
        while True:
            self.expect_sym("(")
            vals = [self.literal()]
            while self.take_sym(","):
                vals.append(self.literal())
            self.expect_sym(")")
            if len(vals) != len(columns):
                raise InvalidArgument(
                    f"{len(columns)} columns but {len(vals)} values")
            rows.append(vals)
            if not self.take_sym(","):
                break
        self.take_sym(";")
        return ast.Insert(table, columns, rows)

    def _update(self) -> ast.Update:
        self.expect_kw("UPDATE")
        table = self.ident()
        self.expect_kw("SET")
        assignments = []
        while True:
            cname = self.ident()
            self.expect_sym("=")
            assignments.append((cname, self._scalar_or_literal()))
            if not self.take_sym(","):
                break
        where = self._where()
        self.take_sym(";")
        return ast.Update(table, assignments, where)

    def _delete(self) -> ast.Delete:
        self.expect_kw("DELETE")
        self.expect_kw("FROM")
        table = self.ident()
        where = self._where()
        self.take_sym(";")
        return ast.Delete(table, where)

    # -- SELECT ------------------------------------------------------------
    _CLAUSE_KWS = ("FROM", "WHERE", "GROUP", "ORDER", "LIMIT", "OFFSET",
                   "AS", "JOIN", "INNER", "LEFT", "RIGHT", "FULL",
                   "CROSS", "ON", "HAVING", "AND", "OR", "DESC", "ASC",
                   "UNION", "EXCEPT", "INTERSECT")

    SCALAR_FNS = ("abs", "upper", "lower", "length", "coalesce", "round",
                  "floor", "ceil", "ceiling", "concat", "mod",
                  "substring", "substr", "nullif", "greatest", "least")

    def _create_view(self, replace: bool):
        name = self.ident()
        self.expect_kw("AS")
        t = self.peek()
        if t is None:
            raise InvalidArgument("CREATE VIEW needs a query")
        query_sql = self.raw[t.pos:].rstrip().rstrip(";")
        select = self._select_entry()  # validated now, re-parsed at use
        return ast.CreateView(name, query_sql, select, replace)

    def _select(self) -> ast.Select:
        self.expect_kw("SELECT")
        distinct = bool(self.take_kw("DISTINCT"))
        items = [self._select_item()]
        while self.take_sym(","):
            items.append(self._select_item())
        if not self.at_kw("FROM"):
            # FROM-less SELECT: constant/sequence-function items
            # (PG: SELECT nextval('s')); column references need a FROM.
            for it in items:
                if isinstance(it.expr, Col) or it.expr == "*":
                    raise InvalidArgument("SELECT needs a FROM clause")
            return ast.Select(items, None)
        self.expect_kw("FROM")
        table = self._table_name()
        alias = self._table_alias()
        joins: list[ast.Join] = []
        while True:
            if self.take_kw("JOIN"):
                kind = "inner"
            elif self.at_kw("INNER") and self._kw_ahead(1, "JOIN"):
                self.next(); self.expect_kw("JOIN")
                kind = "inner"
            elif self.at_kw("LEFT"):
                self.next()
                self.take_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "left"
            elif self.at_kw("RIGHT"):
                self.next()
                self.take_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "right"
            elif self.at_kw("FULL"):
                self.next()
                self.take_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "full"
            else:
                break
            jtable = self._table_name()
            jalias = self._table_alias()
            self.expect_kw("ON")
            on = [self._on_pair()]
            while self.take_kw("AND"):
                on.append(self._on_pair())
            joins.append(ast.Join(jtable, jalias, kind, on))
        where = self._where()
        group_by: list[str] = []
        if self.take_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self._colref())
            while self.take_sym(","):
                group_by.append(self._colref())
        having: list[ast.HavingRel] = []
        if self.take_kw("HAVING"):
            while True:
                expr = self._item_expr()
                t = self.next()
                if t.kind != "op":
                    raise InvalidArgument(
                        f"expected operator in HAVING, got {t}")
                op = "!=" if t.text == "<>" else t.text
                having.append(ast.HavingRel(expr, op, self.literal()))
                if not self.take_kw("AND"):
                    break
        order_by: list[ast.OrderBy] = []
        if self.take_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                col = self._colref()
                desc = bool(self.take_kw("DESC"))
                if not desc:
                    self.take_kw("ASC")
                order_by.append(ast.OrderBy(col, desc))
                if not self.take_sym(","):
                    break
        limit = None
        offset = None
        while True:  # PG accepts LIMIT/OFFSET in either order
            if limit is None and self.take_kw("LIMIT"):
                limit = self.literal()
            elif offset is None and self.take_kw("OFFSET"):
                offset = self.literal()
            else:
                break
        self.take_sym(";")
        return ast.Select(items, table, where, group_by, order_by, limit,
                          distinct, alias, joins, having, offset=offset)

    def _kw_ahead(self, n: int, kw: str) -> bool:
        t = self.toks[self.i + n] if self.i + n < len(self.toks) else None
        return t is not None and t.kind == "name" and t.text.upper() == kw

    def _table_name(self) -> str:
        """Possibly schema-qualified table: name or schema.name (the
        pg_catalog / information_schema surface)."""
        name = self.ident()
        if self.at_sym("."):
            self.next()
            return f"{name}.{self.ident()}"
        return name

    def _table_alias(self) -> str | None:
        if self.take_kw("AS"):
            return self.ident()
        t = self.peek()
        if (t is not None and t.kind == "name"
                and t.text.upper() not in self._CLAUSE_KWS):
            return self.ident()
        return None

    def _colref(self) -> str:
        """Possibly-qualified column reference: name or alias.name."""
        name = self.ident()
        if self.at_sym("."):
            self.next()
            return f"{name}.{self.ident()}"
        return name

    def _on_pair(self) -> tuple:
        left = self._colref()
        self.expect_sym("=")
        return (left, self._colref())

    def _select_item(self) -> ast.SelectItem:
        if self.take_sym("*"):
            return ast.SelectItem("*")
        expr = self._item_expr()
        alias = None
        if self.take_kw("AS"):
            alias = self.ident()
        elif (self.peek() is not None and self.peek().kind == "name"
              and self.peek().text.upper() not in self._CLAUSE_KWS):
            alias = self.ident()
        return ast.SelectItem(expr, alias)

    def _seq_func(self):
        """nextval('s') / currval('s') — the only SQL functions the
        value grammar knows (used from VALUES lists and select items)."""
        fn = self.ident().lower()
        self.expect_sym("(")
        seq = self.next()
        if seq.kind != "string":
            raise InvalidArgument(f"{fn} takes a sequence name string")
        self.expect_sym(")")
        return ast.SeqFunc(fn, seq.text[1:-1])

    def _item_expr(self):
        t = self.peek()
        if (t is not None and t.kind == "name"
                and t.text.upper() in ("NEXTVAL", "CURRVAL")
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1].text == "("):
            return self._seq_func()
        if (t is not None and t.kind == "name"
                and t.text.lower() in AGG_FNS
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1].text == "("):
            fn = self.ident().lower()
            self.expect_sym("(")
            if self.take_sym("*"):
                if fn != "count":
                    raise InvalidArgument(f"{fn}(*) is not valid")
                arg = None
            else:
                arg = self._scalar()
            self.expect_sym(")")
            if self.at_kw("OVER"):
                return self._over(fn, arg)
            return ast.Agg(fn, arg)
        if (t is not None and t.kind == "name"
                and t.text.lower() in self.WINDOW_FNS
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1].text == "("):
            fn = self.ident().lower()
            self.expect_sym("(")
            arg, offset, default = None, 1, None
            if not self.at_sym(")"):
                arg = self._scalar()
                if self.take_sym(","):
                    offset = self.literal()
                    if self.take_sym(","):
                        default = self.literal()
            self.expect_sym(")")
            if fn in ("lag", "lead") and arg is None:
                raise InvalidArgument(f"{fn}() needs an argument")
            if not self.at_kw("OVER"):
                raise InvalidArgument(f"{fn}() requires an OVER clause")
            return self._over(fn, arg, offset, default)
        return self._scalar()

    WINDOW_FNS = frozenset({"row_number", "rank", "dense_rank",
                            "lag", "lead"})

    def _over(self, fn, arg, offset=1, default=None) -> ast.WindowFunc:
        """OVER ( [PARTITION BY cols] [ORDER BY col [ASC|DESC], ...] )."""
        self.expect_kw("OVER")
        self.expect_sym("(")
        partition: list[str] = []
        order: list[ast.OrderBy] = []
        if self.take_kw("PARTITION"):
            self.expect_kw("BY")
            partition.append(self._colref())
            while self.take_sym(","):
                partition.append(self._colref())
        if self.take_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                col = self._colref()
                desc = bool(self.take_kw("DESC"))
                if not desc:
                    self.take_kw("ASC")
                order.append(ast.OrderBy(col, desc))
                if not self.take_sym(","):
                    break
        self.expect_sym(")")
        return ast.WindowFunc(fn, arg, partition, order,
                              offset=offset, default=default)

    # -- scalar expressions (storage.expr trees) ---------------------------
    def _scalar(self):
        node = self._term()
        while self.at_sym("+") or self.at_sym("-"):
            op = self.next().text
            node = BinOp(op, node, self._term())
        return node

    def _term(self):
        node = self._factor()
        while self.at_sym("*"):
            self.next()
            node = BinOp("*", node, self._factor())
        return node

    def _factor(self):
        if self.take_sym("("):
            node = self._scalar()
            self.expect_sym(")")
            return node
        t = self.peek()
        if t is not None and (t.kind == "number" or self.at_sym("-")):
            return Const(self.literal())
        if t is not None and t.kind == "string":
            return Const(self.literal())
        if (t is not None and t.kind == "name"
                and t.text.lower() in self.SCALAR_FNS
                and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1].text == "("):
            fn = self.ident().lower()
            self.expect_sym("(")
            args = []
            if not self.at_sym(")"):
                args.append(self._scalar())
                while self.take_sym(","):
                    args.append(self._scalar())
            self.expect_sym(")")
            return ast.Func(fn, args)
        name = self._colref()
        # jsonb path: col -> 'key' -> 0 ->> 'leaf'
        steps = []
        while self.peek() is not None and self.peek().kind == "op" \
                and self.peek().text in ("->", "->>"):
            op = self.next().text
            steps.append((op, self.literal()))
        if steps:
            return ast.JsonPath(name, steps)
        return Col(name)

    def _scalar_or_literal(self):
        """UPDATE SET rhs: a literal (any type) or a column expression."""
        t = self.peek()
        if t is not None and (t.kind in ("string", "param")
                              or (t.kind == "name" and t.text.upper()
                                  in ("TRUE", "FALSE", "NULL"))):
            return self.literal()
        if t is not None and t.kind == "number":
            return self.literal()
        if t is not None and self.at_sym("-"):
            return self.literal()
        return self._scalar()

    # -- WHERE -------------------------------------------------------------
    def _at_subquery(self) -> bool:
        return self.at_sym("(") and self._kw_ahead(1, "SELECT")

    def _subquery(self) -> ast.SubQuery:
        self.expect_sym("(")
        sel = self._select()
        self.expect_sym(")")
        return ast.SubQuery(sel)

    def _where(self) -> list[ast.Rel]:
        rels: list[ast.Rel] = []
        if not self.take_kw("WHERE"):
            return rels
        while True:
            neg = False
            if self.at_kw("NOT") and self._kw_ahead(1, "EXISTS"):
                self.next()
                neg = True
            if self.at_kw("EXISTS"):
                self.expect_kw("EXISTS")
                if not self._at_subquery():
                    raise InvalidArgument(
                        "EXISTS requires a parenthesized subquery")
                rels.append(ast.Rel(None,
                                    "NOT EXISTS" if neg else "EXISTS",
                                    self._subquery()))
                if not self.take_kw("AND"):
                    break
                continue
            col = self._colref()
            if self.take_kw("BETWEEN"):
                lo = self.literal()
                self.expect_kw("AND")
                hi = self.literal()
                rels.append(ast.Rel(col, ">=", lo))
                rels.append(ast.Rel(col, "<=", hi))
            elif self.take_kw("IN"):
                if self._at_subquery():
                    rels.append(ast.Rel(col, "IN", self._subquery()))
                else:
                    self.expect_sym("(")
                    vals = [self.literal()]
                    while self.take_sym(","):
                        vals.append(self.literal())
                    self.expect_sym(")")
                    rels.append(ast.Rel(col, "IN", tuple(vals)))
            else:
                t = self.next()
                if t.kind != "op":
                    raise InvalidArgument(f"expected operator, got {t}")
                op = "!=" if t.text == "<>" else t.text
                if self._at_subquery():
                    value = self._subquery()
                else:
                    v = self.peek()
                    if (v is not None and v.kind == "name"
                            and v.text.upper() not in ("TRUE", "FALSE",
                                                       "NULL")):
                        # Column reference as the rhs: col-vs-col inside
                        # a subquery is how correlation is spelled; the
                        # executor resolves outer refs per row.
                        value = Col(self._colref())
                    else:
                        value = self.literal()
                rels.append(ast.Rel(col, op, value))
            if not self.take_kw("AND"):
                break
        return rels


def parse_statement(sql: str):
    p = Parser(sql)
    stmt = p.parse()
    if p.peek() is not None:
        raise InvalidArgument(f"trailing tokens at {p.peek()}")
    return stmt


def parse_script(sql: str):
    """Split a multi-statement string on top-level ';' and parse each
    (the simple-query wire message may carry several statements).
    Comment-only fragments are skipped, not syntax errors."""
    stmts = []
    for part in _split_statements(sql):
        if part.strip() and tokenize(part):
            stmts.append(parse_statement(part))
    return stmts


def _split_statements(sql: str):
    out, depth, start, i = [], 0, 0, 0
    in_str = False
    while i < len(sql):
        c = sql[i]
        if in_str:
            if c == "'":
                in_str = False
        elif c == "-" and sql[i:i + 2] == "--":
            nl = sql.find("\n", i)
            i = len(sql) if nl < 0 else nl
            continue
        elif c == "'":
            in_str = True
        elif c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        elif c == ";" and depth == 0:
            out.append(sql[start:i])
            start = i + 1
        i += 1
    out.append(sql[start:])
    return out
