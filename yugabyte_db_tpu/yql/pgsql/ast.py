"""SQL (YSQL-dialect) statement AST.

Reference analog: the parse-tree the PostgreSQL fork hands to pggate —
statement shapes mirroring PgStatement subclasses (PgSelect/PgInsert/
PgUpdate/PgDelete/PgCreateTable, src/yb/yql/pggate/pg_select.cc etc.).
Scalar expressions reuse storage.expr nodes (Col/Const/BinOp) so an
aggregate argument parses straight into the device-lowerable tree.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from yugabyte_db_tpu.models.datatypes import DataType


@dataclass
class ColumnDef:
    name: str
    dtype: DataType


@dataclass
class CreateTable:
    name: str
    columns: list[ColumnDef]
    hash_keys: list[str]
    range_keys: list[str]
    if_not_exists: bool = False
    num_tablets: int | None = None


@dataclass
class DropTable:
    name: str
    if_exists: bool = False


@dataclass
class AlterTable:
    """ALTER TABLE t ADD/DROP/RENAME COLUMN."""

    name: str
    action: str                    # "add" | "drop" | "rename"
    column: str | None = None
    dtype: DataType | None = None  # for "add"
    new_name: str | None = None    # for "rename"


@dataclass
class CreateIndex:
    name: str
    table: str
    column: str
    if_not_exists: bool = False


@dataclass
class DropIndex:
    name: str
    if_exists: bool = False


@dataclass
class CreateView:
    """CREATE [OR REPLACE] VIEW name AS <select> — the defining query is
    stored as SQL text and re-planned at use (reference: pg_rewrite)."""

    name: str
    query_sql: str
    select: object             # parsed ast.Select (validation)
    replace: bool = False


@dataclass
class DropView:
    name: str
    if_exists: bool = False


@dataclass
class CreateSequence:
    name: str
    if_not_exists: bool = False


@dataclass
class DropSequence:
    name: str
    if_exists: bool = False


@dataclass
class SeqFunc:
    """nextval('s') / currval('s') in a VALUES list or bare SELECT."""

    kind: str                  # "nextval" | "currval"
    sequence: str


@dataclass
class TxnControl:
    """BEGIN / COMMIT / ROLLBACK / SAVEPOINT name / ROLLBACK TO name /
    RELEASE name."""

    kind: str                  # "begin" | "commit" | "rollback" |
                               # "savepoint" | "rollback_to" | "release"
    name: str | None = None    # savepoint name


@dataclass
class BindMarker:
    """$N placeholder (1-based in SQL text, stored 0-based)."""

    index: int


@dataclass
class Insert:
    table: str
    columns: list[str]
    rows: list[list]           # one value list per VALUES tuple


@dataclass
class Rel:
    """One WHERE conjunct: column op value (IN carries a tuple).
    [NOT] EXISTS conjuncts carry column=None and a SubQuery value."""

    column: str | None
    op: str                    # = != < <= > >= IN | EXISTS | NOT EXISTS
    value: object


@dataclass
class Update:
    table: str
    assignments: list[tuple]   # (column, expr-or-literal)
    where: list[Rel]


@dataclass
class Delete:
    table: str
    where: list[Rel]


@dataclass
class JsonPath:
    """col -> 'key' -> 0 ->> 'leaf': jsonb extraction, host-evaluated
    (reference: jsonb operators over common/jsonb.cc)."""

    column: str
    steps: list                # [(op "->"|"->>", key str|int), ...]


@dataclass
class Agg:
    fn: str                    # count | sum | min | max | avg
    arg: object | None         # storage.expr tree, or None for count(*)


@dataclass
class Func:
    """Scalar function call (abs/upper/lower/length/coalesce/round/
    floor/ceil/concat/mod/substring/nullif/greatest/least), evaluated
    host-side above the storage seam — the work stock PG's executor does
    above the FDW (reference capability:
    src/postgres/src/backend/utils/adt)."""

    name: str
    args: list


@dataclass
class WindowFunc:
    """fn(arg) OVER (PARTITION BY ... ORDER BY ...) — evaluated
    host-side over the fetched relation, the work stock PG's
    nodeWindowAgg.c does above the FDW (reference capability:
    src/postgres/src/backend/executor/nodeWindowAgg.c). With ORDER BY,
    aggregate windows use PG's default frame (RANGE UNBOUNDED PRECEDING
    .. CURRENT ROW): running values where order-key peers share a
    result; without ORDER BY the frame is the whole partition."""

    fn: str                    # row_number|rank|dense_rank|lag|lead|
                               # sum|count|avg|min|max
    arg: object | None         # storage.expr tree (None: row_number etc)
    partition_by: list[str] = field(default_factory=list)
    order_by: list["OrderBy"] = field(default_factory=list)
    offset: int = 1            # lag/lead displacement
    default: object = None     # lag/lead out-of-partition fill


@dataclass
class SelectItem:
    expr: object               # "*" | storage.expr tree | Agg
    alias: str | None = None


@dataclass
class OrderBy:
    column: str
    desc: bool = False


@dataclass
class SubQuery:
    """A parenthesized SELECT used as a scalar / IN-list value in WHERE
    (uncorrelated; reference capability: the full PG executor runs
    subplans above the FDW, src/postgres/src/backend/executor)."""

    select: "Select"


@dataclass
class Join:
    """One JOIN clause: JOIN table [alias] ON a.x = b.y [AND ...]."""

    table: str
    alias: str | None
    kind: str                  # "inner" | "left"
    on: list[tuple]            # [(left_ref, right_ref)] column refs


@dataclass
class HavingRel:
    """One HAVING conjunct: <agg-or-scalar expr> op literal."""

    expr: object               # Agg | storage.expr tree
    op: str
    value: object


@dataclass
class Union:
    """Set operations over same-arity queries: UNION / EXCEPT /
    INTERSECT, each optionally ALL, left-associative with INTERSECT
    binding tighter (parser builds the precedence nesting); the
    trailing ORDER BY / LIMIT / OFFSET applies to the whole chain
    (PG semantics; reference capability: nodeSetOp.c / nodeAppend.c
    above the FDW)."""

    branches: list                   # [Select | Union, ...]
    alls: list                       # [bool] per joint, len-1 of branches
    order_by: list = field(default_factory=list)
    limit: object | None = None
    offset: object | None = None
    ctes: list = field(default_factory=list)
    kinds: list = field(default_factory=list)  # per joint: "union" |
                                               # "except" | "intersect"


@dataclass
class Select:
    items: list[SelectItem]
    table: str
    where: list[Rel] = field(default_factory=list)
    group_by: list[str] = field(default_factory=list)
    order_by: list[OrderBy] = field(default_factory=list)
    limit: object | None = None
    distinct: bool = False
    alias: str | None = None           # base-table alias
    joins: list[Join] = field(default_factory=list)
    having: list[HavingRel] = field(default_factory=list)
    offset: object | None = None       # LIMIT ... OFFSET n
    # WITH clause: [(name, Select)] evaluated before the body; later
    # CTEs and the body may reference earlier names as tables.
    ctes: list = field(default_factory=list)
