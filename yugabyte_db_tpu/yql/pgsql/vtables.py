"""pg_catalog / information_schema virtual tables.

Reference capability: YSQL ships PostgreSQL's full system catalogs
(initdb populates pg_catalog; src/postgres/src/backend/catalog). Here
the introspection surface drivers and ORMs actually query is served
from live cluster state, the same approach as the CQL system vtables
(yql/cql/vtables.py): rows materialize per query, then ride the
executor's normal projection/WHERE/ORDER BY machinery.

Served: pg_catalog.{pg_tables, pg_class, pg_namespace, pg_database,
pg_roles}, information_schema.{tables, columns}. Bare names resolve
too (PG search_path behavior for pg_catalog).
"""

from __future__ import annotations

import uuid

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.utils.metrics import count_swallowed

_PG_TYPE_NAMES = {
    DataType.INT8: "smallint", DataType.INT16: "smallint",
    DataType.INT32: "integer", DataType.INT64: "bigint",
    DataType.STRING: "text", DataType.FLOAT: "real",
    DataType.DOUBLE: "double precision", DataType.BOOL: "boolean",
    DataType.BINARY: "bytea", DataType.TIMESTAMP: "timestamp",
    DataType.COUNTER: "bigint", DataType.JSONB: "jsonb",
    DataType.LIST: "jsonb", DataType.SET: "jsonb", DataType.MAP: "jsonb",
}

_CANONICAL = {
    "pg_tables": "pg_catalog.pg_tables",
    "pg_class": "pg_catalog.pg_class",
    "pg_namespace": "pg_catalog.pg_namespace",
    "pg_database": "pg_catalog.pg_database",
    "pg_roles": "pg_catalog.pg_roles",
}


def is_virtual(table: str) -> bool:
    return (table in _CANONICAL
            or table.startswith("pg_catalog.")
            or table.startswith("information_schema."))


def _oid(name: str) -> int:
    return int(uuid.uuid5(uuid.NAMESPACE_DNS, name).hex[:6], 16)


def _user_tables(processor):
    out = []
    for name in sorted(processor.cluster.tables):
        try:
            schema = processor.cluster.table(name).schema
        except Exception as e:  # noqa: BLE001 — dropped concurrently
            count_swallowed("pg_vtables.table_schema", e)
            continue
        out.append((name, schema))
    return out


def _rows_for(processor, table: str) -> list[dict]:
    if table == "pg_catalog.pg_tables":
        return [{"schemaname": "public", "tablename": n,
                 "tableowner": "postgres", "hasindexes":
                 bool(getattr(processor.cluster.table(n), "indexes", []))}
                for n, _s in _user_tables(processor)]
    if table == "pg_catalog.pg_class":
        return [{"oid": _oid(n), "relname": n, "relkind": "r",
                 "relnamespace": _oid("public"),
                 "relnatts": len(s.columns)}
                for n, s in _user_tables(processor)]
    if table == "pg_catalog.pg_namespace":
        return [{"oid": _oid(ns), "nspname": ns}
                for ns in ("public", "pg_catalog", "information_schema")]
    if table == "pg_catalog.pg_database":
        return [{"datname": "yugabyte", "encoding": 6}]
    if table == "pg_catalog.pg_roles":
        store = getattr(processor.cluster, "auth_store", None)
        if store is None:
            return []
        return [{"rolname": r.name, "rolsuper": r.superuser,
                 "rolcanlogin": r.can_login}
                for r in store().list_roles()]
    if table == "information_schema.tables":
        return [{"table_catalog": "yugabyte", "table_schema": "public",
                 "table_name": n, "table_type": "BASE TABLE"}
                for n, _s in _user_tables(processor)]
    if table == "information_schema.columns":
        rows = []
        for n, s in _user_tables(processor):
            for i, c in enumerate(s.columns, start=1):
                rows.append({
                    "table_catalog": "yugabyte",
                    "table_schema": "public", "table_name": n,
                    "column_name": c.name, "ordinal_position": i,
                    "data_type": _PG_TYPE_NAMES.get(c.dtype, "text"),
                    "is_nullable": "YES" if c.nullable else "NO",
                })
        return rows
    from yugabyte_db_tpu.utils.status import NotFound

    raise NotFound(f"relation {table} does not exist")


class _VCol:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name


class _VSchema:
    def __init__(self, names):
        self.columns = [_VCol(n) for n in names]


class _VHandle:
    def __init__(self, names):
        self.schema = _VSchema(names)


_COLUMN_ORDER = {
    "pg_catalog.pg_tables": ["schemaname", "tablename", "tableowner",
                             "hasindexes"],
    "pg_catalog.pg_class": ["oid", "relname", "relkind", "relnamespace",
                            "relnatts"],
    "pg_catalog.pg_namespace": ["oid", "nspname"],
    "pg_catalog.pg_database": ["datname", "encoding"],
    "pg_catalog.pg_roles": ["rolname", "rolsuper", "rolcanlogin"],
    "information_schema.tables": ["table_catalog", "table_schema",
                                  "table_name", "table_type"],
    "information_schema.columns": ["table_catalog", "table_schema",
                                   "table_name", "column_name",
                                   "ordinal_position", "data_type",
                                   "is_nullable"],
}


def virtual_select(processor, stmt):
    """Run a (join-free) SELECT against one catalog vtable through the
    executor's host projection pipeline."""
    table = _CANONICAL.get(stmt.table, stmt.table)
    dicts = _rows_for(processor, table)
    # WHERE: plain predicate filtering over the dict rows.
    where = processor._resolved_where(stmt.where)
    for rel in where:
        col = rel.column.split(".")[-1]

        def keep(d, rel=rel, col=col):
            v = d.get(col)
            rv = rel.value
            if rel.op == "IN":
                return v in rv
            if v is None or rv is None:
                return False
            return {"=": v == rv, "!=": v != rv, "<": v < rv,
                    "<=": v <= rv, ">": v > rv, ">=": v >= rv}[rel.op]
        dicts = [d for d in dicts if keep(d)]
    alias = stmt.alias or table
    handle = _VHandle(_COLUMN_ORDER[table])
    # The host pipeline's '*' expansion emits alias-qualified refs.
    for d in dicts:
        for k in list(d):
            d[f"{alias}.{k}"] = d[k]
    return processor._finish_select(stmt, dicts, [(alias, handle)],
                                    {alias: handle})