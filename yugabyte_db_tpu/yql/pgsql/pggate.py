"""PgApi: the pggate-shaped embedding API.

Reference analog: pggate's C API object model — PgApiImpl
(src/yb/yql/pggate/pggate.h:58) owning sessions (PgSession,
pg_session.cc) and statement objects (PgSelect/PgInsert/PgUpdate/
PgDelete, pg_select.cc etc.) that the PostgreSQL backend creates via
YBCPgNewSelect / binds / executes via YBCPgExecSelect + YBCPgDmlFetch.
Here the backend is the in-repo SQL frontend (parser + PgProcessor),
so the statement object wraps a parsed AST and replays it with bound
parameters — the prepared-statement shape.
"""

from __future__ import annotations

from yugabyte_db_tpu.yql.pgsql.executor import PgProcessor, PgResult
from yugabyte_db_tpu.yql.pgsql.parser import parse_statement


class PgStatement:
    """A prepared statement: parse once, execute many with $N params
    (reference: PgDocOp reuse across YBCPgExec* calls)."""

    def __init__(self, session: "PgSession", sql: str):
        self.session = session
        self.sql = sql
        self.ast = parse_statement(sql)

    def execute(self, params: list | None = None) -> PgResult | None:
        return self.session.processor.execute(self.ast, params)


class PgSession:
    """One connection's execution context (reference: PgSession —
    per-connection state over the shared client)."""

    def __init__(self, api: "PgApi"):
        self.api = api
        self.processor = PgProcessor(api.cluster)
        self._statements: dict[str, PgStatement] = {}

    def execute(self, sql: str, params: list | None = None):
        return self.processor.execute(sql, params)

    def prepare(self, sql: str) -> PgStatement:
        stmt = self._statements.get(sql)
        if stmt is None:
            stmt = self._statements[sql] = PgStatement(self, sql)
        return stmt


class PgApi:
    """Process-wide pggate entry point over a Cluster seam (LocalCluster
    for in-process tablets, ClientCluster for a real cluster)."""

    def __init__(self, cluster):
        self.cluster = cluster

    def new_session(self) -> PgSession:
        return PgSession(self)
