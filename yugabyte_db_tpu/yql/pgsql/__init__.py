"""The YSQL layer: SQL frontend, pggate-shaped API, PG wire server.

Reference analog: the YSQL stack — PostgreSQL backend over pggate
(src/yb/yql/pggate/pggate.h:58) lowering to PgsqlReadOperation /
PgDocWriteOp (src/yb/docdb/pgsql_operation.cc:345, pg_doc_op.h:142).
Redesigned single-runtime: a SQL parser (parser.py) and executor
(executor.py) drive the same Cluster seam as the CQL frontend, with
grouped/expression aggregates pushed down to the storage engines (the
TPU engine runs them as one device dispatch per tablet); pggate.py is
the embedding API (PgApi/PgSession/PgStatement), wire.py the FE/BE v3
protocol server, and tpch.py the TPC-H Q1/Q6 workload bench.py measures.
"""

from yugabyte_db_tpu.yql.pgsql.executor import PgProcessor, PgResult
from yugabyte_db_tpu.yql.pgsql.operations import PgsqlReadOp
from yugabyte_db_tpu.yql.pgsql.parser import parse_script, parse_statement
from yugabyte_db_tpu.yql.pgsql.pggate import PgApi, PgSession, PgStatement
from yugabyte_db_tpu.yql.pgsql.tpch import (LINEITEM_COLUMNS,
                                            generate_lineitem, q1_result,
                                            q1_spec, q6_result, q6_spec)
from yugabyte_db_tpu.yql.pgsql.wire import PgServer

__all__ = [
    "LINEITEM_COLUMNS",
    "PgApi",
    "PgProcessor",
    "PgResult",
    "PgServer",
    "PgSession",
    "PgStatement",
    "PgsqlReadOp",
    "generate_lineitem",
    "parse_script",
    "parse_statement",
    "q1_result",
    "q1_spec",
    "q6_result",
    "q6_spec",
]
