"""YSQL-shaped analytics path: pgsql-style read operations + TPC-H.

Reference analog: the pggate -> PgsqlReadOperation pipeline
(src/yb/yql/pggate/pggate.h:58, src/yb/docdb/pgsql_operation.cc:345) —
reads with WHERE pushdown, expression aggregates, and GROUP BY evaluated
per tablet inside the scan, combined above it. The SQL surface rides the
shared SELECT frontend (yql.cql.parser grew GROUP BY / ORDER BY /
arithmetic aggregate expressions); this package adds the pgsql-flavored
operation objects and the TPC-H Q1/Q6 workload (schema, datagen,
runners) measured by bench.py.
"""

from yugabyte_db_tpu.yql.pgsql.operations import PgsqlReadOp
from yugabyte_db_tpu.yql.pgsql.tpch import (LINEITEM_COLUMNS,
                                            generate_lineitem, q1_result,
                                            q1_spec, q6_result, q6_spec)

__all__ = [
    "LINEITEM_COLUMNS",
    "PgsqlReadOp",
    "generate_lineitem",
    "q1_result",
    "q1_spec",
    "q6_result",
    "q6_spec",
]
