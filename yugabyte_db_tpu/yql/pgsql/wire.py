"""PgServer: a PostgreSQL v3 wire-protocol frontend (simple query flow).

Reference analog: in the reference, YSQL IS a postgres process
(pgwrapper spawns it, src/yb/tserver/tablet_server_main.cc:160) and the
backend's FE/BE protocol handling is PostgreSQL's own. The TPU-native
redesign keeps the framework single-runtime: this server speaks the
same FE/BE v3 protocol (startup, AuthenticationOk, simple Query,
RowDescription/DataRow/CommandComplete, ErrorResponse) directly on the
shared rpc Messenger via a pluggable ConnectionContext — the exact seam
the CQL and Redis frontends ride (src/yb/rpc/connection_context.h).

Covered: SSLRequest (refused with 'N'), StartupMessage (incl. the
cleartext-password handshake behind ysql_require_auth), simple Query
('Q', multi-statement), Terminate ('X'), and the extended query
protocol drivers actually use — Parse ('P'), Bind ('B'), Describe
('D'), Execute ('E'), Close ('C'), Flush ('H'), Sync ('S') with
error-skip-until-Sync semantics. Describe-portal executes the portal
eagerly (results cached for Execute) so RowDescription can be answered
without a separate planner output-schema pass.
"""

from __future__ import annotations

import struct

from yugabyte_db_tpu.rpc.messenger import ConnectionContext, Messenger
from yugabyte_db_tpu.utils.status import (AlreadyPresent, InvalidArgument,
                                          NotFound)
from yugabyte_db_tpu.yql.pgsql.executor import PgProcessor, PgResult
from yugabyte_db_tpu.yql.pgsql.parser import parse_script

_U32 = struct.Struct(">I")
_SSL_REQUEST = 80877103
_CANCEL_REQUEST = 80877102
_PROTO_V3 = 196608

# type OIDs (pg_type.h)
_OID_BOOL, _OID_BYTEA, _OID_INT8, _OID_INT4 = 16, 17, 20, 23
_OID_TEXT, _OID_FLOAT8 = 25, 701


# -- message builders --------------------------------------------------------

def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + _U32.pack(len(payload) + 4) + payload


def auth_ok() -> bytes:
    return _msg(b"R", _U32.pack(0))


def auth_cleartext_password() -> bytes:
    """AuthenticationCleartextPassword (R, code 3)."""
    return _msg(b"R", _U32.pack(3))


def parameter_status(key: str, value: str) -> bytes:
    return _msg(b"S", key.encode() + b"\x00" + value.encode() + b"\x00")


def ready_for_query(status: bytes = b"I") -> bytes:
    """'I' idle, 'T' in transaction, 'E' failed transaction."""
    return _msg(b"Z", status)


def command_complete(tag: str) -> bytes:
    return _msg(b"C", tag.encode() + b"\x00")


def empty_query_response() -> bytes:
    return _msg(b"I", b"")


def error_response(message: str, code: str = "XX000") -> bytes:
    fields = (b"SERROR\x00" + b"C" + code.encode() + b"\x00"
              + b"M" + message.encode("utf-8", "replace") + b"\x00\x00")
    return _msg(b"E", fields)


def _infer_oid(rows, col: int) -> int:
    for r in rows:
        v = r[col]
        if v is None:
            continue
        if isinstance(v, bool):
            return _OID_BOOL
        if isinstance(v, int):
            return _OID_INT8
        if isinstance(v, float):
            return _OID_FLOAT8
        if isinstance(v, (bytes, bytearray)):
            return _OID_BYTEA
        return _OID_TEXT
    return _OID_TEXT


def row_description(res: PgResult) -> bytes:
    parts = [struct.pack(">H", len(res.columns))]
    for i, name in enumerate(res.columns):
        oid = _infer_oid(res.rows, i)
        parts.append(name.encode() + b"\x00"
                     + struct.pack(">IHIhih", 0, 0, oid, -1, -1, 0))
    return _msg(b"T", b"".join(parts))


def _text(v) -> bytes:
    # Format definition shared with the native wire page server.
    from yugabyte_db_tpu.models.wirefmt import pg_text

    return pg_text(v)


def data_row(row: tuple) -> bytes:
    parts = [struct.pack(">H", len(row))]
    for v in row:
        if v is None:
            parts.append(struct.pack(">i", -1))
        else:
            b = _text(v)
            parts.append(struct.pack(">i", len(b)) + b)
    return _msg(b"D", b"".join(parts))


# -- connection context ------------------------------------------------------

class PgConnectionContext(ConnectionContext):
    """Stateful FE/BE framing: a connection starts in the startup phase
    (untyped length-prefixed packet), then switches to typed messages.
    Calls carry the context itself so the service keeps per-connection
    sessions without the messenger knowing about them."""

    ordered_responses = True

    def __init__(self):
        self._buf = bytearray()
        self._started = False
        self.session = None  # attached by the service on startup
        # Extended-protocol state.
        self.prepared: dict = {}       # name -> parsed statement AST
        self.portals: dict = {}        # name -> {"stmt","params","result"}
        self.skip_until_sync = False

    def feed(self, data: bytes) -> list:
        self._buf.extend(data)
        calls = []
        while True:
            if not self._started:
                if len(self._buf) < 4:
                    return calls
                (length,) = _U32.unpack_from(self._buf, 0)
                if length < 8 or length > 1 << 20:
                    raise ValueError(f"bad startup packet length {length}")
                if len(self._buf) < length:
                    return calls
                payload = bytes(self._buf[4:length])
                del self._buf[:length]
                (proto,) = _U32.unpack_from(payload, 0)
                if proto == _SSL_REQUEST:
                    calls.append((0, "pg", (self, "ssl", None)))
                    continue  # stay in startup phase
                if proto == _CANCEL_REQUEST:
                    continue  # no cancel support: ignore
                params = {}
                kv = payload[4:].split(b"\x00")
                for k, v in zip(kv[::2], kv[1::2]):
                    if k:
                        params[k.decode()] = v.decode()
                self._started = True
                calls.append((0, "pg", (self, "startup", params)))
                continue
            if len(self._buf) < 5:
                return calls
            tag = bytes(self._buf[:1])
            (length,) = _U32.unpack_from(self._buf, 1)
            if length < 4 or length > 64 * 1024 * 1024:
                raise ValueError(f"bad message length {length}")
            end = 1 + length
            if len(self._buf) < end:
                return calls
            payload = bytes(self._buf[5:end])
            del self._buf[:end]
            calls.append((0, "pg", (self, tag.decode(), payload)))

    def serialize(self, response) -> bytes:
        _tag, status, body = response
        if status == "ok":
            return body
        # Handler raised outside the per-statement guard: wire-level
        # error. Report the session's REAL txn state — claiming 'I'
        # while a transaction is open desyncs the driver's state machine.
        st = b"I"
        if self.session is not None and self.session.in_txn:
            st = self.session.txn_status.encode()
        return error_response(str(body)) + ready_for_query(st)


class PgServiceImpl:
    """Executes FE messages. Each connection gets its own PgProcessor
    (mirroring one backend per connection)."""

    def __init__(self, cluster):
        self.cluster = cluster

    @staticmethod
    def _session_ready() -> bytes:
        return (parameter_status("server_version", "11.2-yb-tpu")
                + parameter_status("client_encoding", "UTF8")
                + parameter_status("integer_datetimes", "on")
                + ready_for_query())

    def handle(self, _method: str, call) -> bytes:
        from yugabyte_db_tpu.utils.flags import FLAGS

        ctx, kind, payload = call
        if kind == "ssl":
            return b"N"  # SSL refused; client retries in cleartext
        if kind == "startup":
            if FLAGS.get("ysql_require_auth"):
                # Cleartext-password handshake (reference: pg_hba
                # password auth); the role must exist with LOGIN and a
                # matching password in the replicated role store.
                ctx.pending_user = payload.get("user", "")
                return auth_cleartext_password()
            ctx.session = PgProcessor(self.cluster)
            return auth_ok() + self._session_ready()
        if kind == "p":  # PasswordMessage
            user = getattr(ctx, "pending_user", None)
            if user is None or ctx.session is not None:
                return error_response("unexpected password message",
                                      "08P01")
            password = payload.rstrip(b"\x00").decode(
                "utf-8", "surrogateescape")
            store = getattr(self.cluster, "auth_store", None)
            if store is None or not store().check_login(user, password):
                return error_response(
                    f'password authentication failed for user "{user}"',
                    "28P01")
            ctx.session = PgProcessor(self.cluster)
            ctx.session.login_role = user
            return auth_ok() + self._session_ready()
        if ctx.session is None and kind in "QPBDECHS":
            return error_response("not authenticated", "28000") \
                + ready_for_query()
        if kind == "Q":
            return self._query(ctx, payload)
        if kind in "PBDECH":
            if ctx.skip_until_sync:
                return b""  # discard until Sync after an error
            try:
                return self._extended(ctx, kind, payload)
            except Exception as e:  # noqa: BLE001 — protocol error reply
                ctx.skip_until_sync = True
                code = {  # same mapping as the simple-query path
                    "InvalidArgument": "42601", "AlreadyPresent": "23505",
                    "NotFound": "42P01", "SerializationFailure": "40001",
                    "FailedTransaction": "25P02",
                }.get(type(e).__name__, "XX000")
                return error_response(str(e), code)
        if kind == "S":  # Sync
            ctx.skip_until_sync = False
            st = b"I"
            if ctx.session is not None and ctx.session.in_txn:
                st = ctx.session.txn_status.encode()
            return ready_for_query(st)
        if kind == "X":
            return b""  # client closes after Terminate
        st = b"I"
        if ctx.session is not None and ctx.session.in_txn:
            st = ctx.session.txn_status.encode()
        return error_response(f"unsupported message {kind!r}",
                              code="0A000") + ready_for_query(st)

    # -- extended query protocol --------------------------------------------
    @staticmethod
    def _cstr(payload: bytes, pos: int) -> tuple[str, int]:
        end = payload.index(b"\x00", pos)
        return payload[pos:end].decode("utf-8", "surrogateescape"), end + 1

    def _extended(self, ctx, kind: str, payload: bytes) -> bytes:
        from yugabyte_db_tpu.yql.pgsql.parser import parse_script

        if kind == "P":  # Parse: name, query, n param-type oids
            name, pos = self._cstr(payload, 0)
            query, pos = self._cstr(payload, pos)
            stmts = parse_script(query)
            if len(stmts) > 1:
                raise ValueError(
                    "cannot insert multiple commands into a prepared "
                    "statement")
            ctx.prepared[name] = stmts[0] if stmts else None
            return _msg(b"1", b"")  # ParseComplete
        if kind == "B":  # Bind: portal, stmt, formats, params, result fmts
            portal, pos = self._cstr(payload, 0)
            sname, pos = self._cstr(payload, pos)
            if sname not in ctx.prepared:
                raise ValueError(f"prepared statement {sname!r} "
                                 "does not exist")
            (nfmt,) = struct.unpack_from(">H", payload, pos)
            pos += 2
            fmts = struct.unpack_from(f">{nfmt}H", payload, pos)
            pos += 2 * nfmt
            (nparams,) = struct.unpack_from(">H", payload, pos)
            pos += 2
            params = []
            for i in range(nparams):
                (ln,) = struct.unpack_from(">i", payload, pos)
                pos += 4
                if ln < 0:
                    params.append(None)
                    continue
                raw = payload[pos:pos + ln]
                pos += ln
                fmt = fmts[i] if i < nfmt else (fmts[0] if nfmt else 0)
                if fmt != 0:
                    raise ValueError(
                        "binary parameter format is not supported")
                params.append(raw.decode("utf-8", "surrogateescape"))
            ctx.portals[portal] = {"stmt": ctx.prepared[sname],
                                   "params": params, "result": None,
                                   "done": False}
            return _msg(b"2", b"")  # BindComplete
        if kind == "D":  # Describe
            target = chr(payload[0])
            name, _pos = self._cstr(payload, 1)
            if target == "S":
                if name not in ctx.prepared:
                    raise ValueError(f"prepared statement {name!r} "
                                     "does not exist")
                # Unspecified param types (text); result shape resolves
                # at portal describe/execute time.
                return _msg(b"t", struct.pack(">H", 0)) + _msg(b"n", b"")
            p = ctx.portals.get(name)
            if p is None:
                raise ValueError(f"portal {name!r} does not exist")
            self._run_portal(ctx, p)
            res = p["result"]
            if res is None or not res.columns:
                return _msg(b"n", b"")  # NoData
            return row_description(res)
        if kind == "E":  # Execute: portal, max rows (0 = all)
            name, pos = self._cstr(payload, 0)
            p = ctx.portals.get(name)
            if p is None:
                raise ValueError(f"portal {name!r} does not exist")
            self._run_portal(ctx, p)
            res = p["result"]
            out = bytearray()
            if res is None:
                out += command_complete("OK")
            else:
                for r in res.rows:
                    out += data_row(r)
                if res.command.startswith(("SELECT", "select")) \
                        or res.columns:
                    out += command_complete(f"SELECT {len(res.rows)}")
                else:
                    out += command_complete(res.command)
            return bytes(out)
        if kind == "C":  # Close statement/portal
            target = chr(payload[0])
            name, _pos = self._cstr(payload, 1)
            (ctx.prepared if target == "S" else ctx.portals).pop(name, None)
            return _msg(b"3", b"")  # CloseComplete
        # 'H' Flush: responses are written immediately; nothing buffered.
        return b""

    def _run_portal(self, ctx, p: dict) -> None:
        """Execute a bound portal once (Describe-portal triggers it so
        RowDescription reflects the real result shape; Execute reuses
        the cached result)."""
        if p["done"]:
            return
        p["result"] = (None if p["stmt"] is None
                       else ctx.session.execute(p["stmt"], p["params"]))
        p["done"] = True

    def _query(self, ctx, payload: bytes) -> bytes:
        from yugabyte_db_tpu.yql.pgsql.executor import (FailedTransaction,
                                                        SerializationFailure)

        session = ctx.session or PgProcessor(self.cluster)

        def txn_status() -> bytes:
            return session.txn_status.encode()

        sql = payload.rstrip(b"\x00").decode("utf-8", "replace")
        out = bytearray()
        try:
            stmts = parse_script(sql)
        except Exception as e:  # noqa: BLE001 - parse error to client
            return bytes(error_response(str(e), "42601")
                         + ready_for_query(txn_status()))
        if not stmts:
            return bytes(empty_query_response()
                         + ready_for_query(txn_status()))
        for stmt in stmts:
            try:
                res = session.execute(stmt)
            except SerializationFailure as e:
                out += error_response(str(e), "40001")
                break
            except FailedTransaction as e:
                out += error_response(str(e), "25P02")
                break
            except InvalidArgument as e:
                out += error_response(str(e), "42601")
                break
            except AlreadyPresent as e:
                out += error_response(str(e), "23505")
                break
            except NotFound as e:
                out += error_response(str(e), "42P01")
                break
            except Exception as e:  # noqa: BLE001
                out += error_response(str(e))
                break
            if res is None:
                out += command_complete("OK")
            elif res.command.startswith(("SELECT", "select")) or res.columns:
                out += row_description(res)
                for r in res.rows:
                    out += data_row(r)
                out += command_complete(f"SELECT {len(res.rows)}")
            else:
                out += command_complete(res.command)
        out += ready_for_query(txn_status())
        return bytes(out)


class PgServer:
    """The YSQL frontend daemon: a Messenger listener with the PG
    connection context (the reference shape: tserver spawns the SQL
    frontend on port 5433)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.service = PgServiceImpl(cluster)
        self.messenger = Messenger("pg-server")

    def listen(self, host: str = "127.0.0.1", port: int = 0):
        return self.messenger.listen(host, port, self.service.handle,
                                     context_factory=PgConnectionContext)

    def shutdown(self) -> None:
        self.messenger.shutdown()
