"""PgProcessor: parse -> plan -> execute SQL against the cluster seam.

Reference analog: the YSQL execution stack — the PostgreSQL executor's
foreign-scan path (ybc_fdw.c:364 ybcIterateForeignScan) feeding
PgsqlReadOperation with WHERE pushdown and per-tablet partial aggregates
(src/yb/docdb/pgsql_operation.cc:345,473), and the DML path through
PgDocWriteOp (src/yb/yql/pggate/pg_doc_op.h:142). Here the planner
lowers SELECT straight to ScanSpecs on the shared Cluster seam (the
same LocalCluster / ClientCluster objects the CQL processor drives),
with grouped/expression aggregates pushed down to the storage engine —
on the TPU engine that is one device dispatch per tablet (ops.group_agg)
— and per-tablet partials combined above the scan (operations.py).

SQL semantic notes (vs the CQL processor):
- INSERT enforces primary-key uniqueness (PG errors on duplicates;
  CQL upserts).
- UPDATE/DELETE accept arbitrary WHERE: non-PK predicates resolve via a
  predicate-pushdown scan, then write per matching row.
- avg() lowers to sum+count partials and is derived after the combine
  (partial averages cannot be merged across tablets).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import expr as X
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.storage.scan_spec import AggSpec, Predicate, ScanSpec
from yugabyte_db_tpu.utils.status import AlreadyPresent, InvalidArgument
from yugabyte_db_tpu.yql.pgsql import ast
from yugabyte_db_tpu.yql.pgsql.operations import combine_grouped
from yugabyte_db_tpu.yql.pgsql.parser import parse_statement


class SerializationFailure(Exception):
    """Transaction conflict/abort (PG error code 40001): retry it."""


class FailedTransaction(Exception):
    """Statement issued inside an aborted block (PG code 25P02)."""


@dataclass
class PgResult:
    """Rows returned to the driver (the wire server turns this into
    RowDescription + DataRow messages)."""

    columns: list[str] = field(default_factory=list)
    rows: list[tuple] = field(default_factory=list)
    command: str = "SELECT"    # CommandComplete tag prefix

    def __iter__(self):
        return iter(self.rows)

    def dicts(self) -> list[dict]:
        return [dict(zip(self.columns, r)) for r in self.rows]


class PgProcessor:
    """One SQL session over a Cluster seam.

    Transactions (BEGIN/COMMIT/ROLLBACK) run on the distributed seam's
    TransactionManager: DML inside a transaction buffers intents through
    a YBTransaction (snapshot isolation, first-committer-wins conflicts
    surfaced as 40001); point SELECTs read-your-writes, range SELECTs
    read the transaction's snapshot (own uncommitted writes are not
    merged into range scans — the documented client-txn contract)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._txn = None
        self._txn_failed = False  # aborted block awaiting COMMIT/ROLLBACK
        self._yb_tables: dict = {}
        self._currvals: dict[str, int] = {}  # per-session currval state

    @property
    def in_txn(self) -> bool:
        return self._txn is not None or self._txn_failed

    @property
    def txn_status(self) -> str:
        """The ReadyForQuery status byte: I idle, T in txn, E failed."""
        if self._txn_failed:
            return "E"
        return "T" if self._txn is not None else "I"

    # -- entry point -------------------------------------------------------
    def execute(self, sql, params: list | None = None) -> PgResult | None:
        stmt = parse_statement(sql) if isinstance(sql, str) else sql
        self._params = params or []
        if isinstance(stmt, ast.TxnControl):
            return self._exec_txn_control(stmt)
        if self._txn_failed:
            # PG 25P02: the block already failed; only COMMIT/ROLLBACK
            # (both of which roll back) end it
            raise FailedTransaction(
                "current transaction is aborted, commands ignored until "
                "end of transaction block")
        fn = {
            ast.CreateTable: self._exec_create_table,
            ast.DropTable: self._exec_drop_table,
            ast.AlterTable: self._exec_alter_table,
            ast.CreateIndex: self._exec_create_index,
            ast.DropIndex: self._exec_drop_index,
            ast.Insert: self._exec_insert,
            ast.Update: self._exec_update,
            ast.Delete: self._exec_delete,
            ast.Select: self._exec_query,
            ast.Union: self._exec_query,
            ast.CreateView: self._exec_create_view,
            ast.DropView: self._exec_drop_view,
            ast.CreateSequence: self._exec_create_sequence,
            ast.DropSequence: self._exec_drop_sequence,
        }[type(stmt)]
        try:
            return fn(stmt)
        except Exception:
            if self._txn is not None:
                # a failed statement aborts the whole block (PG
                # semantics): nothing from it may ever commit
                self._txn.abort()
                self._txn = None
                self._txn_failed = True
            raise

    # -- transactions ------------------------------------------------------
    def _exec_txn_control(self, stmt: ast.TxnControl):
        from yugabyte_db_tpu.txn.errors import (TransactionAborted,
                                                TransactionConflict)

        if stmt.kind == "begin":
            if self.in_txn:
                raise InvalidArgument(
                    "there is already a transaction in progress")
            mgr_fn = getattr(self.cluster, "transaction_manager", None)
            if mgr_fn is None:
                raise InvalidArgument(
                    "transactions require a distributed cluster")
            self._txn = mgr_fn().begin()
            return PgResult(command="BEGIN")
        if stmt.kind in ("savepoint", "rollback_to", "release"):
            if self._txn_failed:
                # Divergence from PG, stated plainly: a failed statement
                # aborts the WHOLE block here (statement-level
                # subtransactions are not implemented), so a savepoint
                # cannot resurrect it.
                raise FailedTransaction(
                    "current transaction is aborted (savepoints cannot "
                    "recover a failed block in this implementation)")
            if self._txn is None:
                raise InvalidArgument(
                    "SAVEPOINT can only be used in transaction blocks")
            if stmt.kind == "savepoint":
                self._txn.savepoint(stmt.name)
                return PgResult(command="SAVEPOINT")
            try:
                if stmt.kind == "rollback_to":
                    self._txn.rollback_to_savepoint(stmt.name)
                    return PgResult(command="ROLLBACK")
                self._txn.release_savepoint(stmt.name)
                return PgResult(command="RELEASE")
            except KeyError as e:
                raise InvalidArgument(str(e)) from None
        if self._txn_failed:
            # COMMIT of a failed block is a rollback (PG reports it so)
            self._txn_failed = False
            return PgResult(command="ROLLBACK")
        if self._txn is None:
            raise InvalidArgument("no transaction in progress")
        txn, self._txn = self._txn, None
        if stmt.kind == "rollback":
            txn.abort()
            return PgResult(command="ROLLBACK")
        try:
            txn.commit()
        except (TransactionConflict, TransactionAborted) as e:
            raise SerializationFailure(str(e)) from e
        return PgResult(command="COMMIT")

    def _yb_table(self, name: str):
        t = self._yb_tables.get(name)
        if t is None:
            t = self._yb_tables[name] = self.cluster.open_yb_table(name)
        return t

    def _read_ht(self, tablet) -> int:
        """The read point for scans: the txn snapshot inside a
        transaction, the tablet's safe time otherwise."""
        if self._txn is not None:
            return self._txn.read_ht
        return tablet.read_time().value

    # -- binding / coercion ------------------------------------------------
    def _resolve(self, value):
        if isinstance(value, ast.BindMarker):
            try:
                return self._params[value.index]
            except IndexError:
                raise InvalidArgument(
                    f"bind marker ${value.index + 1} has no value") from None
        if isinstance(value, ast.SeqFunc):
            return self._resolve_seq_func(value)
        return value

    def _coerce(self, col: ColumnSchema, value):
        from yugabyte_db_tpu.yql.common import coerce_value

        value = self._resolve(value)
        # PG-style input conversion: extended-protocol parameters arrive
        # as TEXT ('123'), and PG coerces string literals to the target
        # type; mirror that here (CQL stays strict in its own coercer).
        if isinstance(value, str):
            dt = col.dtype
            try:
                if dt.is_integer:
                    value = int(value)
                elif dt in (DataType.DOUBLE, DataType.FLOAT):
                    value = float(value)
                elif dt == DataType.BOOL:
                    low = value.lower()
                    if low in ("t", "true", "1", "on", "yes"):
                        value = True
                    elif low in ("f", "false", "0", "off", "no"):
                        value = False
                    else:
                        raise ValueError(value)
            except ValueError:
                raise InvalidArgument(
                    f"invalid input syntax for {dt.name}: {value!r}") \
                    from None
        return coerce_value(col, value)

    # -- DDL ---------------------------------------------------------------
    def _exec_create_table(self, stmt: ast.CreateTable):
        if stmt.name in self.cluster.tables:
            if stmt.if_not_exists:
                return None
            raise AlreadyPresent(f"relation {stmt.name} already exists")
        by_name = {c.name for c in stmt.columns}
        for k in stmt.hash_keys + stmt.range_keys:
            if k not in by_name:
                raise InvalidArgument(f"primary key column {k} not defined")
        cols = []
        for c in stmt.columns:
            if c.name in stmt.hash_keys:
                kind = ColumnKind.HASH
            elif c.name in stmt.range_keys:
                kind = ColumnKind.RANGE
            else:
                kind = ColumnKind.REGULAR
            if kind != ColumnKind.REGULAR and \
                    c.dtype in (DataType.FLOAT, DataType.DOUBLE):
                raise InvalidArgument(
                    f"floating-point column {c.name} cannot be a key")
            cols.append(ColumnSchema(c.name, c.dtype, kind,
                                     nullable=kind == ColumnKind.REGULAR))
        schema = Schema(cols, table_id=stmt.name)
        self.cluster.create_table(stmt.name, schema, stmt.num_tablets)
        self._yb_tables.pop(stmt.name, None)
        return PgResult(command="CREATE TABLE")

    def _exec_drop_table(self, stmt: ast.DropTable):
        from yugabyte_db_tpu.utils.status import NotFound

        try:
            self.cluster.drop_table(stmt.name)
        except NotFound:
            if not stmt.if_exists:
                raise
        self._yb_tables.pop(stmt.name, None)
        return PgResult(command="DROP TABLE")

    def _exec_alter_table(self, stmt: ast.AlterTable):
        """Schema evolution by stable column ids (ADD -> NULL for
        existing rows, DROP retires the id, RENAME touches no data)."""
        from yugabyte_db_tpu.yql.common import evolve_schema

        handle = self.cluster.table(stmt.name)
        self.cluster.alter_table(handle, evolve_schema(
            handle, stmt.action, stmt.column, stmt.dtype, stmt.new_name))
        self._yb_tables.pop(stmt.name, None)
        return PgResult(command="ALTER TABLE")

    def _exec_create_index(self, stmt: ast.CreateIndex):
        handle = self.cluster.table(stmt.table)
        if any(i["name"] == stmt.name
               for i in getattr(handle, "indexes", [])):
            if stmt.if_not_exists:
                return None
            raise AlreadyPresent(f"index {stmt.name} exists")
        if not handle.schema.has_column(stmt.column):
            raise InvalidArgument(f"unknown column {stmt.column}")
        if handle.schema.column(stmt.column).is_key:
            raise InvalidArgument(f"cannot index key column {stmt.column}")
        itable = self.cluster.create_index(handle, stmt.name, stmt.column)
        self._backfill_index(handle, stmt.column, itable)
        return PgResult(command="CREATE INDEX")

    def _backfill_index(self, handle, column: str, itable: str) -> None:
        """Populate the index from existing base rows (reference: the
        online index backfill job; here a scan + index-entry writes)."""
        from yugabyte_db_tpu.index import index_entry

        ih = self.cluster.table(itable)
        key_names = [c.name for c in handle.schema.key_columns]
        proj = key_names + [column]
        for tablet in handle.tablets:
            res = tablet.scan(ScanSpec(
                read_ht=tablet.read_time().value, projection=proj))
            for row in res.rows:
                value = row[-1]
                if value is None:
                    continue
                base_kv = dict(zip(key_names, row[:-1]))
                hc, rv = index_entry(ih.schema, value, base_kv)
                self.cluster.tablet_for_hash(ih, hc).write([rv])

    def _exec_drop_index(self, stmt: ast.DropIndex):
        from yugabyte_db_tpu.utils.status import NotFound

        for name in list(self.cluster.tables):
            try:
                handle = self.cluster.table(name)
            except NotFound:
                continue
            for idx in getattr(handle, "indexes", []):
                if idx["name"] == stmt.name:
                    self.cluster.drop_index(handle, stmt.name)
                    return PgResult(command="DROP INDEX")
        if not stmt.if_exists:
            raise NotFound(f"index {stmt.name} not found")
        return PgResult(command="DROP INDEX")

    # -- DML ---------------------------------------------------------------
    def _key_and_tablet(self, handle, key_values: dict):
        from yugabyte_db_tpu.yql.common import key_and_tablet

        return key_and_tablet(self.cluster, handle, key_values)

    def _write_row(self, handle, key_values: dict, key: bytes, tablet,
                   row: RowVersion, if_not_exists: bool = False) -> None:
        if getattr(handle, "indexes", None) and \
                getattr(self.cluster, "maintain_indexes", None):
            from yugabyte_db_tpu.index import normalize_index

            indexed_cids = set()
            for i in handle.indexes:
                ni = normalize_index(i)
                for cname in ni["columns"] + ni["include"]:
                    indexed_cids.add(handle.schema.column(cname).col_id)
            if row.tombstone or (indexed_cids & row.columns.keys()):
                # Conditional INSERT: the row must not exist, so the old
                # state is absent by contract — no tombstones. A later
                # duplicate rejection then leaves at most a stale extra
                # entry (base-verified away), never a removed one.
                old = (None if if_not_exists
                       else tablet.current_row_values(key))
                self.cluster.maintain_indexes(handle, key_values, old, row)
        tablet.write([row], if_not_exists=if_not_exists)

    def _exec_insert(self, stmt: ast.Insert):
        handle = self.cluster.table(stmt.table)
        schema = handle.schema
        for cname in stmt.columns:
            if not schema.has_column(cname):
                raise InvalidArgument(f"unknown column {cname}")
        n = 0
        for values in stmt.rows:
            provided = dict(zip(stmt.columns, values))
            key_values, columns = {}, {}
            for c in schema.key_columns:
                v = (self._coerce(c, provided[c.name])
                     if c.name in provided else None)
                if v is None:  # checked AFTER bind resolution: $N may be None
                    raise InvalidArgument(
                        f"null value in column {c.name} violates "
                        f"not-null constraint")
                key_values[c.name] = v
            for c in schema.value_columns:
                if c.name in provided:
                    columns[c.col_id] = self._coerce(c, provided[c.name])
            if self._txn is not None:
                # Uniqueness inside a txn: read-your-writes existence
                # check; overlapping inserts from OTHER txns resolve at
                # the intent level (first-committer-wins).
                yt = self._yb_table(stmt.table)
                if self._txn.get(yt, key_values) is not None:
                    raise AlreadyPresent(
                        "duplicate key value violates unique constraint")
                vals = dict(key_values)
                vals.update({c.name: columns[c.col_id]
                             for c in schema.value_columns
                             if c.col_id in columns})
                self._txn.insert(yt, vals)
                n += 1
                continue
            key, tablet = self._key_and_tablet(handle, key_values)
            # PG semantics: duplicate key is an error (23505), not an
            # upsert. The check is ATOMIC with the write — it runs on the
            # tablet under the same lock as the apply (Tablet.write
            # if_not_exists / the tserver's intent-admission lock).
            self._write_row(handle, key_values, key, tablet, RowVersion(
                key, ht=0, liveness=True, columns=columns),
                if_not_exists=True)
            n += 1
        return PgResult(command=f"INSERT 0 {n}")

    def _match_rows(self, handle, where: list[ast.Rel]):
        """Resolve WHERE to (key_values, row-dict) pairs. Full-PK equality
        short-circuits to a point read; anything else scans with
        predicate pushdown."""
        schema = handle.schema
        where, ok = self._fold_exists(where)
        if not ok:
            return []
        key_names = [c.name for c in schema.key_columns]
        eq = {r.column: r.value for r in where if r.op == "="}
        if set(key_names) <= set(eq) and len(where) == len(key_names):
            kv = {n: self._coerce(schema.column(n), eq[n])
                  for n in key_names}
            if self._txn is not None:
                got = self._txn_point_get(handle, kv)
                return [] if got is None else [got]
            key, tablet = self._key_and_tablet(handle, kv)
            res = tablet.scan(ScanSpec(
                lower=key, upper=key + b"\x00",
                read_ht=self._read_ht(tablet), projection=None))
            return [(kv, dict(zip(res.columns, r))) for r in res.rows]
        preds = self._predicates(schema, where)
        out = []
        for tablet in handle.tablets:
            res = tablet.scan(ScanSpec(
                read_ht=self._read_ht(tablet), predicates=preds))
            for r in res.rows:
                d = dict(zip(res.columns, r))
                out.append(({n: d[n] for n in key_names}, d))
        if self._txn is not None:
            out = self._overlay_own_writes(handle, preds, out)
        return out

    def _txn_point_get(self, handle, kv):
        """Point resolution inside a txn: read-your-writes (own buffered
        and flushed intents overlay the committed snapshot). Returns
        (kv, row-dict) or None."""
        row = self._txn.get(self._yb_table(handle.name), kv)
        if row is None:
            return None
        names = [c.name for c in handle.schema.columns]
        return (kv, dict(zip(names, row)))

    def _overlay_own_writes(self, handle, preds, snapshot_rows):
        """Statements inside a transaction must see earlier statements'
        effects: merge the txn's own buffered writes over the snapshot
        match set (replace matched rows, drop tombstoned ones, add newly
        inserted ones that match the predicates)."""
        from yugabyte_db_tpu.models.encoding import decode_doc_key
        from yugabyte_db_tpu.models.partition import compute_hash_code

        schema = handle.schema
        key_names = [c.name for c in schema.key_columns]
        own = self._txn.own_rows(self._yb_table(handle.name))
        if not own:
            return snapshot_rows
        by_id = {c.col_id: c.name for c in schema.value_columns}
        out = []
        seen = set()
        for kv, d in snapshot_rows:
            key = schema.encode_primary_key(
                kv, compute_hash_code(schema, kv))
            row = own.get(key)
            if row is None:
                out.append((kv, d))
                continue
            seen.add(key)
            if row.tombstone:
                continue
            merged = dict(d)
            for cid, v in row.columns.items():
                if cid in by_id:
                    merged[by_id[cid]] = v
            if all(p.matches(merged.get(p.column)) for p in preds):
                out.append((kv, merged))
        for key, row in own.items():
            if key in seen or row.tombstone:
                continue
            _, hashed, ranges = decode_doc_key(key)
            kv = dict(zip(key_names, hashed + ranges))
            # full state (committed base + own overlay) via the point
            # get — the snapshot row may exist but have been excluded by
            # the pre-overlay predicate values, and building from only
            # the buffered columns would invent NULLs
            got = self._txn_point_get(handle, kv)
            if got is None:
                continue
            d = got[1]
            if all(p.matches(d.get(p.column)) for p in preds):
                out.append((kv, d))
        return out

    def _resolve_subquery(self, rel: ast.Rel) -> ast.Rel:
        """Execute an uncorrelated subquery used as a WHERE value.
        Scalar NULL / empty results lower to the never-matching IN ()
        (PG: comparison with NULL selects no rows, not an error)."""
        res = self._exec_select(rel.value.select)
        if len(res.columns) != 1:
            raise InvalidArgument("subquery must return a single column")
        if rel.op == "IN":
            # NULL elements can never satisfy '=' — drop them.
            vals = tuple(r[0] for r in res.rows if r[0] is not None)
            return ast.Rel(rel.column, "IN", vals)
        if len(res.rows) > 1:
            raise InvalidArgument(
                "more than one row returned by a subquery used as "
                "an expression")
        v = res.rows[0][0] if res.rows else None
        if v is None:
            return ast.Rel(rel.column, "IN", ())
        return ast.Rel(rel.column, rel.op, v)

    def _resolved_where(self, where: list[ast.Rel]) -> list[ast.Rel]:
        return [self._resolve_subquery(r)
                if isinstance(r.value, ast.SubQuery)
                and r.op not in ("EXISTS", "NOT EXISTS") else r
                for r in where]

    def _fold_exists(self, where: list[ast.Rel]):
        """Evaluate uncorrelated [NOT] EXISTS conjuncts once; returns
        (remaining_rels, ok) — ok False means no row can match. Used by
        paths without per-row subplan support (aggregates, UPDATE /
        DELETE); the row-select path runs EXISTS per row instead."""
        out, ok = [], True
        for rel in where:
            if rel.op in ("EXISTS", "NOT EXISTS"):
                try:
                    res = self._exec_query(rel.value.select)
                except InvalidArgument as e:
                    # Only an unresolvable outer-column reference means
                    # the subquery is correlated; a typo'd table or
                    # column inside the subquery must surface as-is.
                    msg = str(e)
                    if ("cannot be used as a comparison value" in msg
                            or "unknown table alias" in msg):
                        raise InvalidArgument(
                            "correlated [NOT] EXISTS is supported only "
                            "in a single-table SELECT WHERE clause "
                            f"({e})") from e
                    raise
                if bool(res.rows) != (rel.op == "EXISTS"):
                    ok = False
                continue
            out.append(rel)
        return out, ok

    def _predicates(self, schema: Schema, where: list[ast.Rel]):
        preds = []
        for rel in where:
            if rel.op in ("EXISTS", "NOT EXISTS"):
                raise InvalidArgument(
                    "EXISTS is not supported in this clause")
            if isinstance(rel.value, ast.SubQuery):
                rel = self._resolve_subquery(rel)
            if isinstance(rel.value, X.Col):
                raise InvalidArgument(
                    f"column reference {rel.value.name} cannot be used "
                    f"as a comparison value in this clause")
            if not schema.has_column(rel.column):
                raise InvalidArgument(f"unknown column {rel.column}")
            col = schema.column(rel.column)
            if rel.op == "IN":
                vals = tuple(self._coerce(col, v)
                             for v in self._resolve(rel.value))
                preds.append(Predicate(rel.column, "IN", vals))
            else:
                preds.append(Predicate(rel.column, rel.op,
                                       self._coerce(col, rel.value)))
        return preds

    def _exec_update(self, stmt: ast.Update):
        handle = self.cluster.table(stmt.table)
        schema = handle.schema
        sets = []
        for cname, rhs in stmt.assignments:
            if not schema.has_column(cname):
                raise InvalidArgument(f"unknown column {cname}")
            col = schema.column(cname)
            if col.is_key:
                raise InvalidArgument(f"cannot SET key column {cname}")
            sets.append((col, rhs))
        n = 0
        for kv, old in self._match_rows(handle, stmt.where):
            set_values = {}
            for col, rhs in sets:
                if isinstance(rhs, (X.Col, X.Const, X.BinOp)):
                    v = X.eval_expr(rhs, lambda name: old.get(name))
                    if col.dtype in (DataType.DOUBLE, DataType.FLOAT) \
                            and isinstance(v, int):
                        v = float(v)
                    set_values[col.name] = v
                else:
                    set_values[col.name] = self._coerce(col, rhs)
            if self._txn is not None:
                self._txn.update(self._yb_table(stmt.table), kv,
                                 set_values)
                n += 1
                continue
            columns = {handle.schema.column(nm).col_id: v
                       for nm, v in set_values.items()}
            key, tablet = self._key_and_tablet(handle, kv)
            self._write_row(handle, kv, key, tablet,
                            RowVersion(key, ht=0, columns=columns))
            n += 1
        return PgResult(command=f"UPDATE {n}")

    def _exec_delete(self, stmt: ast.Delete):
        handle = self.cluster.table(stmt.table)
        n = 0
        for kv, _old in self._match_rows(handle, stmt.where):
            if self._txn is not None:
                self._txn.delete_row(self._yb_table(stmt.table), kv)
                n += 1
                continue
            key, tablet = self._key_and_tablet(handle, kv)
            self._write_row(handle, kv, key, tablet,
                            RowVersion(key, ht=0, tombstone=True))
            n += 1
        return PgResult(command=f"DELETE {n}")

    # -- SELECT ------------------------------------------------------------
    # -- views / sequences --------------------------------------------------
    def _exec_create_view(self, stmt):
        from yugabyte_db_tpu.utils.status import AlreadyPresent

        try:
            self.cluster.create_view(stmt.name, stmt.query_sql,
                                     stmt.replace)
        except AlreadyPresent:
            raise InvalidArgument(f"view {stmt.name} exists") from None
        return PgResult(command="CREATE VIEW")

    def _exec_drop_view(self, stmt):
        from yugabyte_db_tpu.utils.status import NotFound

        try:
            self.cluster.drop_view(stmt.name)
        except NotFound:
            if not stmt.if_exists:
                raise InvalidArgument(
                    f"view {stmt.name} does not exist") from None
        return PgResult(command="DROP VIEW")

    def _exec_create_sequence(self, stmt):
        from yugabyte_db_tpu.utils.status import AlreadyPresent

        try:
            self.cluster.create_sequence(stmt.name)
        except AlreadyPresent:
            if not stmt.if_not_exists:
                raise InvalidArgument(
                    f"sequence {stmt.name} exists") from None
        return PgResult(command="CREATE SEQUENCE")

    def _exec_drop_sequence(self, stmt):
        from yugabyte_db_tpu.utils.status import NotFound

        try:
            self.cluster.drop_sequence(stmt.name)
        except NotFound:
            if not stmt.if_exists:
                raise InvalidArgument(
                    f"sequence {stmt.name} does not exist") from None
        return PgResult(command="DROP SEQUENCE")

    def _resolve_seq_func(self, f):
        if f.kind == "nextval":
            from yugabyte_db_tpu.utils.status import NotFound

            try:
                v = self.cluster.sequence_next(f.sequence)
            except NotFound:
                raise InvalidArgument(
                    f"sequence {f.sequence} does not exist") from None
            self._currvals[f.sequence] = v
            return v
        v = self._currvals.get(f.sequence)
        if v is None:
            raise InvalidArgument(
                f"currval of sequence {f.sequence} is not yet defined "
                "in this session")
        return v

    def _view_sql(self, name: str):
        """The defining query if ``name`` is a view. Local registries
        answer from memory; the distributed seam is consulted only when
        the name is not a known TABLE (so the read hot path never pays
        a master round trip for plain tables)."""
        if not hasattr(self.cluster, "get_view"):
            return None
        views = getattr(self.cluster, "views", None)
        if views is not None:  # in-process registry: free lookup
            return views.get(name)
        if name in self._yb_tables:
            return None
        try:
            self._yb_table(name)
            return None        # a real table
        except Exception:      # noqa: BLE001 — unknown name: try views
            return self.cluster.get_view(name)

    def _select_from_view(self, stmt: ast.Select, view_sql: str):
        """A SELECT whose FROM names a view: run the stored defining
        query, then evaluate the outer query over its rows in memory
        (views inside JOINs are not supported yet)."""
        if stmt.joins:
            raise InvalidArgument("views cannot be joined yet")
        self._view_depth = getattr(self, "_view_depth", 0) + 1
        try:
            if self._view_depth > 8:
                raise InvalidArgument(
                    "view nesting too deep (cyclic definition?)")
            inner = self._exec_query(parse_statement(view_sql))
        finally:
            self._view_depth -= 1
        return self._select_over_rows(stmt, inner.columns, inner.rows)

    def _select_over_rows(self, stmt: ast.Select, columns: list[str],
                          in_rows: list[tuple]) -> PgResult:
        """Evaluate a SELECT over an in-memory relation (view result or
        CTE): WHERE (incl. subquery values), expression/function items,
        aggregates + GROUP BY + HAVING, DISTINCT, ORDER BY,
        LIMIT/OFFSET — the executor work stock PG runs over a
        tuplestore scan (nodeCtescan.c / nodeSubqueryscan.c)."""
        prefix = (stmt.alias + ".") if stmt.alias else None
        dicts = []
        for r in in_rows:
            d = dict(zip(columns, r))
            if prefix:
                for c, v in zip(columns, r):
                    d[prefix + c] = v
            dicts.append(d)
        known = set(columns) | ({prefix + c for c in columns}
                                if prefix else set())
        for rel in self._resolved_where(stmt.where):
            if rel.op in ("EXISTS", "NOT EXISTS"):
                # Uncorrelated over an in-memory relation: one execution
                # decides the whole conjunct.
                res = self._exec_query(rel.value.select)
                if bool(res.rows) != (rel.op == "EXISTS"):
                    dicts = []
                continue
            if rel.column not in known:
                raise InvalidArgument(
                    f"column {rel.column} is not in the relation")
            val = self._resolve(rel.value)
            if isinstance(val, X.Col):
                if val.name not in known:
                    raise InvalidArgument(
                        f"column {val.name} is not in the relation")
                op = rel.op
                dicts = [d for d in dicts
                         if self._cmp(op, d.get(rel.column),
                                      d.get(val.name))]
                continue
            p = Predicate(rel.column, rel.op,
                          tuple(val) if rel.op == "IN" else val)
            dicts = [d for d in dicts if p.matches(d.get(p.column))]
        names, exprs = [], []
        for it in stmt.items:
            if it.expr == "*":
                names.extend(columns)
                exprs.extend(X.Col(c) for c in columns)
                continue
            if isinstance(it.expr, ast.Agg):
                arg = it.expr.arg
                names.append(it.alias or
                             f"{it.expr.fn}({'*' if arg is None else '...'})")
            elif isinstance(it.expr, X.Col):
                names.append(it.alias or it.expr.name.split(".")[-1])
            else:
                names.append(it.alias or "?column?")
            exprs.append(it.expr)
        for e in exprs:
            for c in self._item_columns(e):
                if c not in known:
                    raise InvalidArgument(
                        f"column {c} is not in the relation")
        has_agg = (stmt.group_by
                   or any(isinstance(e, ast.Agg) for e in exprs)
                   or any(isinstance(h.expr, ast.Agg)
                          for h in stmt.having))
        limit = self._limit(stmt)
        if has_agg:
            rows = self._host_aggregate(stmt, dicts, exprs)
            if stmt.distinct:
                rows = list(dict.fromkeys(rows))
            rows = self._order_and_limit(stmt, names, rows, limit)
            return PgResult(columns=names, rows=rows)
        hidden = 0
        for ob in stmt.order_by:
            if ob.column not in names and ob.column in known:
                names.append(ob.column)
                exprs.append(X.Col(ob.column))
                hidden += 1
        rows = [tuple(self._eval_item(e, d) for e in exprs)
                for d in dicts]
        return self._dedup_order_trim(stmt, names, rows, limit, hidden)

    def _select_window(self, stmt: ast.Select) -> PgResult:
        """SELECT with window-function items. Rewrite as a two-stage
        plan: fetch the full relation (base table / view / CTE / join —
        the inner SELECT reuses every existing path), then evaluate
        windows host-side and project — the split stock PG's planner
        makes between the scan below and WindowAgg above the FDW
        (reference capability:
        src/postgres/src/backend/executor/nodeWindowAgg.c)."""
        import dataclasses as _dc

        if (stmt.group_by or stmt.having
                or any(isinstance(it.expr, ast.Agg) for it in stmt.items)):
            raise InvalidArgument(
                "window functions cannot be combined with GROUP BY or "
                "plain aggregates")
        if stmt.table is None:
            # FROM-less window (PG: SELECT row_number() OVER () -> 1):
            # the relation is one empty row.
            dicts, star, known = [{}], [], set()
        elif stmt.joins:
            dicts, tables, handles, _q, owners = self._join_rows(stmt)
            star = [f"{a}.{c.name}" for a, _t in tables
                    for c in handles[a].schema.columns]
            known = set(star) | {n for n, als in owners.items()
                                 if len(als) == 1}
        else:
            stmt = self._strip_qualifiers(stmt)
            inner = _dc.replace(stmt, items=[ast.SelectItem("*")],
                                order_by=[], limit=None, offset=None,
                                distinct=False)
            base = self._exec_select(inner)
            star = list(base.columns)
            dicts = [dict(zip(star, r)) for r in base.rows]
            known = set(star)
        for it in stmt.items:
            if it.expr == "*":
                continue
            for c in self._item_columns(it.expr):
                if c not in known:
                    raise InvalidArgument(
                        f"column {c} is not in the relation")
        names: list[str] = []
        series: list[list] = []
        for it in stmt.items:
            e = it.expr
            if e == "*":
                for c in star:
                    names.append(c.split(".")[-1])
                    series.append([d.get(c) for d in dicts])
                continue
            if isinstance(e, ast.WindowFunc):
                names.append(it.alias or e.fn)
                series.append(self._eval_window(e, dicts))
            else:
                if isinstance(e, X.Col):
                    names.append(it.alias or e.name.split(".")[-1])
                else:
                    names.append(it.alias or "?column?")
                series.append([self._eval_item(e, d) for d in dicts])
        # Hidden ORDER BY columns (may reference non-projected columns;
        # PG allows this for non-DISTINCT selects).
        hidden = 0
        for ob in stmt.order_by:
            if ob.column not in names and ob.column in known:
                names.append(ob.column)
                series.append([d.get(ob.column) for d in dicts])
                hidden += 1
        rows = [tuple(s[i] for s in series) for i in range(len(dicts))]
        return self._dedup_order_trim(stmt, names, rows,
                                      self._limit(stmt), hidden)

    def _eval_window(self, wf: ast.WindowFunc, dicts: list[dict]) -> list:
        """One window function over the relation: returns a value per
        input row (input order preserved by the caller). Aggregate
        windows with ORDER BY use PG's default frame — RANGE UNBOUNDED
        PRECEDING .. CURRENT ROW — so order-key peers share the running
        value; without ORDER BY the frame is the whole partition."""
        for c in wf.partition_by + [ob.column for ob in wf.order_by]:
            if dicts and c not in dicts[0]:
                raise InvalidArgument(
                    f"column {c} is not in the relation")
        off = self._resolve(wf.offset)
        default = self._resolve(wf.default)
        if wf.fn in ("lag", "lead") and (not isinstance(off, int)
                                         or isinstance(off, bool)
                                         or off < 0):
            raise InvalidArgument(f"{wf.fn} offset must be a "
                                  "non-negative integer")
        parts: dict[tuple, list[int]] = {}
        for i, d in enumerate(dicts):
            parts.setdefault(tuple(d.get(c) for c in wf.partition_by),
                             []).append(i)
        out: list = [None] * len(dicts)
        for order in parts.values():
            order = list(order)  # stable within equal order keys
            for ob in reversed(wf.order_by):
                order.sort(key=lambda i, c=ob.column:
                           ((dicts[i].get(c) is None), dicts[i].get(c)),
                           reverse=ob.desc)
            okeys = [tuple(dicts[i].get(ob.column) for ob in wf.order_by)
                     for i in order]
            fn = wf.fn
            if fn == "row_number":
                for pos, i in enumerate(order):
                    out[i] = pos + 1
            elif fn in ("rank", "dense_rank"):
                rank = dense = 0
                prev: object = object()
                for pos, i in enumerate(order):
                    if okeys[pos] != prev:
                        rank, prev = pos + 1, okeys[pos]
                        dense += 1
                    out[i] = rank if fn == "rank" else dense
            elif fn in ("lag", "lead"):
                vals = [self._eval_item(wf.arg, dicts[i]) for i in order]
                step = off if fn == "lag" else -off
                for pos, i in enumerate(order):
                    j = pos - step
                    out[i] = (vals[j] if 0 <= j < len(vals)
                              else default)
            else:  # sum/count/avg/min/max over the frame
                star = wf.arg is None
                args = ([None] * len(order) if star else
                        [self._eval_item(wf.arg, dicts[i])
                         for i in order])
                if not wf.order_by:
                    val = self._win_agg(fn, args, len(order), star)
                    for i in order:
                        out[i] = val
                else:
                    # Incremental accumulator: carry count/sum/min/max
                    # across peer-group boundaries (the frame only ever
                    # grows), O(n) per partition.
                    n_seen = cnt = 0
                    total = lo = hi = None
                    pos = 0
                    while pos < len(order):
                        end = pos
                        while end < len(order) and okeys[end] == okeys[pos]:
                            end += 1
                        n_seen = end
                        for v in args[pos:end]:
                            if v is None:
                                continue
                            cnt += 1
                            total = v if total is None else total + v
                            lo = v if lo is None or v < lo else lo
                            hi = v if hi is None or v > hi else hi
                        if fn == "count":
                            val = n_seen if star else cnt
                        elif cnt == 0:
                            val = None
                        elif fn == "sum":
                            val = total
                        elif fn == "avg":
                            val = total / cnt
                        elif fn == "min":
                            val = lo
                        elif fn == "max":
                            val = hi
                        else:
                            raise InvalidArgument(
                                f"unknown window aggregate {fn}")
                        for p in range(pos, end):
                            out[order[p]] = val
                        pos = end
        return out

    @staticmethod
    def _win_agg(fn: str, args: list, n_rows: int, star: bool):
        if fn == "count":
            return n_rows if star else sum(v is not None for v in args)
        vals = [v for v in args if v is not None]
        if not vals:
            return None
        if fn == "sum":
            return sum(vals)
        if fn == "avg":
            return sum(vals) / len(vals)
        if fn == "min":
            return min(vals)
        if fn == "max":
            return max(vals)
        raise InvalidArgument(f"unknown window aggregate {fn}")

    def _exec_query(self, stmt):
        """Dispatch a query statement (SELECT or UNION chain), handling
        a WITH clause once for both kinds: evaluate each CTE in order
        (PG materializes CTEs; later CTEs and the body see earlier
        names), scoped to this statement and restored after."""
        if getattr(stmt, "ctes", None):
            saved = dict(getattr(self, "_cte_results", {}) or {})
            self._cte_results = dict(saved)
            try:
                for name, sel in stmt.ctes:
                    self._cte_results[name] = self._exec_query(sel)
                import dataclasses as _dc

                return self._exec_query(_dc.replace(stmt, ctes=[]))
            finally:
                self._cte_results = saved
        if isinstance(stmt, ast.Union):
            return self._exec_union(stmt)
        return self._exec_select(stmt)

    def _exec_union(self, u: ast.Union) -> PgResult:
        """Set operations: evaluate each branch, require equal arity,
        combine per joint — UNION (dedup unless ALL), EXCEPT (dedup lhs
        minus rhs; ALL subtracts per-occurrence), INTERSECT (dedup
        both-sides; ALL keeps multiset minimum counts) — then apply the
        chain-level ORDER BY/LIMIT/OFFSET (the work stock PG's
        Append/SetOp nodes do above the FDW; reference capability:
        src/postgres/src/backend/executor/nodeSetOp.c)."""
        from collections import Counter

        results = [self._exec_query(b) for b in u.branches]
        n = len(results[0].columns)
        for r in results[1:]:
            if len(r.columns) != n:
                raise InvalidArgument(
                    "each query in a set operation must have the same "
                    "number of columns")
        kinds = u.kinds or ["union"] * len(u.alls)

        def hkey(v):
            # Canonical hashable view of a cell (jsonb rows carry
            # dicts/lists; PG supports them in set operations).
            if isinstance(v, dict):
                return ("\x00d", tuple(sorted(
                    (k, hkey(x)) for k, x in v.items())))
            if isinstance(v, (list, tuple)):
                return ("\x00l", tuple(hkey(x) for x in v))
            if isinstance(v, set):
                return ("\x00s", tuple(sorted(map(hkey, v),
                                              key=repr)))
            return v

        def rkey(row):
            return tuple(hkey(v) for v in row)

        def dedup(rows):
            seen = {}
            for t in rows:
                seen.setdefault(rkey(t), t)
            return list(seen.values())

        acc = list(results[0].rows)
        for r, is_all, kind in zip(results[1:], u.alls, kinds):
            rows = list(r.rows)
            if kind == "union":
                acc = ([*acc, *rows] if is_all
                       else dedup([*acc, *rows]))
            elif kind == "except":
                if is_all:
                    remove = Counter(map(rkey, rows))
                    out = []
                    for t in acc:
                        k = rkey(t)
                        if remove[k] > 0:
                            remove[k] -= 1
                        else:
                            out.append(t)
                    acc = out
                else:
                    right = set(map(rkey, rows))
                    acc = [t for t in dedup(acc)
                           if rkey(t) not in right]
            else:  # intersect
                if is_all:
                    counts = Counter(map(rkey, rows))
                    out = []
                    for t in acc:
                        k = rkey(t)
                        if counts[k] > 0:
                            counts[k] -= 1
                            out.append(t)
                    acc = out
                else:
                    right = set(map(rkey, rows))
                    acc = [t for t in dedup(acc) if rkey(t) in right]
        names = list(results[0].columns)
        shim = ast.Select(items=[], table=None, order_by=u.order_by,
                          limit=u.limit, offset=u.offset)
        rows = self._order_and_limit(shim, names, acc,
                                     self._limit(shim))
        return PgResult(columns=names, rows=rows)

    def _exec_select(self, stmt: ast.Select):
        if getattr(stmt, "ctes", None):
            # WITH rides the shared query dispatcher (CTE handling for
            # SELECT and UNION lives in one place).
            return self._exec_query(stmt)
        if any(isinstance(it.expr, ast.WindowFunc) for it in stmt.items):
            return self._select_window(stmt)
        cte = (getattr(self, "_cte_results", None) or {}).get(stmt.table)
        if cte is not None:
            if stmt.joins:
                raise InvalidArgument("CTEs cannot be joined yet")
            return self._select_over_rows(stmt, cte.columns, cte.rows)
        if stmt.table is None:
            # FROM-less SELECT: constant / sequence-function items.
            names, row = [], []
            from yugabyte_db_tpu.storage import expr as X

            for i, it in enumerate(stmt.items):
                e = it.expr
                if isinstance(e, ast.SeqFunc):
                    names.append(it.alias or e.kind)
                    row.append(self._resolve_seq_func(e))
                elif isinstance(e, X.Const):
                    names.append(it.alias or f"?column?")
                    row.append(e.value)
                else:
                    raise InvalidArgument(
                        "FROM-less SELECT supports constants and "
                        "sequence functions")
            return PgResult(columns=names, rows=[tuple(row)],
                            command="SELECT 1")
        view_sql = self._view_sql(stmt.table)
        if view_sql is not None:
            return self._select_from_view(stmt, view_sql)
        if not stmt.joins:
            from yugabyte_db_tpu.yql.pgsql import vtables as PV

            if PV.is_virtual(stmt.table):
                return PV.virtual_select(self, stmt)
        if stmt.joins:
            return self._select_join(stmt)
        stmt = self._strip_qualifiers(stmt)
        handle = self.cluster.table(stmt.table)
        schema = handle.schema
        has_agg = (any(isinstance(it.expr, ast.Agg) for it in stmt.items)
                   or any(isinstance(h.expr, ast.Agg) for h in stmt.having))
        if has_agg or stmt.group_by:
            return self._select_aggregate(handle, stmt)
        return self._select_rows(handle, stmt)

    def _strip_qualifiers(self, stmt: ast.Select) -> ast.Select:
        """Single-table SELECT: rewrite 'alias.col' refs to bare names
        (the storage seam knows bare columns only)."""
        alias = stmt.alias or stmt.table
        prefix = alias + "."

        def fix(name: str) -> str:
            if isinstance(name, str) and name.startswith(prefix):
                return name[len(prefix):]
            if isinstance(name, str) and "." in name:
                raise InvalidArgument(
                    f"unknown table alias in reference {name}")
            return name

        def fix_expr(e):
            if isinstance(e, X.Col):
                return X.Col(fix(e.name)) if "." in e.name else e
            if isinstance(e, X.BinOp):
                return X.BinOp(e.op, fix_expr(e.left), fix_expr(e.right))
            if isinstance(e, ast.JsonPath):
                return ast.JsonPath(fix(e.column), e.steps)
            if isinstance(e, ast.Agg):
                return ast.Agg(e.fn, None if e.arg is None
                               else fix_expr(e.arg))
            if isinstance(e, ast.WindowFunc):
                return ast.WindowFunc(
                    e.fn, None if e.arg is None else fix_expr(e.arg),
                    [fix(c) for c in e.partition_by],
                    [ast.OrderBy(fix(o.column), o.desc)
                     for o in e.order_by],
                    offset=e.offset, default=e.default)
            return e

        needs = (any(r.column and "." in r.column for r in stmt.where)
                 or any(isinstance(r.value, X.Col) and "." in r.value.name
                        for r in stmt.where)
                 or any("." in g for g in stmt.group_by)
                 or any("." in o.column for o in stmt.order_by))
        items = [ast.SelectItem(fix_expr(it.expr)
                                if it.expr != "*" else "*", it.alias)
                 for it in stmt.items]
        having = [ast.HavingRel(fix_expr(h.expr), h.op, h.value)
                  for h in stmt.having]
        if not needs and items == stmt.items and having == stmt.having:
            return stmt
        return ast.Select(
            items, stmt.table,
            [ast.Rel(fix(r.column), r.op,
                     X.Col(fix(r.value.name))
                     if isinstance(r.value, X.Col) else r.value)
             for r in stmt.where],
            [fix(g) for g in stmt.group_by],
            [ast.OrderBy(fix(o.column), o.desc) for o in stmt.order_by],
            stmt.limit, stmt.distinct, stmt.alias, [], having,
            offset=stmt.offset)

    # -- joins (above the storage seam; reference capability: the PG
    # executor's hash/merge joins over FDW scans, src/postgres executor) --
    def _select_join(self, stmt: ast.Select):
        joined, tables, handles, qualify, _owners = self._join_rows(stmt)
        return self._finish_select(stmt, joined, tables, handles, qualify)

    def _join_rows(self, stmt: ast.Select):
        """Produce the joined relation as dicts keyed by both qualified
        ('a.col') and unambiguous bare names. Returns (dicts, tables,
        handles, qualify, owners) for _finish_select / window
        evaluation; owners maps bare column name -> owning aliases (the
        single source of the bare-name-resolution rule)."""
        where_rels, exists_ok = self._fold_exists(stmt.where)
        if len(where_rels) != len(stmt.where):
            import dataclasses as _dc

            stmt = _dc.replace(stmt, where=where_rels)
        base_alias = stmt.alias or stmt.table
        tables = [(base_alias, stmt.table)]
        tables += [(j.alias or j.table, j.table) for j in stmt.joins]
        if len({a for a, _ in tables}) != len(tables):
            raise InvalidArgument("duplicate table alias in FROM")
        handles = {a: self.cluster.table(t) for a, t in tables}
        owners: dict[str, list[str]] = {}
        for a, _t in tables:
            for c in handles[a].schema.columns:
                owners.setdefault(c.name, []).append(a)

        def qualify(ref: str) -> tuple[str, str]:
            if "." in ref:
                a, c = ref.split(".", 1)
                if a not in handles:
                    raise InvalidArgument(f"unknown table alias {a}")
                if not handles[a].schema.has_column(c):
                    raise InvalidArgument(f"unknown column {ref}")
                return a, c
            als = owners.get(ref)
            if not als:
                raise InvalidArgument(f"unknown column {ref}")
            if len(als) > 1:
                raise InvalidArgument(
                    f"column reference {ref} is ambiguous")
            return als[0], ref

        # Resolve subqueries once; split WHERE into per-table pushdowns.
        where = self._resolved_where(stmt.where)
        per: dict[str, list[ast.Rel]] = {a: [] for a, _ in tables}
        for rel in where:
            a, c = qualify(rel.column)
            per[a].append(ast.Rel(c, rel.op, rel.value))

        rows_by_alias: dict[str, list[dict]] = {}
        for a, _tname in tables:
            h = handles[a]
            preds = self._predicates(h.schema, per[a])
            rows_by_alias[a] = [
                {f"{a}.{k}": v for k, v in d.items()}
                for d in self._scan_dicts(h, per[a], preds, None, None)]

        joined = rows_by_alias[base_alias]
        seen_aliases = {base_alias}
        for j, (a, _tname) in zip(stmt.joins, tables[1:]):
            lkeys, rkeys = [], []
            for lref, rref in j.on:
                la, lc = qualify(lref)
                ra, rc = qualify(rref)
                if ra != a:  # written right-to-left: flip
                    la, lc, ra, rc = ra, rc, la, lc
                if ra != a or la not in seen_aliases:
                    raise InvalidArgument(
                        f"ON must relate {a} to an earlier table")
                lkeys.append(f"{la}.{lc}")
                rkeys.append(f"{a}.{rc}")
            index: dict[tuple, list[dict]] = {}
            for d in rows_by_alias[a]:
                kt = tuple(d[k] for k in rkeys)
                if any(v is None for v in kt):
                    continue  # SQL: NULL never joins
                index.setdefault(kt, []).append(d)
            null_right = {f"{a}.{c.name}": None
                          for c in handles[a].schema.columns}
            null_left = {f"{la}.{c.name}": None
                         for la in seen_aliases
                         for c in handles[la].schema.columns}
            out = []
            matched_right: set[int] = set()
            for ld in joined:
                kt = tuple(ld[k] for k in lkeys)
                matches = (index.get(kt)
                           if not any(v is None for v in kt) else None)
                if matches:
                    for rd in matches:
                        m = dict(ld)
                        m.update(rd)
                        out.append(m)
                        if j.kind in ("right", "full"):
                            matched_right.add(id(rd))
                elif j.kind in ("left", "full"):
                    m = dict(ld)
                    m.update(null_right)
                    out.append(m)
            if j.kind in ("right", "full"):
                # Right side preserved: NULL-extend every column
                # accumulated so far for unmatched right rows (also
                # rows whose join key is NULL — they never match).
                for rd in rows_by_alias[a]:
                    if id(rd) not in matched_right:
                        m = dict(null_left)
                        m.update(rd)
                        out.append(m)
            joined = out
            seen_aliases.add(a)

        # Bare-name aliases for unambiguous columns (output resolution).
        bare = [(n, f"{als[0]}.{n}") for n, als in owners.items()
                if len(als) == 1]
        for d in joined:
            for n, qn in bare:
                d[n] = d[qn]

        # Re-verify WHERE post-join: predicates pushed below a LEFT JOIN's
        # right side must still filter NULL-extended rows (PG applies
        # WHERE after the join).
        if where and any(j.kind in ("left", "right", "full")
                         for j in stmt.joins):
            post = []
            for rel in where:
                a, c = qualify(rel.column)
                col = handles[a].schema.column(c)
                if rel.op == "IN":
                    val = tuple(self._coerce(col, v)
                                for v in self._resolve(rel.value))
                else:
                    val = self._coerce(col, rel.value)
                post.append(Predicate(f"{a}.{c}", rel.op, val))
            joined = [d for d in joined
                      if all(p.matches(d.get(p.column)) for p in post)]

        if not exists_ok:
            joined = []
        return joined, tables, handles, qualify, owners

    @classmethod
    def _eval_item(cls, expr, d: dict):
        """Evaluate one select-item expression over a row dict: scalar
        trees (Col/Const/BinOp with SQL NULL propagation), scalar
        function calls (ast.Func), jsonb paths — the expression work
        stock PG's executor does above the FDW."""
        if isinstance(expr, X.Col):
            return d.get(expr.name)
        if isinstance(expr, X.Const):
            return expr.value
        if isinstance(expr, X.BinOp):
            left = cls._eval_item(expr.left, d)
            right = cls._eval_item(expr.right, d)
            if left is None or right is None:
                return None
            return {"+": lambda: left + right,
                    "-": lambda: left - right,
                    "*": lambda: left * right}[expr.op]()
        if isinstance(expr, ast.Func):
            return cls._eval_func(expr.name,
                                  [cls._eval_item(a, d)
                                   for a in expr.args])
        if isinstance(expr, ast.JsonPath):
            import json

            v = d.get(expr.column)
            for op, key in expr.steps:
                if v is None:
                    return None
                if isinstance(v, dict):
                    v = v.get(key)
                elif isinstance(v, list) and isinstance(key, int) \
                        and -len(v) <= key < len(v):
                    v = v[key]
                else:
                    return None
                if op == "->>" and v is not None:
                    v = (json.dumps(v, separators=(",", ":"))
                         if isinstance(v, (dict, list)) else
                         ("true" if v is True else "false"
                          if v is False else str(v)))
            return v
        return X.eval_expr(expr, lambda n: d.get(n))

    @staticmethod
    def _eval_func(name: str, args: list):
        """SQL scalar-function semantics (PG behavior: NULL in -> NULL
        out except coalesce/concat/greatest/least/nullif)."""
        if name == "coalesce":
            return next((a for a in args if a is not None), None)
        if name == "nullif":
            a, b = args
            return None if a == b else a
        if name == "greatest":
            vals = [a for a in args if a is not None]
            return max(vals) if vals else None
        if name == "least":
            vals = [a for a in args if a is not None]
            return min(vals) if vals else None
        if name == "concat":  # PG concat() treats NULL as ''
            return "".join("" if a is None else
                           ("t" if a is True else "f") if isinstance(
                               a, bool) else str(a) for a in args)
        if any(a is None for a in args):
            return None
        if name == "abs":
            return abs(args[0])
        if name == "upper":
            return str(args[0]).upper()
        if name == "lower":
            return str(args[0]).lower()
        if name == "length":
            return len(str(args[0]))
        if name == "round":
            import math

            v = args[0]
            if len(args) == 2:
                # PG rounds halves away from zero (Python: to even).
                nd = int(args[1])
                if isinstance(v, int):
                    if nd >= 0:
                        return v
                    scale = 10 ** (-nd)
                    q = (abs(v) + scale // 2) // scale * scale
                    return -q if v < 0 else q
                scale = 10.0 ** nd
                scaled = v * scale
                r = (math.floor(scaled + 0.5) if scaled >= 0
                     else math.ceil(scaled - 0.5))
                return r / scale
            if isinstance(v, int):
                return v
            return float(math.floor(v + 0.5) if v >= 0
                         else math.ceil(v - 0.5))
        if name == "floor":
            import math

            return (args[0] if isinstance(args[0], int)
                    else float(math.floor(args[0])))
        if name in ("ceil", "ceiling"):
            import math

            return (args[0] if isinstance(args[0], int)
                    else float(math.ceil(args[0])))
        if name == "mod":
            a, b = args
            # PG mod() takes the dividend's sign (Python %: divisor's);
            # exact int arithmetic (math.fmod loses >2^53 precision).
            if isinstance(a, int) and isinstance(b, int):
                r = abs(a) % abs(b)
                return -r if a < 0 else r
            import math

            return math.fmod(a, b)
        if name in ("substring", "substr"):
            s = str(args[0])
            start = int(args[1])
            ln = int(args[2]) if len(args) > 2 else None
            # PG 1-based; start can be <= 0 (consumes into the length).
            if ln is None:
                return s[max(start - 1, 0):]
            end = start - 1 + ln
            return s[max(start - 1, 0):max(end, 0)]
        raise InvalidArgument(f"unknown function {name}")

    @classmethod
    def _item_columns(cls, expr) -> set:
        if isinstance(expr, ast.JsonPath):
            return {expr.column}
        if isinstance(expr, ast.Func):
            out: set = set()
            for a in expr.args:
                out |= cls._item_columns(a)
            return out
        if isinstance(expr, ast.Agg):
            return (cls._item_columns(expr.arg)
                    if expr.arg is not None else set())
        if isinstance(expr, ast.WindowFunc):
            out = (cls._item_columns(expr.arg)
                   if expr.arg is not None else set())
            out |= set(expr.partition_by)
            out |= {ob.column for ob in expr.order_by}
            return out
        if isinstance(expr, X.BinOp):
            return cls._item_columns(expr.left) | \
                cls._item_columns(expr.right)
        return X.columns_of(expr)

    def _outer_refs(self, sub: ast.Select, outer_schema,
                    outer_alias: str):
        """Outer-column references inside a subquery's WHERE: values
        spelled as column refs that resolve to the OUTER relation
        (qualified with its alias, or unqualified names the inner table
        lacks). Returns {ref_name: outer_column} or None when the
        subquery is uncorrelated."""
        try:
            inner_schema = (self.cluster.table(sub.table).schema
                            if sub.table else None)
        except Exception:  # noqa: BLE001 — CTE/view inner: treat plain
            inner_schema = None
        prefix = outer_alias + "."
        refs = {}
        for rel in sub.where:
            v = rel.value
            if not isinstance(v, X.Col):
                continue
            name = v.name
            if name.startswith(prefix):
                refs[name] = name[len(prefix):]
            elif "." not in name and inner_schema is not None \
                    and not inner_schema.has_column(name) \
                    and outer_schema.has_column(name):
                refs[name] = name
        return refs or None

    def _eval_correlated(self, rel: ast.Rel, refs: dict, d: dict,
                         cache: dict) -> bool:
        """One correlated-subquery conjunct against one outer row: bind
        the outer refs to the row's values, run the subquery (memoized
        on the binding tuple), compare (PG subplan semantics: NULL /
        empty scalar never matches; >1 scalar row errors)."""
        key = tuple(d.get(c) for c in refs.values())
        hit = cache.get(key)
        if hit is None:
            import dataclasses as _dc

            sub = rel.value.select
            new_where = []
            for r in sub.where:
                if isinstance(r.value, X.Col) and r.value.name in refs:
                    new_where.append(ast.Rel(
                        r.column, r.op, d.get(refs[r.value.name])))
                else:
                    new_where.append(r)
            res = self._exec_select(_dc.replace(sub, where=new_where))
            if rel.op not in ("EXISTS", "NOT EXISTS") \
                    and len(res.columns) != 1:
                raise InvalidArgument(
                    "subquery must return a single column")
            hit = cache[key] = [r[0] if r else None for r in res.rows]
        if rel.op in ("EXISTS", "NOT EXISTS"):
            return bool(hit) == (rel.op == "EXISTS")
        if rel.op == "IN":
            left = d.get(rel.column)
            return left is not None and any(
                left == v for v in hit if v is not None)
        if len(hit) > 1:
            raise InvalidArgument(
                "more than one row returned by a subquery used as "
                "an expression")
        v = hit[0] if hit else None
        return v is not None and self._cmp(rel.op, d.get(rel.column), v)

    def _select_rows(self, handle, stmt: ast.Select):
        schema = handle.schema
        outer_alias = stmt.alias or stmt.table
        plain, correlated, colcol = [], [], []
        for rel in stmt.where:
            if rel.op in ("EXISTS", "NOT EXISTS"):
                # Correlated or not, [NOT] EXISTS rides the per-row
                # subplan path (uncorrelated = one memoized execution
                # under the empty binding tuple).
                refs = self._outer_refs(rel.value.select, schema,
                                        outer_alias)
                correlated.append((rel, refs or {}, {}))
                continue
            if isinstance(rel.value, X.Col):
                for name in (rel.column, rel.value.name):
                    if not schema.has_column(name):
                        raise InvalidArgument(f"unknown column {name}")
                colcol.append(rel)  # col-vs-col: host filter
                continue
            refs = (self._outer_refs(rel.value.select, schema,
                                     outer_alias)
                    if isinstance(rel.value, ast.SubQuery) else None)
            if refs is not None:
                correlated.append((rel, refs, {}))
            else:
                plain.append(rel)
        if correlated or colcol:
            import dataclasses as _dc

            # Fetch candidates with the plain predicates pushed down,
            # then run each correlated subplan per outer row (memoized
            # per outer-binding tuple — PG's SubPlan rescan shape) and
            # col-vs-col filters, and finish projection/order/limit
            # over the survivors.
            preds = self._predicates(schema, plain)
            all_names = [c.name for c in schema.columns]
            survivors = []
            for d in self._scan_dicts(handle, plain, preds, all_names,
                                      None):
                if not all(self._cmp(r.op, d.get(r.column),
                                     d.get(r.value.name))
                           for r in colcol):
                    continue
                if all(self._eval_correlated(rel, refs, d, cache)
                       for rel, refs, cache in correlated):
                    survivors.append(tuple(d.get(c) for c in all_names))
            return self._select_over_rows(
                _dc.replace(stmt, where=[]), all_names, survivors)
        preds = self._predicates(schema, stmt.where)
        all_names = [c.name for c in schema.columns]
        names, exprs = [], []
        for it in stmt.items:
            if it.expr == "*":
                names.extend(all_names)
                exprs.extend(X.Col(n) for n in all_names)
                continue
            if isinstance(it.expr, X.Col):
                if not schema.has_column(it.expr.name):
                    raise InvalidArgument(f"unknown column {it.expr.name}")
                names.append(it.alias or it.expr.name)
            else:
                names.append(it.alias or "?column?")
            exprs.append(it.expr)
        # ORDER BY may reference table columns outside the select list
        # (PG semantics): carry them as hidden trailing columns.
        hidden = 0
        for ob in stmt.order_by:
            if ob.column not in names and schema.has_column(ob.column):
                names.append(ob.column)
                exprs.append(X.Col(ob.column))
                hidden += 1
        needed = sorted({c for e in exprs for c in self._item_columns(e)})
        limit = self._limit(stmt)
        offset = self._offset(stmt)
        # Engine-level LIMIT is only a safe pushdown when no later sort
        # reorders rows and a single tablet preserves global key order;
        # OFFSET rows are still consumed host-side, so push their count.
        push_limit = (limit + (offset or 0)
                      if limit is not None and not stmt.order_by
                      and len(handle.tablets) == 1 else None)
        if stmt.distinct:
            if hidden:
                raise InvalidArgument(
                    "for SELECT DISTINCT, ORDER BY expressions must "
                    "appear in the select list")
            push_limit = None  # dedup may need more input rows
        rows = []
        for d in self._scan_dicts(handle, stmt.where, preds, needed,
                                  push_limit):
            rows.append(tuple(self._eval_item(e, d) for e in exprs))
        return self._dedup_order_trim(stmt, names, rows, limit, hidden)

    _SCAN_POOL = None
    _SCAN_POOL_LOCK = __import__("threading").Lock()

    @classmethod
    def _scan_pool(cls):
        if cls._SCAN_POOL is None:
            with cls._SCAN_POOL_LOCK:
                if cls._SCAN_POOL is None:
                    from concurrent.futures import ThreadPoolExecutor

                    cls._SCAN_POOL = ThreadPoolExecutor(
                        max_workers=4, thread_name_prefix="pg-docop")
        return cls._SCAN_POOL

    def _prefetch_scans(self, tablets, spec_of):
        """PgDocOp-style prefetching (reference:
        src/yb/yql/pggate/pg_doc_op.h:111 — async batched doc ops):
        keep several tablets' reads in flight and yield results in
        tablet order, so the next tablet's fetch overlaps this one's
        result consumption. Single-tablet plans stay synchronous."""
        if len(tablets) <= 1:
            for t in tablets:
                yield t, t.scan(spec_of(t))
            return
        import collections

        pool = self._scan_pool()
        futs = collections.deque()
        idx = 0
        inflight = 3
        while idx < len(tablets) or futs:
            while idx < len(tablets) and len(futs) < inflight:
                t = tablets[idx]
                futs.append((t, pool.submit(t.scan, spec_of(t))))
                idx += 1
            t, fut = futs.popleft()
            yield t, fut.result()

    def _scan_dicts(self, handle, where, preds, needed, push_limit):
        """Row dicts matching WHERE: index-driven when an '='-bound
        column is indexed (index-table hash scan -> base point reads,
        re-verifying predicates against the base row), full predicate-
        pushdown scan otherwise."""
        schema = handle.schema
        if self._txn is not None:
            # full-PK point SELECT inside a txn: read-your-writes
            key_names = [c.name for c in schema.key_columns]
            eq = {r.column: r.value for r in where if r.op == "="}
            if set(key_names) <= set(eq) and len(where) == len(key_names):
                kv = {n: self._coerce(schema.column(n), eq[n])
                      for n in key_names}
                got = self._txn_point_get(handle, kv)
                if got is not None:
                    yield got[1]
                return
        from yugabyte_db_tpu.index import normalize_index

        idx_info = None
        for rel in where:
            if rel.op != "=":
                continue
            for idx in getattr(handle, "indexes", []):
                ni = normalize_index(idx)
                # The SQL planner lowers only single-column indexes; a
                # compound index needs every hash column bound.
                if ni["columns"] == [rel.column]:
                    idx_info = (ni, rel)
                    break
            if idx_info:
                break
        if idx_info is None:
            for _tablet, res in self._prefetch_scans(
                    handle.tablets,
                    lambda t: ScanSpec(read_ht=self._read_ht(t),
                                       predicates=preds,
                                       projection=needed,
                                       limit=push_limit)):
                for r in res.rows:
                    yield dict(zip(res.columns, r))
            return
        from yugabyte_db_tpu.models.encoding import (encode_doc_key_prefix,
                                                     prefix_successor)
        from yugabyte_db_tpu.models.partition import compute_hash_code

        idx, rel = idx_info
        ih = self.cluster.table(idx["index_table"])
        ischema = ih.schema
        value = self._coerce(schema.column(rel.column), rel.value)
        hc = compute_hash_code(ischema, {rel.column: value})
        prefix = encode_doc_key_prefix(
            hc, [(value, ischema.hash_columns[0].dtype)], [])
        key_names = [c.name for c in schema.key_columns]
        itablet = self.cluster.tablet_for_hash(ih, hc)
        ires = itablet.scan(ScanSpec(
            lower=prefix, upper=prefix_successor(prefix),
            read_ht=self._read_ht(itablet), projection=key_names))
        for irow in ires.rows:
            base_kv = dict(zip(key_names, irow))
            key, btablet = self._key_and_tablet(handle, base_kv)
            res = btablet.scan(ScanSpec(
                lower=key, upper=key + b"\x00",
                read_ht=self._read_ht(btablet),
                predicates=preds, projection=needed, limit=1))
            for r in res.rows:
                yield dict(zip(res.columns, r))

    @staticmethod
    def _cmp(op: str, left, right) -> bool:
        """SQL comparison for HAVING / post-join verification: NULL on
        either side fails every operator."""
        if left is None or right is None:
            return False
        return {"=": left == right, "!=": left != right,
                "<": left < right, "<=": left <= right,
                ">": left > right, ">=": left >= right}[op]

    def _finish_select(self, stmt: ast.Select, dicts: list[dict],
                       tables, handles, qualify=None) -> PgResult:
        """Host projection/aggregation over joined row dicts (the work
        PG's executor does above the FDW scans)."""
        if qualify is not None:
            # Validate every column reference (catches ambiguous bare
            # names, which would otherwise silently read as NULL).
            def check(e):
                if isinstance(e, ast.Agg):
                    if e.arg is not None:
                        check(e.arg)
                    return
                for c in self._item_columns(e):
                    qualify(c)
            for it in stmt.items:
                if it.expr != "*":
                    check(it.expr)
            for h in stmt.having:
                check(h.expr)
            for g in stmt.group_by:
                qualify(g)
        names, exprs = [], []
        for it in stmt.items:
            if it.expr == "*":
                for a, _t in tables:
                    for c in handles[a].schema.columns:
                        names.append(c.name)
                        exprs.append(X.Col(f"{a}.{c.name}"))
                continue
            if isinstance(it.expr, ast.Agg):
                arg = it.expr.arg
                names.append(it.alias or
                             f"{it.expr.fn}({'*' if arg is None else '...'})")
            elif isinstance(it.expr, X.Col):
                names.append(it.alias or it.expr.name.split(".")[-1])
            else:
                names.append(it.alias or "?column?")
            exprs.append(it.expr)
        has_agg = (stmt.group_by
                   or any(isinstance(e, ast.Agg) for e in exprs)
                   or any(isinstance(h.expr, ast.Agg)
                          for h in stmt.having))
        limit = self._limit(stmt)
        if has_agg:
            rows = self._host_aggregate(stmt, dicts, exprs)
            if stmt.distinct:
                rows = list(dict.fromkeys(rows))
            rows = self._order_and_limit(stmt, names, rows, limit)
            return PgResult(columns=names, rows=rows)
        hidden = 0
        for ob in stmt.order_by:
            if ob.column not in names:
                names.append(ob.column)
                exprs.append(X.Col(ob.column))
                hidden += 1
        rows = [tuple(self._eval_item(e, d) for e in exprs)
                for d in dicts]
        return self._dedup_order_trim(stmt, names, rows, limit, hidden)

    def _host_aggregate(self, stmt: ast.Select, dicts: list[dict],
                        exprs) -> list[tuple]:
        """Group + fold on host over row dicts; returns output rows in
        group-key order (HAVING applied)."""
        group_by = list(stmt.group_by)
        agg_items: list[tuple] = []     # (fn, arg)
        out_plan: list[tuple] = []      # ("agg", slot) | ("expr", e)
        for e in exprs:
            if isinstance(e, ast.Agg):
                out_plan.append(("agg", len(agg_items)))
                agg_items.append((e.fn, e.arg))
            else:
                out_plan.append(("expr", e))
        having_plan: list[tuple] = []
        for h in stmt.having:
            if isinstance(h.expr, ast.Agg):
                having_plan.append(("agg", len(agg_items), h.op, h.value))
                agg_items.append((h.expr.fn, h.expr.arg))
            else:
                having_plan.append(("expr", h.expr, h.op, h.value))

        def new_accs():
            return [[0, 0, None, None] for _ in agg_items]  # n,s,mn,mx

        groups: dict[tuple, tuple] = {}
        order: list[tuple] = []
        for d in dicts:
            gk = tuple(self._eval_item(X.Col(g), d) for g in group_by)
            st = groups.get(gk)
            if st is None:
                st = groups[gk] = (d, new_accs())
                order.append(gk)
            for acc, (fn, arg) in zip(st[1], agg_items):
                if fn == "count" and arg is None:
                    acc[0] += 1
                    continue
                v = self._eval_item(arg, d)
                if v is None:
                    continue
                acc[0] += 1
                if fn in ("sum", "avg"):
                    acc[1] += v
                if acc[2] is None or v < acc[2]:
                    acc[2] = v
                if acc[3] is None or v > acc[3]:
                    acc[3] = v

        def finalize(fn, acc):
            n, s, mn, mx = acc
            if fn == "count":
                return n
            if fn == "sum":
                return s if n else None
            if fn == "avg":
                return s / n if n else None
            return mn if fn == "min" else mx

        if not group_by and not groups:
            groups[()] = ({}, new_accs())   # PG: aggregates over zero
            order.append(())                # rows yield one row
        order.sort(key=lambda gk: tuple((v is None, v) for v in gk))
        rows = []
        for gk in order:
            rep, accs = groups[gk]
            keep = True
            for hp in having_plan:
                if hp[0] == "agg":
                    _k, slot, op, lit = hp
                    fn, _arg = agg_items[slot]
                    val = finalize(fn, accs[slot])
                else:
                    _k, e, op, lit = hp
                    val = self._eval_item(e, rep)
                if not self._cmp(op, val, self._resolve(lit)):
                    keep = False
                    break
            if not keep:
                continue
            out = []
            for kind, payload in out_plan:
                if kind == "agg":
                    fn, _arg = agg_items[payload]
                    out.append(finalize(fn, accs[payload]))
                else:
                    out.append(self._eval_item(payload, rep))
            rows.append(tuple(out))
        return rows

    def _select_aggregate(self, handle, stmt: ast.Select):
        schema = handle.schema
        where, ok = self._fold_exists(stmt.where)
        if not ok:
            # An EXISTS conjunct failed: aggregate over no rows — PG
            # still yields one row (count 0 / NULL sums) when there is
            # no GROUP BY. An impossible IN () predicate on any column
            # produces exactly the zero-row aggregate; keyless schemas
            # (virtual tables) use their first column.
            cols = schema.key_columns or schema.columns
            where = [ast.Rel(cols[0].name, "IN", ())]
        if where is not stmt.where:
            import dataclasses as _dc

            stmt = _dc.replace(stmt, where=where)
        preds = self._predicates(schema, stmt.where)
        group_by = list(stmt.group_by)
        for g in group_by:
            if not schema.has_column(g):
                raise InvalidArgument(f"unknown column {g}")

        # Output plan: each item maps to (kind, payload) where kind is
        # "group" (index into group_by) or "agg"; avg lowers into
        # sum+count partial slots derived after the combine.
        aggs: list[AggSpec] = []
        out_plan = []
        names = []
        for it in stmt.items:
            if isinstance(it.expr, ast.Agg):
                fn, arg = it.expr.fn, it.expr.arg
                label = it.alias or (
                    f"{fn}({'*' if arg is None else '...'})")
                if fn == "avg":
                    si = len(aggs)
                    aggs.append(self._agg_spec("sum", arg, f"_avg_s{si}"))
                    aggs.append(self._agg_spec("count", arg, f"_avg_c{si}"))
                    out_plan.append(("avg", si))
                else:
                    out_plan.append(("agg", len(aggs)))
                    aggs.append(self._agg_spec(fn, arg, label))
                names.append(label)
            elif isinstance(it.expr, X.Col):
                if it.expr.name not in group_by:
                    raise InvalidArgument(
                        f"column {it.expr.name} must appear in GROUP BY")
                out_plan.append(("group", group_by.index(it.expr.name)))
                names.append(it.alias or it.expr.name)
            else:
                raise InvalidArgument(
                    "non-aggregate expressions must be GROUP BY columns")

        # HAVING conjuncts ride as hidden aggregate slots through the
        # same per-tablet partial combine (avg lowers to sum+count).
        having_plan = []
        for h in stmt.having:
            if isinstance(h.expr, ast.Agg):
                fn, arg = h.expr.fn, h.expr.arg
                if fn == "avg":
                    si = len(aggs)
                    aggs.append(self._agg_spec("sum", arg, f"_hv_s{si}"))
                    aggs.append(self._agg_spec("count", arg, f"_hv_c{si}"))
                    having_plan.append(("avg", si, h.op, h.value))
                else:
                    having_plan.append(("agg", len(aggs), h.op, h.value))
                    aggs.append(self._agg_spec(fn, arg, f"_hv{len(aggs)}"))
            elif isinstance(h.expr, X.Col):
                if h.expr.name not in group_by:
                    raise InvalidArgument(
                        f"HAVING column {h.expr.name} must appear in "
                        f"GROUP BY")
                having_plan.append(
                    ("group", group_by.index(h.expr.name), h.op, h.value))
            else:
                raise InvalidArgument("unsupported HAVING expression")

        spec = ScanSpec(read_ht=MAX_HT, predicates=preds,
                        aggregates=aggs, group_by=group_by or None)
        # Per-tablet partial aggregates with PgDocOp-style prefetching:
        # every tablet's scan is in flight while partials combine.
        results = [res for _t, res in self._prefetch_scans(
            handle.tablets,
            lambda t: ScanSpec(read_ht=self._read_ht(t),
                               predicates=preds, aggregates=aggs,
                               group_by=group_by or None))]
        combined = combine_grouped(spec, results)
        ngb = len(group_by)

        def slot(row, kind, payload):
            if kind == "group":
                return row[payload]
            if kind == "agg":
                # combined columns: group cols, then aggs in order
                return row[ngb + payload]
            # avg: sum at payload, count at payload+1
            s, c = row[ngb + payload], row[ngb + payload + 1]
            return s / c if c else None

        rows = []
        for row in combined.rows:
            if not all(self._cmp(op, slot(row, kind, payload),
                                 self._resolve(lit))
                       for kind, payload, op, lit in having_plan):
                continue
            rows.append(tuple(slot(row, kind, payload)
                              for kind, payload in out_plan))
        if stmt.distinct:
            rows = list(dict.fromkeys(rows))
        rows = self._order_and_limit(stmt, names, rows, self._limit(stmt))
        return PgResult(columns=names, rows=rows)

    def _agg_spec(self, fn: str, arg, label: str) -> AggSpec:
        if arg is None:
            return AggSpec("count", None, label=label)
        if isinstance(arg, X.Col):
            return AggSpec(fn, arg.name, label=label)
        if fn not in ("sum",):
            raise InvalidArgument(
                f"{fn} over an expression is not supported")
        return AggSpec(fn, None, expr=arg, label=label)

    def _limit(self, stmt: ast.Select):
        limit = self._resolve(stmt.limit)
        if limit is not None and (not isinstance(limit, int)
                                  or isinstance(limit, bool) or limit < 0):
            raise InvalidArgument("LIMIT must be a non-negative integer")
        return limit

    def _offset(self, stmt: ast.Select):
        off = self._resolve(getattr(stmt, "offset", None))
        if off is not None and (not isinstance(off, int)
                                or isinstance(off, bool) or off < 0):
            raise InvalidArgument("OFFSET must be a non-negative integer")
        return off

    def _dedup_order_trim(self, stmt: ast.Select, names: list[str],
                          rows: list[tuple], limit, hidden: int):
        """Shared SELECT tail: DISTINCT dedup (hidden ORDER BY columns
        are invalid under DISTINCT, as in PG), ORDER BY + LIMIT/OFFSET,
        then trim hidden trailing columns."""
        if stmt.distinct:
            if hidden:
                raise InvalidArgument(
                    "for SELECT DISTINCT, ORDER BY expressions must "
                    "appear in the select list")
            rows = list(dict.fromkeys(rows))
        rows = self._order_and_limit(stmt, names, rows, limit)
        if hidden:
            rows = [r[:-hidden] for r in rows]
            names = names[:-hidden]
        return PgResult(columns=names, rows=rows)

    def _order_and_limit(self, stmt: ast.Select, names: list[str], rows,
                         limit):
        if stmt.order_by:
            pos = {}
            for ob in stmt.order_by:
                if ob.column not in names:
                    raise InvalidArgument(
                        f"ORDER BY column {ob.column} is not in the "
                        f"select list")
                pos[ob.column] = names.index(ob.column)
            for ob in reversed(stmt.order_by):
                i = pos[ob.column]
                # PG defaults: ASC -> NULLS LAST, DESC -> NULLS FIRST
                rows.sort(key=lambda r: ((r[i] is None), r[i]),
                          reverse=ob.desc)
        offset = self._offset(stmt)
        if offset:
            rows = rows[offset:]
        if limit is not None:
            rows = rows[:limit]
        return rows
