"""PgProcessor: parse -> plan -> execute SQL against the cluster seam.

Reference analog: the YSQL execution stack — the PostgreSQL executor's
foreign-scan path (ybc_fdw.c:364 ybcIterateForeignScan) feeding
PgsqlReadOperation with WHERE pushdown and per-tablet partial aggregates
(src/yb/docdb/pgsql_operation.cc:345,473), and the DML path through
PgDocWriteOp (src/yb/yql/pggate/pg_doc_op.h:142). Here the planner
lowers SELECT straight to ScanSpecs on the shared Cluster seam (the
same LocalCluster / ClientCluster objects the CQL processor drives),
with grouped/expression aggregates pushed down to the storage engine —
on the TPU engine that is one device dispatch per tablet (ops.group_agg)
— and per-tablet partials combined above the scan (operations.py).

SQL semantic notes (vs the CQL processor):
- INSERT enforces primary-key uniqueness (PG errors on duplicates;
  CQL upserts).
- UPDATE/DELETE accept arbitrary WHERE: non-PK predicates resolve via a
  predicate-pushdown scan, then write per matching row.
- avg() lowers to sum+count partials and is derived after the combine
  (partial averages cannot be merged across tablets).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage import expr as X
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.storage.scan_spec import AggSpec, Predicate, ScanSpec
from yugabyte_db_tpu.utils.status import AlreadyPresent, InvalidArgument
from yugabyte_db_tpu.yql.pgsql import ast
from yugabyte_db_tpu.yql.pgsql.operations import combine_grouped
from yugabyte_db_tpu.yql.pgsql.parser import parse_statement


class SerializationFailure(Exception):
    """Transaction conflict/abort (PG error code 40001): retry it."""


class FailedTransaction(Exception):
    """Statement issued inside an aborted block (PG code 25P02)."""


@dataclass
class PgResult:
    """Rows returned to the driver (the wire server turns this into
    RowDescription + DataRow messages)."""

    columns: list[str] = field(default_factory=list)
    rows: list[tuple] = field(default_factory=list)
    command: str = "SELECT"    # CommandComplete tag prefix

    def __iter__(self):
        return iter(self.rows)

    def dicts(self) -> list[dict]:
        return [dict(zip(self.columns, r)) for r in self.rows]


class PgProcessor:
    """One SQL session over a Cluster seam.

    Transactions (BEGIN/COMMIT/ROLLBACK) run on the distributed seam's
    TransactionManager: DML inside a transaction buffers intents through
    a YBTransaction (snapshot isolation, first-committer-wins conflicts
    surfaced as 40001); point SELECTs read-your-writes, range SELECTs
    read the transaction's snapshot (own uncommitted writes are not
    merged into range scans — the documented client-txn contract)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._txn = None
        self._txn_failed = False  # aborted block awaiting COMMIT/ROLLBACK
        self._yb_tables: dict = {}

    @property
    def in_txn(self) -> bool:
        return self._txn is not None or self._txn_failed

    @property
    def txn_status(self) -> str:
        """The ReadyForQuery status byte: I idle, T in txn, E failed."""
        if self._txn_failed:
            return "E"
        return "T" if self._txn is not None else "I"

    # -- entry point -------------------------------------------------------
    def execute(self, sql, params: list | None = None) -> PgResult | None:
        stmt = parse_statement(sql) if isinstance(sql, str) else sql
        self._params = params or []
        if isinstance(stmt, ast.TxnControl):
            return self._exec_txn_control(stmt)
        if self._txn_failed:
            # PG 25P02: the block already failed; only COMMIT/ROLLBACK
            # (both of which roll back) end it
            raise FailedTransaction(
                "current transaction is aborted, commands ignored until "
                "end of transaction block")
        fn = {
            ast.CreateTable: self._exec_create_table,
            ast.DropTable: self._exec_drop_table,
            ast.AlterTable: self._exec_alter_table,
            ast.CreateIndex: self._exec_create_index,
            ast.DropIndex: self._exec_drop_index,
            ast.Insert: self._exec_insert,
            ast.Update: self._exec_update,
            ast.Delete: self._exec_delete,
            ast.Select: self._exec_select,
        }[type(stmt)]
        try:
            return fn(stmt)
        except Exception:
            if self._txn is not None:
                # a failed statement aborts the whole block (PG
                # semantics): nothing from it may ever commit
                self._txn.abort()
                self._txn = None
                self._txn_failed = True
            raise

    # -- transactions ------------------------------------------------------
    def _exec_txn_control(self, stmt: ast.TxnControl):
        from yugabyte_db_tpu.txn.client import (TransactionAborted,
                                                TransactionConflict)

        if stmt.kind == "begin":
            if self.in_txn:
                raise InvalidArgument(
                    "there is already a transaction in progress")
            mgr_fn = getattr(self.cluster, "transaction_manager", None)
            if mgr_fn is None:
                raise InvalidArgument(
                    "transactions require a distributed cluster")
            self._txn = mgr_fn().begin()
            return PgResult(command="BEGIN")
        if self._txn_failed:
            # COMMIT of a failed block is a rollback (PG reports it so)
            self._txn_failed = False
            return PgResult(command="ROLLBACK")
        if self._txn is None:
            raise InvalidArgument("no transaction in progress")
        txn, self._txn = self._txn, None
        if stmt.kind == "rollback":
            txn.abort()
            return PgResult(command="ROLLBACK")
        try:
            txn.commit()
        except (TransactionConflict, TransactionAborted) as e:
            raise SerializationFailure(str(e)) from e
        return PgResult(command="COMMIT")

    def _yb_table(self, name: str):
        t = self._yb_tables.get(name)
        if t is None:
            t = self._yb_tables[name] = self.cluster.open_yb_table(name)
        return t

    def _read_ht(self, tablet) -> int:
        """The read point for scans: the txn snapshot inside a
        transaction, the tablet's safe time otherwise."""
        if self._txn is not None:
            return self._txn.read_ht
        return tablet.read_time().value

    # -- binding / coercion ------------------------------------------------
    def _resolve(self, value):
        if isinstance(value, ast.BindMarker):
            try:
                return self._params[value.index]
            except IndexError:
                raise InvalidArgument(
                    f"bind marker ${value.index + 1} has no value") from None
        return value

    def _coerce(self, col: ColumnSchema, value):
        from yugabyte_db_tpu.yql.common import coerce_value

        return coerce_value(col, self._resolve(value))

    # -- DDL ---------------------------------------------------------------
    def _exec_create_table(self, stmt: ast.CreateTable):
        if stmt.name in self.cluster.tables:
            if stmt.if_not_exists:
                return None
            raise AlreadyPresent(f"relation {stmt.name} already exists")
        by_name = {c.name for c in stmt.columns}
        for k in stmt.hash_keys + stmt.range_keys:
            if k not in by_name:
                raise InvalidArgument(f"primary key column {k} not defined")
        cols = []
        for c in stmt.columns:
            if c.name in stmt.hash_keys:
                kind = ColumnKind.HASH
            elif c.name in stmt.range_keys:
                kind = ColumnKind.RANGE
            else:
                kind = ColumnKind.REGULAR
            if kind != ColumnKind.REGULAR and \
                    c.dtype in (DataType.FLOAT, DataType.DOUBLE):
                raise InvalidArgument(
                    f"floating-point column {c.name} cannot be a key")
            cols.append(ColumnSchema(c.name, c.dtype, kind,
                                     nullable=kind == ColumnKind.REGULAR))
        schema = Schema(cols, table_id=stmt.name)
        self.cluster.create_table(stmt.name, schema, stmt.num_tablets)
        self._yb_tables.pop(stmt.name, None)
        return PgResult(command="CREATE TABLE")

    def _exec_drop_table(self, stmt: ast.DropTable):
        from yugabyte_db_tpu.utils.status import NotFound

        try:
            self.cluster.drop_table(stmt.name)
        except NotFound:
            if not stmt.if_exists:
                raise
        self._yb_tables.pop(stmt.name, None)
        return PgResult(command="DROP TABLE")

    def _exec_alter_table(self, stmt: ast.AlterTable):
        """Schema evolution by stable column ids (ADD -> NULL for
        existing rows, DROP retires the id, RENAME touches no data)."""
        from yugabyte_db_tpu.yql.common import evolve_schema

        handle = self.cluster.table(stmt.name)
        self.cluster.alter_table(handle, evolve_schema(
            handle, stmt.action, stmt.column, stmt.dtype, stmt.new_name))
        self._yb_tables.pop(stmt.name, None)
        return PgResult(command="ALTER TABLE")

    def _exec_create_index(self, stmt: ast.CreateIndex):
        handle = self.cluster.table(stmt.table)
        if any(i["name"] == stmt.name
               for i in getattr(handle, "indexes", [])):
            if stmt.if_not_exists:
                return None
            raise AlreadyPresent(f"index {stmt.name} exists")
        if not handle.schema.has_column(stmt.column):
            raise InvalidArgument(f"unknown column {stmt.column}")
        if handle.schema.column(stmt.column).is_key:
            raise InvalidArgument(f"cannot index key column {stmt.column}")
        itable = self.cluster.create_index(handle, stmt.name, stmt.column)
        self._backfill_index(handle, stmt.column, itable)
        return PgResult(command="CREATE INDEX")

    def _backfill_index(self, handle, column: str, itable: str) -> None:
        """Populate the index from existing base rows (reference: the
        online index backfill job; here a scan + index-entry writes)."""
        from yugabyte_db_tpu.index import index_entry

        ih = self.cluster.table(itable)
        key_names = [c.name for c in handle.schema.key_columns]
        proj = key_names + [column]
        for tablet in handle.tablets:
            res = tablet.scan(ScanSpec(
                read_ht=tablet.read_time().value, projection=proj))
            for row in res.rows:
                value = row[-1]
                if value is None:
                    continue
                base_kv = dict(zip(key_names, row[:-1]))
                hc, rv = index_entry(ih.schema, value, base_kv)
                self.cluster.tablet_for_hash(ih, hc).write([rv])

    def _exec_drop_index(self, stmt: ast.DropIndex):
        from yugabyte_db_tpu.utils.status import NotFound

        for name in list(self.cluster.tables):
            try:
                handle = self.cluster.table(name)
            except NotFound:
                continue
            for idx in getattr(handle, "indexes", []):
                if idx["name"] == stmt.name:
                    self.cluster.drop_index(handle, stmt.name)
                    return PgResult(command="DROP INDEX")
        if not stmt.if_exists:
            raise NotFound(f"index {stmt.name} not found")
        return PgResult(command="DROP INDEX")

    # -- DML ---------------------------------------------------------------
    def _key_and_tablet(self, handle, key_values: dict):
        from yugabyte_db_tpu.yql.common import key_and_tablet

        return key_and_tablet(self.cluster, handle, key_values)

    def _write_row(self, handle, key_values: dict, key: bytes, tablet,
                   row: RowVersion, if_not_exists: bool = False) -> None:
        if getattr(handle, "indexes", None) and \
                getattr(self.cluster, "maintain_indexes", None):
            indexed_cids = {handle.schema.column(i["column"]).col_id
                            for i in handle.indexes}
            if row.tombstone or (indexed_cids & row.columns.keys()):
                # Conditional INSERT: the row must not exist, so the old
                # state is absent by contract — no tombstones. A later
                # duplicate rejection then leaves at most a stale extra
                # entry (base-verified away), never a removed one.
                old = (None if if_not_exists
                       else tablet.current_row_values(key))
                self.cluster.maintain_indexes(handle, key_values, old, row)
        tablet.write([row], if_not_exists=if_not_exists)

    def _exec_insert(self, stmt: ast.Insert):
        handle = self.cluster.table(stmt.table)
        schema = handle.schema
        for cname in stmt.columns:
            if not schema.has_column(cname):
                raise InvalidArgument(f"unknown column {cname}")
        n = 0
        for values in stmt.rows:
            provided = dict(zip(stmt.columns, values))
            key_values, columns = {}, {}
            for c in schema.key_columns:
                v = (self._coerce(c, provided[c.name])
                     if c.name in provided else None)
                if v is None:  # checked AFTER bind resolution: $N may be None
                    raise InvalidArgument(
                        f"null value in column {c.name} violates "
                        f"not-null constraint")
                key_values[c.name] = v
            for c in schema.value_columns:
                if c.name in provided:
                    columns[c.col_id] = self._coerce(c, provided[c.name])
            if self._txn is not None:
                # Uniqueness inside a txn: read-your-writes existence
                # check; overlapping inserts from OTHER txns resolve at
                # the intent level (first-committer-wins).
                yt = self._yb_table(stmt.table)
                if self._txn.get(yt, key_values) is not None:
                    raise AlreadyPresent(
                        "duplicate key value violates unique constraint")
                vals = dict(key_values)
                vals.update({c.name: columns[c.col_id]
                             for c in schema.value_columns
                             if c.col_id in columns})
                self._txn.insert(yt, vals)
                n += 1
                continue
            key, tablet = self._key_and_tablet(handle, key_values)
            # PG semantics: duplicate key is an error (23505), not an
            # upsert. The check is ATOMIC with the write — it runs on the
            # tablet under the same lock as the apply (Tablet.write
            # if_not_exists / the tserver's intent-admission lock).
            self._write_row(handle, key_values, key, tablet, RowVersion(
                key, ht=0, liveness=True, columns=columns),
                if_not_exists=True)
            n += 1
        return PgResult(command=f"INSERT 0 {n}")

    def _match_rows(self, handle, where: list[ast.Rel]):
        """Resolve WHERE to (key_values, row-dict) pairs. Full-PK equality
        short-circuits to a point read; anything else scans with
        predicate pushdown."""
        schema = handle.schema
        key_names = [c.name for c in schema.key_columns]
        eq = {r.column: r.value for r in where if r.op == "="}
        if set(key_names) <= set(eq) and len(where) == len(key_names):
            kv = {n: self._coerce(schema.column(n), eq[n])
                  for n in key_names}
            if self._txn is not None:
                got = self._txn_point_get(handle, kv)
                return [] if got is None else [got]
            key, tablet = self._key_and_tablet(handle, kv)
            res = tablet.scan(ScanSpec(
                lower=key, upper=key + b"\x00",
                read_ht=self._read_ht(tablet), projection=None))
            return [(kv, dict(zip(res.columns, r))) for r in res.rows]
        preds = self._predicates(schema, where)
        out = []
        for tablet in handle.tablets:
            res = tablet.scan(ScanSpec(
                read_ht=self._read_ht(tablet), predicates=preds))
            for r in res.rows:
                d = dict(zip(res.columns, r))
                out.append(({n: d[n] for n in key_names}, d))
        if self._txn is not None:
            out = self._overlay_own_writes(handle, preds, out)
        return out

    def _txn_point_get(self, handle, kv):
        """Point resolution inside a txn: read-your-writes (own buffered
        and flushed intents overlay the committed snapshot). Returns
        (kv, row-dict) or None."""
        row = self._txn.get(self._yb_table(handle.name), kv)
        if row is None:
            return None
        names = [c.name for c in handle.schema.columns]
        return (kv, dict(zip(names, row)))

    def _overlay_own_writes(self, handle, preds, snapshot_rows):
        """Statements inside a transaction must see earlier statements'
        effects: merge the txn's own buffered writes over the snapshot
        match set (replace matched rows, drop tombstoned ones, add newly
        inserted ones that match the predicates)."""
        from yugabyte_db_tpu.models.encoding import decode_doc_key
        from yugabyte_db_tpu.models.partition import compute_hash_code

        schema = handle.schema
        key_names = [c.name for c in schema.key_columns]
        own = self._txn.own_rows(self._yb_table(handle.name))
        if not own:
            return snapshot_rows
        by_id = {c.col_id: c.name for c in schema.value_columns}
        out = []
        seen = set()
        for kv, d in snapshot_rows:
            key = schema.encode_primary_key(
                kv, compute_hash_code(schema, kv))
            row = own.get(key)
            if row is None:
                out.append((kv, d))
                continue
            seen.add(key)
            if row.tombstone:
                continue
            merged = dict(d)
            for cid, v in row.columns.items():
                if cid in by_id:
                    merged[by_id[cid]] = v
            if all(p.matches(merged.get(p.column)) for p in preds):
                out.append((kv, merged))
        for key, row in own.items():
            if key in seen or row.tombstone:
                continue
            _, hashed, ranges = decode_doc_key(key)
            kv = dict(zip(key_names, hashed + ranges))
            # full state (committed base + own overlay) via the point
            # get — the snapshot row may exist but have been excluded by
            # the pre-overlay predicate values, and building from only
            # the buffered columns would invent NULLs
            got = self._txn_point_get(handle, kv)
            if got is None:
                continue
            d = got[1]
            if all(p.matches(d.get(p.column)) for p in preds):
                out.append((kv, d))
        return out

    def _predicates(self, schema: Schema, where: list[ast.Rel]):
        preds = []
        for rel in where:
            if not schema.has_column(rel.column):
                raise InvalidArgument(f"unknown column {rel.column}")
            col = schema.column(rel.column)
            if rel.op == "IN":
                vals = tuple(self._coerce(col, v)
                             for v in self._resolve(rel.value))
                preds.append(Predicate(rel.column, "IN", vals))
            else:
                preds.append(Predicate(rel.column, rel.op,
                                       self._coerce(col, rel.value)))
        return preds

    def _exec_update(self, stmt: ast.Update):
        handle = self.cluster.table(stmt.table)
        schema = handle.schema
        sets = []
        for cname, rhs in stmt.assignments:
            if not schema.has_column(cname):
                raise InvalidArgument(f"unknown column {cname}")
            col = schema.column(cname)
            if col.is_key:
                raise InvalidArgument(f"cannot SET key column {cname}")
            sets.append((col, rhs))
        n = 0
        for kv, old in self._match_rows(handle, stmt.where):
            set_values = {}
            for col, rhs in sets:
                if isinstance(rhs, (X.Col, X.Const, X.BinOp)):
                    v = X.eval_expr(rhs, lambda name: old.get(name))
                    if col.dtype in (DataType.DOUBLE, DataType.FLOAT) \
                            and isinstance(v, int):
                        v = float(v)
                    set_values[col.name] = v
                else:
                    set_values[col.name] = self._coerce(col, rhs)
            if self._txn is not None:
                self._txn.update(self._yb_table(stmt.table), kv,
                                 set_values)
                n += 1
                continue
            columns = {handle.schema.column(nm).col_id: v
                       for nm, v in set_values.items()}
            key, tablet = self._key_and_tablet(handle, kv)
            self._write_row(handle, kv, key, tablet,
                            RowVersion(key, ht=0, columns=columns))
            n += 1
        return PgResult(command=f"UPDATE {n}")

    def _exec_delete(self, stmt: ast.Delete):
        handle = self.cluster.table(stmt.table)
        n = 0
        for kv, _old in self._match_rows(handle, stmt.where):
            if self._txn is not None:
                self._txn.delete_row(self._yb_table(stmt.table), kv)
                n += 1
                continue
            key, tablet = self._key_and_tablet(handle, kv)
            self._write_row(handle, kv, key, tablet,
                            RowVersion(key, ht=0, tombstone=True))
            n += 1
        return PgResult(command=f"DELETE {n}")

    # -- SELECT ------------------------------------------------------------
    def _exec_select(self, stmt: ast.Select):
        handle = self.cluster.table(stmt.table)
        schema = handle.schema
        has_agg = any(isinstance(it.expr, ast.Agg) for it in stmt.items)
        if has_agg or stmt.group_by:
            return self._select_aggregate(handle, stmt)
        return self._select_rows(handle, stmt)

    @staticmethod
    def _eval_item(expr, d: dict):
        """Evaluate one select-item expression over a row dict (scalar
        trees via storage.expr; jsonb paths host-side)."""
        if isinstance(expr, ast.JsonPath):
            import json

            v = d.get(expr.column)
            for op, key in expr.steps:
                if v is None:
                    return None
                if isinstance(v, dict):
                    v = v.get(key)
                elif isinstance(v, list) and isinstance(key, int) \
                        and -len(v) <= key < len(v):
                    v = v[key]
                else:
                    return None
                if op == "->>" and v is not None:
                    v = (json.dumps(v, separators=(",", ":"))
                         if isinstance(v, (dict, list)) else
                         ("true" if v is True else "false"
                          if v is False else str(v)))
            return v
        return X.eval_expr(expr, lambda n: d.get(n))

    @staticmethod
    def _item_columns(expr) -> set:
        if isinstance(expr, ast.JsonPath):
            return {expr.column}
        return X.columns_of(expr)

    def _select_rows(self, handle, stmt: ast.Select):
        schema = handle.schema
        preds = self._predicates(schema, stmt.where)
        all_names = [c.name for c in schema.columns]
        names, exprs = [], []
        for it in stmt.items:
            if it.expr == "*":
                names.extend(all_names)
                exprs.extend(X.Col(n) for n in all_names)
                continue
            if isinstance(it.expr, X.Col):
                if not schema.has_column(it.expr.name):
                    raise InvalidArgument(f"unknown column {it.expr.name}")
                names.append(it.alias or it.expr.name)
            else:
                names.append(it.alias or "?column?")
            exprs.append(it.expr)
        # ORDER BY may reference table columns outside the select list
        # (PG semantics): carry them as hidden trailing columns.
        hidden = 0
        for ob in stmt.order_by:
            if ob.column not in names and schema.has_column(ob.column):
                names.append(ob.column)
                exprs.append(X.Col(ob.column))
                hidden += 1
        needed = sorted({c for e in exprs for c in self._item_columns(e)})
        limit = self._limit(stmt)
        # Engine-level LIMIT is only a safe pushdown when no later sort
        # reorders rows and a single tablet preserves global key order.
        push_limit = (limit if not stmt.order_by
                      and len(handle.tablets) == 1 else None)
        rows = []
        for d in self._scan_dicts(handle, stmt.where, preds, needed,
                                  push_limit):
            rows.append(tuple(self._eval_item(e, d) for e in exprs))
        rows = self._order_and_limit(stmt, names, rows, limit)
        if hidden:
            rows = [r[:-hidden] for r in rows]
            names = names[:-hidden]
        return PgResult(columns=names, rows=rows)

    def _scan_dicts(self, handle, where, preds, needed, push_limit):
        """Row dicts matching WHERE: index-driven when an '='-bound
        column is indexed (index-table hash scan -> base point reads,
        re-verifying predicates against the base row), full predicate-
        pushdown scan otherwise."""
        schema = handle.schema
        if self._txn is not None:
            # full-PK point SELECT inside a txn: read-your-writes
            key_names = [c.name for c in schema.key_columns]
            eq = {r.column: r.value for r in where if r.op == "="}
            if set(key_names) <= set(eq) and len(where) == len(key_names):
                kv = {n: self._coerce(schema.column(n), eq[n])
                      for n in key_names}
                got = self._txn_point_get(handle, kv)
                if got is not None:
                    yield got[1]
                return
        idx_info = None
        for rel in where:
            if rel.op != "=":
                continue
            for idx in getattr(handle, "indexes", []):
                if idx["column"] == rel.column:
                    idx_info = (idx, rel)
                    break
            if idx_info:
                break
        if idx_info is None:
            for tablet in handle.tablets:
                res = tablet.scan(ScanSpec(
                    read_ht=self._read_ht(tablet), predicates=preds,
                    projection=needed, limit=push_limit))
                for r in res.rows:
                    yield dict(zip(res.columns, r))
            return
        from yugabyte_db_tpu.models.encoding import (encode_doc_key_prefix,
                                                     prefix_successor)
        from yugabyte_db_tpu.models.partition import compute_hash_code

        idx, rel = idx_info
        ih = self.cluster.table(idx["index_table"])
        ischema = ih.schema
        value = self._coerce(schema.column(rel.column), rel.value)
        hc = compute_hash_code(ischema, {rel.column: value})
        prefix = encode_doc_key_prefix(
            hc, [(value, ischema.hash_columns[0].dtype)], [])
        key_names = [c.name for c in schema.key_columns]
        itablet = self.cluster.tablet_for_hash(ih, hc)
        ires = itablet.scan(ScanSpec(
            lower=prefix, upper=prefix_successor(prefix),
            read_ht=self._read_ht(itablet), projection=key_names))
        for irow in ires.rows:
            base_kv = dict(zip(key_names, irow))
            key, btablet = self._key_and_tablet(handle, base_kv)
            res = btablet.scan(ScanSpec(
                lower=key, upper=key + b"\x00",
                read_ht=self._read_ht(btablet),
                predicates=preds, projection=needed, limit=1))
            for r in res.rows:
                yield dict(zip(res.columns, r))

    def _select_aggregate(self, handle, stmt: ast.Select):
        schema = handle.schema
        preds = self._predicates(schema, stmt.where)
        group_by = list(stmt.group_by)
        for g in group_by:
            if not schema.has_column(g):
                raise InvalidArgument(f"unknown column {g}")

        # Output plan: each item maps to (kind, payload) where kind is
        # "group" (index into group_by) or "agg"; avg lowers into
        # sum+count partial slots derived after the combine.
        aggs: list[AggSpec] = []
        out_plan = []
        names = []
        for it in stmt.items:
            if isinstance(it.expr, ast.Agg):
                fn, arg = it.expr.fn, it.expr.arg
                label = it.alias or (
                    f"{fn}({'*' if arg is None else '...'})")
                if fn == "avg":
                    si = len(aggs)
                    aggs.append(self._agg_spec("sum", arg, f"_avg_s{si}"))
                    aggs.append(self._agg_spec("count", arg, f"_avg_c{si}"))
                    out_plan.append(("avg", si))
                else:
                    out_plan.append(("agg", len(aggs)))
                    aggs.append(self._agg_spec(fn, arg, label))
                names.append(label)
            elif isinstance(it.expr, X.Col):
                if it.expr.name not in group_by:
                    raise InvalidArgument(
                        f"column {it.expr.name} must appear in GROUP BY")
                out_plan.append(("group", group_by.index(it.expr.name)))
                names.append(it.alias or it.expr.name)
            else:
                raise InvalidArgument(
                    "non-aggregate expressions must be GROUP BY columns")

        spec = ScanSpec(read_ht=MAX_HT, predicates=preds,
                        aggregates=aggs, group_by=group_by or None)
        results = []
        for tablet in handle.tablets:
            results.append(tablet.scan(ScanSpec(
                read_ht=self._read_ht(tablet), predicates=preds,
                aggregates=aggs, group_by=group_by or None)))
        combined = combine_grouped(spec, results)
        ngb = len(group_by)
        rows = []
        for row in combined.rows:
            out = []
            for kind, payload in out_plan:
                if kind == "group":
                    out.append(row[payload])
                elif kind == "agg":
                    # combined columns: group cols, then aggs in order
                    out.append(row[ngb + payload])
                else:  # avg: sum at payload, count at payload+1
                    s, c = row[ngb + payload], row[ngb + payload + 1]
                    out.append(s / c if c else None)
            rows.append(tuple(out))
        rows = self._order_and_limit(stmt, names, rows, self._limit(stmt))
        return PgResult(columns=names, rows=rows)

    def _agg_spec(self, fn: str, arg, label: str) -> AggSpec:
        if arg is None:
            return AggSpec("count", None, label=label)
        if isinstance(arg, X.Col):
            return AggSpec(fn, arg.name, label=label)
        if fn not in ("sum",):
            raise InvalidArgument(
                f"{fn} over an expression is not supported")
        return AggSpec(fn, None, expr=arg, label=label)

    def _limit(self, stmt: ast.Select):
        limit = self._resolve(stmt.limit)
        if limit is not None and (not isinstance(limit, int)
                                  or isinstance(limit, bool) or limit < 0):
            raise InvalidArgument("LIMIT must be a non-negative integer")
        return limit

    @staticmethod
    def _order_and_limit(stmt: ast.Select, names: list[str], rows, limit):
        if stmt.order_by:
            pos = {}
            for ob in stmt.order_by:
                if ob.column not in names:
                    raise InvalidArgument(
                        f"ORDER BY column {ob.column} is not in the "
                        f"select list")
                pos[ob.column] = names.index(ob.column)
            for ob in reversed(stmt.order_by):
                i = pos[ob.column]
                # PG defaults: ASC -> NULLS LAST, DESC -> NULLS FIRST
                rows.sort(key=lambda r: ((r[i] is None), r[i]),
                          reverse=ob.desc)
        if limit is not None:
            rows = rows[:limit]
        return rows
