"""PgsqlReadOp: the pggate-shaped read operation.

Reference analog: PgsqlReadOperation::Execute
(src/yb/docdb/pgsql_operation.cc:345) with EvalAggregate/
PopulateAggregate (:473,487) — a read request carrying WHERE pushdown,
GROUP BY columns, and expression aggregates, executed against one
tablet's storage seam and combined above the scan. The TPU redesign
pushes the whole grouped/expression evaluation into one device dispatch
(ops.group_agg) when the engine can; this object is the API carrier.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from yugabyte_db_tpu.storage.scan_spec import AggSpec, ScanResult, ScanSpec


@dataclass
class PgsqlReadOp:
    """One pgsql-style read: build once, execute per tablet, combine."""

    spec: ScanSpec

    @staticmethod
    def aggregate(predicates=None, aggregates=None, group_by=None,
                  read_ht=None, lower=b"", upper=b"") -> "PgsqlReadOp":
        from yugabyte_db_tpu.storage.row_version import MAX_HT

        return PgsqlReadOp(ScanSpec(
            lower=lower, upper=upper,
            read_ht=read_ht if read_ht is not None else MAX_HT,
            predicates=list(predicates or []),
            aggregates=list(aggregates or []),
            group_by=list(group_by) if group_by else None))

    def execute(self, engine) -> ScanResult:
        """Run against one tablet's storage engine (the YQLStorageIf
        seam)."""
        return engine.scan(self.spec)

    def execute_partitioned(self, engines) -> ScanResult:
        """Run against many tablets and combine partial aggregates
        host-side (the above-the-scan combine of the reference's FDW /
        CQL executor)."""
        results = [e.scan(self.spec) for e in engines]
        return combine_grouped(self.spec, results)


def combine_grouped(spec: ScanSpec, results: list[ScanResult]) -> ScanResult:
    """Merge per-tablet grouped aggregate partials (sum/count add,
    min/max extremize)."""
    gb = spec.group_by or []
    ngb = len(gb)
    aggs = spec.aggregates or []
    groups: dict[tuple, list] = {}
    scanned = 0
    for res in results:
        scanned += res.rows_scanned
        for row in res.rows:
            gkey = tuple(row[:ngb])
            acc = groups.get(gkey)
            if acc is None:
                groups[gkey] = list(row[ngb:])
                continue
            for i, a in enumerate(aggs):
                v = row[ngb + i]
                if v is None:
                    continue
                if acc[i] is None:
                    acc[i] = v
                elif a.fn in ("sum", "count"):
                    acc[i] += v
                elif a.fn == "min":
                    acc[i] = min(acc[i], v)
                elif a.fn == "max":
                    acc[i] = max(acc[i], v)
    if not groups and not gb:
        groups[()] = [0 if a.fn == "count" else None for a in aggs]
    rows = [tuple(g) + tuple(groups[g])
            for g in sorted(groups, key=lambda g: tuple(
                (v is None, v) for v in g))]
    names = list(gb) + [a.output_name for a in aggs]
    return ScanResult(names, rows, None, scanned)
