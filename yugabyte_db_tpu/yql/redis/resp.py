"""RESP (REdis Serialization Protocol) codec + connection context.

Reference analog: src/yb/yql/redis/redisserver/redis_parser.cc and the
RedisConnectionContext of redis_rpc.cc. Implements RESP2: commands
arrive as arrays of bulk strings (plus the inline-command form); replies
are simple strings, errors, integers, bulk strings, and arrays.
"""

from __future__ import annotations

from yugabyte_db_tpu.rpc.messenger import ConnectionContext

try:
    from yugabyte_db_tpu.native import yb_rb as _yb_rb
except ImportError:  # native batch parser not built: pure-Python parse
    _yb_rb = None

CRLF = b"\r\n"


class ProtocolError(Exception):
    pass


def parse_commands(buf: bytearray):
    """Consume complete commands from ``buf``; yields lists of bytes.
    Leaves partial data in place."""
    out = []
    while buf:
        if buf[:1] == b"*":
            end = buf.find(CRLF)
            if end < 0:
                break
            try:
                n = int(buf[1:end])
            except ValueError:
                raise ProtocolError("bad array length")
            pos = end + 2
            args = []
            ok = True
            for _ in range(max(n, 0)):
                if buf[pos:pos + 1] != b"$":
                    if pos >= len(buf):
                        ok = False
                        break
                    raise ProtocolError("expected bulk string")
                lend = buf.find(CRLF, pos)
                if lend < 0:
                    ok = False
                    break
                try:
                    ln = int(buf[pos + 1:lend])
                except ValueError:
                    raise ProtocolError("bad bulk length")
                if ln < 0:
                    # RESP2 commands carry no null bulk strings; a negative
                    # length here would desynchronize the parse offset.
                    raise ProtocolError("negative bulk length in command")
                start = lend + 2
                if len(buf) < start + ln + 2:
                    ok = False
                    break
                args.append(bytes(buf[start:start + ln]))
                pos = start + ln + 2
            if not ok:
                break
            del buf[:pos]
            if args:
                out.append(args)
        else:
            # inline command form: "PING\r\n"
            end = buf.find(CRLF)
            if end < 0:
                break
            line = bytes(buf[:end])
            del buf[:end + 2]
            parts = line.split()
            if parts:
                out.append(parts)
    return out


# -- reply encoding ----------------------------------------------------------

def simple(s: str) -> bytes:
    return b"+" + s.encode() + CRLF


def error(msg: str) -> bytes:
    return b"-ERR " + msg.encode() + CRLF


def integer(n: int) -> bytes:
    return b":" + str(n).encode() + CRLF


def bulk(v) -> bytes:
    if v is None:
        return b"$-1" + CRLF
    if isinstance(v, str):
        v = v.encode("utf-8", "surrogateescape")
    return b"$" + str(len(v)).encode() + CRLF + v + CRLF


def array(items) -> bytes:
    if items is None:
        return b"*-1" + CRLF
    out = [b"*" + str(len(items)).encode() + CRLF]
    for it in items:
        if isinstance(it, int):
            out.append(integer(it))
        elif isinstance(it, (list, tuple)):
            out.append(array(it))
        else:
            out.append(bulk(it))
    return b"".join(out)


class RedisConnectionContext(ConnectionContext):
    """RESP over the shared messenger: replies pair with commands by
    ORDER, so handlers run one at a time per connection."""

    ordered_responses = True

    def __init__(self):
        self._buf = bytearray()
        self._seq = 0

    def feed(self, data: bytes) -> list:
        self._buf.extend(data)
        # Native batch parse first (servebatch.cc): one C++ pass over the
        # drained buffer for the strict array-of-bulks grammar every
        # pipelined client speaks. It consumes nothing and returns None
        # on anything else (inline commands, malformed lengths), so the
        # Python parser below re-parses the SAME bytes and error
        # behavior stays identical to a build without the native module.
        cmds = None
        if _yb_rb is not None:
            parsed = _yb_rb.parse_resp(self._buf)
            if parsed is not None:
                cmds, consumed = parsed
                if consumed:
                    del self._buf[:consumed]
        if cmds is None:
            cmds = parse_commands(self._buf)
        if not cmds:
            return []
        # One call carries the whole pipelined burst: the service
        # batches runs of GET/SET into multi-key reads / one flush
        # (replies stay in command order inside the single response).
        call = (self._seq, "redis_batch", cmds)
        self._seq += 1
        return [call]

    def serialize(self, response) -> bytes:
        _seq, status, body = response
        if status == "ok":
            return body
        return error(str(body))
