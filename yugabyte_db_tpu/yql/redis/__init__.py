"""YEDIS: the Redis-compatible frontend.

Reference analog: src/yb/yql/redis/redisserver/ — RedisServer riding the
shared rpc::Messenger through RedisConnectionContext (redis_rpc.cc), a
RESP parser (redis_parser.cc), and the command registry
(redis_commands.cc:69-154) lowering commands onto DocDB rows
(redis_operation.cc). Here Redis data maps onto one framework table:

    (rkey STRING hash, field STRING range) -> value STRING (+ type tag)

so strings are (rkey, "") rows, hash fields (rkey, f) rows, and set
members (rkey, m) marker rows; TTL rides the storage engine's native
per-version expiry.
"""

from yugabyte_db_tpu.yql.redis.server import RedisServer

__all__ = ["RedisServer"]
