"""RedisServer: RESP commands lowered onto framework rows.

Reference analog: src/yb/yql/redis/redisserver/redis_service.cc + the
per-command handlers of redis_commands.cc (~85 commands there; the core
string/hash/set/TTL/server families here) executing as DocDB operations
(redis_operation.cc).

Data model (module docstring of yql.redis): one table keyed
(rkey hash, field range) with a value column; strings use field "",
hashes their field names, sets their members (value ignored). TTL maps
to the engine's native per-version expiry, so expiration needs no
background reaper — exactly the reference's DocDB TTL reuse.
"""

from __future__ import annotations

import fnmatch
import threading

from yugabyte_db_tpu.client import YBSession
from yugabyte_db_tpu.client.client import YBClient
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.encoding import prefix_successor
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.rpc.messenger import Messenger
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.yql.redis import resp

REDIS_TABLE = "sys.redis"

COLUMNS = [
    ColumnSchema("rkey", DataType.STRING, ColumnKind.HASH),
    ColumnSchema("field", DataType.STRING, ColumnKind.RANGE),
    ColumnSchema("value", DataType.STRING),
]


class RedisServiceImpl:
    def __init__(self, client: YBClient, num_tablets: int = 4,
                 replication_factor: int = 3):
        self.client = client
        try:
            self.table = client.create_table(
                REDIS_TABLE, COLUMNS, num_tablets=num_tablets,
                replication_factor=replication_factor)
        except Exception as e:  # noqa: BLE001
            if "exist" not in str(e).lower():
                raise
            self.table = client.open_table(REDIS_TABLE)
        self.session = YBSession(client)
        self.commands_served = 0
        # Redis guarantees per-command atomicity; the messenger runs
        # handlers for DIFFERENT connections concurrently on a worker
        # pool, and one session's op buffer is shared — so commands are
        # serialized here (the single-shard execution model of the
        # reference's redis proxy, one op per batcher flush).
        self._lock = threading.Lock()

    # -- row helpers ---------------------------------------------------------
    def _get(self, rkey: str, field: str):
        row = self.session.get(self.table, {"rkey": rkey, "field": field})
        return None if row is None else row[2]

    def _put(self, rkey: str, field: str, value: str,
             ttl_us: int | None = None):
        # TTLs ride as RELATIVE microseconds; the tablet leader resolves
        # them against the write's own stamped hybrid time (client wall
        # clocks and tablet hybrid clocks legitimately disagree).
        self.session.insert(self.table, {
            "rkey": rkey, "field": field, "value": value,
        }, ttl_us=ttl_us)
        self.session.flush()

    def _del(self, rkey: str, field: str):
        self.session.delete(self.table, {"rkey": rkey, "field": field})
        self.session.flush()

    def _fields(self, rkey: str):
        """All (field, value) rows of one redis key (one hash-routed
        range scan over the key's row group)."""
        from yugabyte_db_tpu.models.encoding import encode_doc_key_prefix

        hc = self.table.hash_code({"rkey": rkey})
        lower = encode_doc_key_prefix(hc, [(rkey, DataType.STRING)], [])
        spec = ScanSpec(lower=lower, upper=prefix_successor(lower),
                        projection=["field", "value"])
        return self.session.scan(self.table, spec).rows

    # -- dispatch ------------------------------------------------------------
    def handle(self, args: list[bytes]) -> bytes:
        self.commands_served += 1
        name = args[0].decode().upper()
        fn = getattr(self, "cmd_" + name.lower(), None)
        if fn is None:
            return resp.error(f"unknown command '{name}'")
        try:
            with self._lock:
                try:
                    return fn([a.decode("utf-8", "surrogateescape")
                               for a in args[1:]])
                finally:
                    # A handler that errored mid-buffer must not leak its
                    # partial ops into the next command's flush.
                    self.session._ops.clear()
        except IndexError:
            return resp.error(
                f"wrong number of arguments for '{name.lower()}' command")

    # -- server commands -----------------------------------------------------
    def cmd_ping(self, a):
        return resp.bulk(a[0]) if a else resp.simple("PONG")

    def cmd_echo(self, a):
        return resp.bulk(a[0])

    def cmd_select(self, a):
        return resp.simple("OK")  # single logical database

    def cmd_command(self, a):
        return resp.array([])

    def cmd_info(self, a):
        return resp.bulk(f"# Server\nredis_compat:yedis\n"
                         f"commands_served:{self.commands_served}\n")

    # -- strings -------------------------------------------------------------
    def cmd_set(self, a):
        key, value = a[0], a[1]
        ttl_us = None
        i = 2
        nx = xx = False
        while i < len(a):
            opt = a[i].upper()
            if opt == "EX":
                ttl_us = int(float(a[i + 1]) * 1_000_000)
                i += 2
            elif opt == "PX":
                ttl_us = int(float(a[i + 1]) * 1_000)
                i += 2
            elif opt == "NX":
                nx = True
                i += 1
            elif opt == "XX":
                xx = True
                i += 1
            else:
                return resp.error("syntax error")
        if nx or xx:
            cur = self._get(key, "")
            if (nx and cur is not None) or (xx and cur is None):
                return resp.bulk(None)
        self._put(key, "", value, ttl_us)
        return resp.simple("OK")

    def cmd_setex(self, a):
        self._put(a[0], "", a[2], int(float(a[1]) * 1_000_000))
        return resp.simple("OK")

    def cmd_setnx(self, a):
        if self._get(a[0], "") is not None:
            return resp.integer(0)
        self._put(a[0], "", a[1])
        return resp.integer(1)

    def cmd_get(self, a):
        return resp.bulk(self._get(a[0], ""))

    def cmd_getset(self, a):
        old = self._get(a[0], "")
        self._put(a[0], "", a[1])
        return resp.bulk(old)

    def cmd_append(self, a):
        cur = self._get(a[0], "") or ""
        new = cur + a[1]
        self._put(a[0], "", new)
        return resp.integer(len(new))

    def cmd_strlen(self, a):
        v = self._get(a[0], "")
        return resp.integer(len(v) if v else 0)

    def cmd_mget(self, a):
        return resp.array([self._get(k, "") for k in a])

    def cmd_mset(self, a):
        if not a or len(a) % 2:
            return resp.error("wrong number of arguments for 'mset' command")
        for i in range(0, len(a), 2):
            self.session.insert(self.table, {
                "rkey": a[i], "field": "", "value": a[i + 1]})
        self.session.flush()
        return resp.simple("OK")

    def cmd_incr(self, a):
        return self._incrby(a[0], 1)

    def cmd_incrby(self, a):
        return self._incrby(a[0], int(a[1]))

    def cmd_decr(self, a):
        return self._incrby(a[0], -1)

    def cmd_decrby(self, a):
        return self._incrby(a[0], -int(a[1]))

    def _incrby(self, key, by):
        cur = self._get(key, "")
        if cur is not None:
            try:
                cur = int(cur)
            except ValueError:
                return resp.error(
                    "value is not an integer or out of range")
        new = (cur or 0) + by
        self._put(key, "", str(new))
        return resp.integer(new)

    def cmd_del(self, a):
        n = 0
        for key in a:
            rows = self._fields(key)
            for field, _v in rows:
                self.session.delete(self.table,
                                    {"rkey": key, "field": field})
            if rows:
                n += 1
        self.session.flush()
        return resp.integer(n)

    def cmd_exists(self, a):
        return resp.integer(sum(1 for k in a if self._fields(k)))

    def cmd_expire(self, a):
        key = a[0]
        rows = self._fields(key)
        if not rows:
            return resp.integer(0)
        ttl_us = int(float(a[1]) * 1_000_000)
        for field, value in rows:
            self._put(key, field, value, ttl_us)
        return resp.integer(1)

    def cmd_ttl(self, a):
        # Without surfacing expire_ht through the read path this reports
        # -1 (no TTL) for live keys, -2 for missing (reference's contract
        # subset).
        return resp.integer(-1 if self._fields(a[0]) else -2)

    def cmd_keys(self, a):
        pattern = a[0] if a else "*"
        spec = ScanSpec(projection=["rkey"])
        rows = self.session.scan(self.table, spec).rows
        keys = sorted({r[0] for r in rows})
        return resp.array([k for k in keys
                           if fnmatch.fnmatchcase(k, pattern)])

    # -- hashes --------------------------------------------------------------
    def cmd_hset(self, a):
        key = a[0]
        if len(a) < 3 or len(a) % 2 == 0:
            return resp.error("wrong number of arguments for 'hset' command")
        n = 0
        for i in range(1, len(a), 2):
            if self._get(key, "\x01" + a[i]) is None:
                n += 1
            self.session.insert(self.table, {
                "rkey": key, "field": "\x01" + a[i], "value": a[i + 1]})
        self.session.flush()
        return resp.integer(n)

    def cmd_hmset(self, a):
        self.cmd_hset(a)
        return resp.simple("OK")

    def cmd_hget(self, a):
        return resp.bulk(self._get(a[0], "\x01" + a[1]))

    def cmd_hmget(self, a):
        return resp.array([self._get(a[0], "\x01" + f) for f in a[1:]])

    def cmd_hdel(self, a):
        n = 0
        for f in a[1:]:
            if self._get(a[0], "\x01" + f) is not None:
                self._del(a[0], "\x01" + f)
                n += 1
        return resp.integer(n)

    def cmd_hexists(self, a):
        return resp.integer(
            0 if self._get(a[0], "\x01" + a[1]) is None else 1)

    def _hash_rows(self, key):
        return [(f[1:], v) for f, v in self._fields(key)
                if f.startswith("\x01")]

    def cmd_hgetall(self, a):
        out = []
        for f, v in self._hash_rows(a[0]):
            out.extend([f, v])
        return resp.array(out)

    def cmd_hkeys(self, a):
        return resp.array([f for f, _v in self._hash_rows(a[0])])

    def cmd_hvals(self, a):
        return resp.array([v for _f, v in self._hash_rows(a[0])])

    def cmd_hlen(self, a):
        return resp.integer(len(self._hash_rows(a[0])))

    # -- sets ----------------------------------------------------------------
    def cmd_sadd(self, a):
        key = a[0]
        n = 0
        for m in a[1:]:
            if self._get(key, "\x02" + m) is None:
                n += 1
            self.session.insert(self.table, {
                "rkey": key, "field": "\x02" + m, "value": ""})
        self.session.flush()
        return resp.integer(n)

    def cmd_srem(self, a):
        n = 0
        for m in a[1:]:
            if self._get(a[0], "\x02" + m) is not None:
                self._del(a[0], "\x02" + m)
                n += 1
        return resp.integer(n)

    def cmd_smembers(self, a):
        return resp.array(sorted(
            f[1:] for f, _v in self._fields(a[0])
            if f.startswith("\x02")))

    def cmd_sismember(self, a):
        return resp.integer(
            0 if self._get(a[0], "\x02" + a[1]) is None else 1)

    def cmd_scard(self, a):
        return resp.integer(len([1 for f, _v in self._fields(a[0])
                                 if f.startswith("\x02")]))


class RedisServer:
    """RESP wire server over the messenger (the yb-tserver's port-6379
    proxy, tablet_server_main.cc:191)."""

    def __init__(self, client: YBClient, messenger: Messenger | None = None,
                 **kwargs):
        self.service = RedisServiceImpl(client, **kwargs)
        self._own_messenger = messenger is None
        self.messenger = messenger or Messenger(name="redis")

    def listen(self, host: str = "127.0.0.1", port: int = 0):
        def handler(_method, args):
            return self.service.handle(args)

        from yugabyte_db_tpu.yql.redis.resp import RedisConnectionContext

        return self.messenger.listen(host, port, handler,
                                     context_factory=RedisConnectionContext)

    def shutdown(self) -> None:
        if self._own_messenger:
            self.messenger.shutdown()
