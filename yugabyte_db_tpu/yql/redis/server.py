"""RedisServer: RESP commands lowered onto framework rows.

Reference analog: src/yb/yql/redis/redisserver/redis_service.cc + the
per-command registry of redis_commands.cc:69-154 (~85 commands)
executing as DocDB operations (redis_operation.cc). This server covers
the same families: strings, hashes, sets, sorted sets, lists,
time series (TS*), TTL (EXPIRE/PEXPIRE/EXPIREAT/PERSIST/...), rename,
multi-database (CREATEDB/LISTDB/DELETEDB/SELECT), FLUSHDB/FLUSHALL,
AUTH/CONFIG, and pubsub/MONITOR with real server-push frames.

Data model (module docstring of yql.redis): one table keyed
(rkey hash, field range) with a value column. The stored rkey is
"<db>\\x00<user key>" (database namespacing); the field's first byte
encodes the datatype, mirroring how the reference's RedisWriteOperation
tags subdocument types:

  ""            string value
  "\\x01"+f     hash field f
  "\\x02"+m     set member m
  "\\x03"+m     sorted-set member m      (value = score)
  "\\x04"+ts17  time-series entry        (ts17: order-preserving hex)
  "\\x05"+idx19 list element             (idx19: order-preserving dec)

TTL maps to the engine's native per-version expiry, so expiration needs
no background reaper — exactly the reference's DocDB TTL reuse.
"""

from __future__ import annotations

import fnmatch
import threading
import time

from yugabyte_db_tpu.client import YBSession
from yugabyte_db_tpu.client.client import YBClient
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.encoding import prefix_successor
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.rpc.messenger import Messenger
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.utils.metrics import (count_swallowed,
                                           observe_serve_batch)
from yugabyte_db_tpu.yql.redis import resp

try:
    from yugabyte_db_tpu.native import yb_rb as _yb_rb
except ImportError:  # native serving module not built: Python path only
    _yb_rb = None

REDIS_TABLE = "sys.redis"

COLUMNS = [
    ColumnSchema("rkey", DataType.STRING, ColumnKind.HASH),
    ColumnSchema("field", DataType.STRING, ColumnKind.RANGE),
    ColumnSchema("value", DataType.STRING),
]

# field-name type tags
_HASH, _SET, _ZSET, _TS, _LIST = "\x01", "\x02", "\x03", "\x04", "\x05"
_TS_OFF = 1 << 63
_LIST_OFF = 5 * 10 ** 18
_DB_REGISTRY = "\x00dbs"   # registry rows: rkey=_DB_REGISTRY, field=<db>


def _enc_ts(ts: int) -> str:
    if not -_TS_OFF <= ts < _TS_OFF:
        raise ValueError("timestamp out of range")
    return format(ts + _TS_OFF, "017x")


def _dec_ts(field: str) -> int:
    return int(field[1:], 16) - _TS_OFF


def _fmt_score(s: float) -> str:
    return str(int(s)) if s == int(s) else repr(s)


class _ConnState:
    __slots__ = ("db", "authed", "subs", "psubs", "monitor")

    def __init__(self):
        self.db = "0"
        self.authed = False
        self.subs: set[str] = set()
        self.psubs: set[str] = set()
        self.monitor = False


class RedisServiceImpl:
    def __init__(self, client: YBClient, num_tablets: int = 4,
                 replication_factor: int = 3, messenger=None):
        self.client = client
        self.messenger = messenger
        try:
            self.table = client.create_table(
                REDIS_TABLE, COLUMNS, num_tablets=num_tablets,
                replication_factor=replication_factor)
        except Exception as e:  # noqa: BLE001
            if "exist" not in str(e).lower():
                raise
            self.table = client.open_table(REDIS_TABLE)
        self.session = YBSession(client)
        self.commands_served = 0
        self.config: dict[str, str] = {}
        # Redis guarantees per-command atomicity; the messenger runs
        # handlers for DIFFERENT connections concurrently on a worker
        # pool, and one session's op buffer is shared — so commands are
        # serialized here (the single-shard execution model of the
        # reference's redis proxy, one op per batcher flush).
        self._lock = threading.Lock()
        self._states: dict = {}          # conn -> _ConnState
        self._default_state = _ConnState()
        self._cur = self._default_state  # state of the command in flight
        self._subscribers: dict = {}     # conn -> _ConnState (subs alive)
        self._monitors: set = set()      # conns in MONITOR mode
        if not self._registry_dbs():
            self._registry_add("0")

    # -- db registry ---------------------------------------------------------
    def _registry_dbs(self) -> list[str]:
        hc = self.table.hash_code({"rkey": _DB_REGISTRY})
        from yugabyte_db_tpu.models.encoding import encode_doc_key_prefix

        lower = encode_doc_key_prefix(hc, [(_DB_REGISTRY, DataType.STRING)], [])
        spec = ScanSpec(lower=lower, upper=prefix_successor(lower),
                        projection=["field"])
        return sorted(r[0] for r in self.session.scan(self.table, spec).rows)

    def _registry_add(self, db: str) -> None:
        self.session.insert(self.table, {"rkey": _DB_REGISTRY,
                                         "field": db, "value": ""})
        self.session.flush()

    # -- row helpers ---------------------------------------------------------
    def _rk(self, key: str) -> str:
        """Storage rkey: current database + NUL + user key."""
        return f"{self._cur.db}\x00{key}"

    def _get(self, key: str, field: str):
        row = self.session.get(self.table,
                               {"rkey": self._rk(key), "field": field})
        return None if row is None else row[2]

    def _put(self, key: str, field: str, value: str,
             ttl_us: int | None = None, flush: bool = True):
        # TTLs ride as RELATIVE microseconds; the tablet leader resolves
        # them against the write's own stamped hybrid time (client wall
        # clocks and tablet hybrid clocks legitimately disagree).
        self.session.insert(self.table, {
            "rkey": self._rk(key), "field": field, "value": value,
        }, ttl_us=ttl_us)
        if flush:
            self.session.flush()

    def _del(self, key: str, field: str, flush: bool = True):
        self.session.delete(self.table,
                            {"rkey": self._rk(key), "field": field})
        if flush:
            self.session.flush()

    def _fields(self, key: str):
        """All (field, value) rows of one redis key (one hash-routed
        range scan over the key's row group)."""
        from yugabyte_db_tpu.models.encoding import encode_doc_key_prefix

        rkey = self._rk(key)
        hc = self.table.hash_code({"rkey": rkey})
        lower = encode_doc_key_prefix(hc, [(rkey, DataType.STRING)], [])
        spec = ScanSpec(lower=lower, upper=prefix_successor(lower),
                        projection=["field", "value"])
        return self.session.scan(self.table, spec).rows

    def _typed(self, key: str, tag: str):
        return [(f[1:], v) for f, v in self._fields(key)
                if f.startswith(tag)]

    def _all_rows(self, db: str | None):
        """(rkey, field) of every row in one db (None = every db)."""
        rows = self.session.scan(
            self.table, ScanSpec(projection=["rkey", "field"])).rows
        out = []
        for rk, f in rows:
            if rk == _DB_REGISTRY:
                continue
            if db is None or rk.startswith(db + "\x00"):
                out.append((rk, f))
        return out

    # -- dispatch ------------------------------------------------------------
    _PREAUTH = frozenset(["AUTH", "PING", "QUIT", "COMMAND"])

    def handle_batch(self, cmds: list[list[bytes]], conn=None) -> bytes:
        """Pipelined execution: one call per socket read's worth of
        parsed commands. Runs of plain GETs serve through ONE batched
        multi-key read (ts.scan_batch via session.get_many) and runs of
        plain SETs buffer into ONE flush — the shape that makes the
        reference's RedisPipelinedKeyValue numbers possible (its proxy
        batches ops through the async client; docs/yb-perf-v1.0.7.md:
        18-19). Everything else takes the per-command path."""
        observe_serve_batch("redis", len(cmds))
        out = []
        i = 0
        n = len(cmds)
        while i < n:
            c = cmds[i]
            name = c[0].decode().upper() if c else ""
            # Reply-count invariant: the batch MUST emit exactly one
            # reply per command even when a storage call throws — a
            # short reply stream would permanently desync the RESP
            # pairing on this connection.
            if name == "GET" and len(c) == 2:
                j = i
                keys = []
                while j < n and len(cmds[j]) == 2 and \
                        cmds[j][0].decode().upper() == "GET":
                    keys.append(cmds[j][1].decode("utf-8",
                                                  "surrogateescape"))
                    j += 1
                if j - i > 1:
                    try:
                        out.append(self._batch_get(keys, conn))
                    except Exception as e:  # noqa: BLE001
                        out.append(resp.error(str(e)) * len(keys))
                    with self._lock:
                        self.commands_served += j - i
                    i = j
                    continue
            elif name == "SET" and len(c) == 3:
                j = i
                sets = []
                while j < n and len(cmds[j]) == 3 and \
                        cmds[j][0].decode().upper() == "SET":
                    sets.append(
                        (cmds[j][1].decode("utf-8", "surrogateescape"),
                         cmds[j][2].decode("utf-8", "surrogateescape")))
                    j += 1
                if j - i > 1:
                    try:
                        out.append(self._batch_set(sets, conn))
                    except Exception as e:  # noqa: BLE001
                        out.append(resp.error(str(e)) * len(sets))
                    with self._lock:
                        self.commands_served += j - i
                    i = j
                    continue
            try:
                out.append(self.handle(c, conn))
            except Exception as e:  # noqa: BLE001
                out.append(resp.error(str(e)))
            i += 1
        return b"".join(out)

    def _enter(self, conn, name: str) -> bytes | None:
        """Per-command session state + auth gate (callers hold _lock)."""
        if conn is None:
            self._cur = self._default_state
        else:
            st = self._states.get(conn)
            if st is None:
                st = self._states[conn] = _ConnState()
            self._cur = st
        if self.config.get("requirepass") and not self._cur.authed \
                and name not in self._PREAUTH:
            return resp.error("NOAUTH Authentication required.")
        return None

    def _batch_get(self, keys: list[str], conn) -> bytes:
        # Session state (auth, _cur.db for rkeys, MONITOR feeds) resolves
        # under the lock; the storage fetch runs OUTSIDE it so other
        # connections' commands aren't serialized behind this batch's
        # RPC round-trips. Pipelined GETs are not atomic in Redis (that
        # is MULTI), so interleaved writes between them are legal.
        with self._lock:
            err = self._enter(conn, "GET")
            if err is not None:
                return err * len(keys)
            if self._monitors:
                for k in keys:
                    self._feed_monitors(conn, "GET", [k])
            rkeys = [self._rk(k) for k in keys]
        return b"".join(resp.bulk(v) for v in self._fetch_values(rkeys))

    def _get_values(self, keys: list[str]) -> list:
        """Values of plain string keys (field "") in key order. Callers
        hold _lock (self._cur.db feeds the storage rkey)."""
        return self._fetch_values([self._rk(k) for k in keys])

    def _fetch_values(self, rkeys: list[str]) -> list:
        """Fetch resolved rkeys — the native batch serving path when
        every hop is eligible (raw stored payload bytes),
        session.get_many otherwise (str). resp.bulk encodes bytes and
        str to IDENTICAL reply bytes: the stored column payload is
        exactly the value's utf-8 surrogateescape encoding (tagcodec
        T_STR). Needs no lock: rkeys are pre-resolved and the session
        handles are immutable."""
        values = self._native_get_values(rkeys)
        if values is None:
            values = [False] * len(rkeys)
        # False entries: native couldn't answer definitively (module
        # absent, tablet fallback, non-string stored value) — serve
        # those through the canonical Python read path.
        need = [i for i, v in enumerate(values) if v is False]
        if need:
            rows = self.session.get_many(
                self.table,
                [{"rkey": rkeys[i], "field": ""} for i in need])
            for i, r in zip(need, rows):
                values[i] = None if r is None else r[2]
        return values

    def _native_get_values(self, rkeys: list[str]):
        """One ts.redis_read_batch RPC per tablet for a batch of point
        keys, served from the native memtable (docs/serving-path.md).
        None = native path unavailable; per-key False = fall back for
        that key (a tablet replying "fallback" leaves its whole group
        False)."""
        if _yb_rb is None:
            return None
        try:
            locs = self.client.meta_cache.locations(self.table.name)
            tablets = sorted(locs.tablets,
                             key=lambda t: t.partition_start)
            routed = _yb_rb.encode_point_keys(
                (3,), (3,), [(rk, "") for rk in rkeys],
                [t.partition_start for t in tablets], 1)
        except Exception as e:  # noqa: BLE001 — Python path is canonical
            count_swallowed("redis.native_route", e)
            return None
        groups: dict[int, tuple[list, list]] = {}
        for i, (part, key) in enumerate(routed):
            g = groups.get(part)
            if g is None:
                g = groups[part] = ([], [])
            g[0].append(i)
            g[1].append(key)
        values: list = [False] * len(rkeys)
        col_id = self.table.col_id["value"]
        for part, (idxs, keys) in groups.items():
            try:
                r = self.client.tablet_rpc(
                    self.table.name, tablets[part],
                    "ts.redis_read_batch",
                    {"keys": keys, "col_id": col_id})
            except Exception as e:  # noqa: BLE001 — per-group fallback
                count_swallowed("redis.native_read_batch", e)
                continue
            if r.get("fallback"):
                continue
            for i, v in zip(idxs, r["values"]):
                values[i] = v
        return values

    def _batch_set(self, sets: list[tuple[str, str]], conn) -> bytes:
        with self._lock:
            err = self._enter(conn, "SET")
            if err is not None:
                return err * len(sets)
            if self._monitors:
                for k, v in sets:
                    self._feed_monitors(conn, "SET", [k, v])
            try:
                for k, v in sets:
                    self.session.insert(self.table, {
                        "rkey": self._rk(k), "field": "", "value": v})
                self.session.flush()
            finally:
                self.session._ops.clear()
            return resp.simple("OK") * len(sets)

    def handle(self, args: list[bytes], conn=None) -> bytes:
        with self._lock:
            self.commands_served += 1
        name = args[0].decode().upper()
        fn = getattr(self, "cmd_" + name.lower(), None)
        if fn is None:
            return resp.error(f"unknown command '{name}'")
        try:
            with self._lock:
                err = self._enter(conn, name)
                if err is not None:
                    return err
                decoded = [a.decode("utf-8", "surrogateescape")
                           for a in args[1:]]
                self._feed_monitors(conn, name, decoded)
                try:
                    return fn(decoded, conn) if getattr(
                        fn, "wants_conn", False) else fn(decoded)
                finally:
                    # A handler that errored mid-buffer must not leak its
                    # partial ops into the next command's flush.
                    self.session._ops.clear()
        except IndexError:
            return resp.error(
                f"wrong number of arguments for '{name.lower()}' command")
        except ValueError:
            return resp.error("value is not an integer or out of range")

    def _push(self, conn, data: bytes) -> None:
        if self.messenger is not None and conn is not None \
                and not getattr(conn, "closed", False):
            self.messenger.send_on(conn, data)

    def _feed_monitors(self, conn, name, args) -> None:
        if not self._monitors:
            return
        line = " ".join([f"{time.time():.6f}", f'"{name}"']
                        + [f'"{a}"' for a in args])
        for mc in list(self._monitors):
            if getattr(mc, "closed", False):
                self._monitors.discard(mc)
            elif mc is not conn:
                self._push(mc, resp.simple(line))

    # -- server commands -----------------------------------------------------
    def cmd_ping(self, a):
        return resp.bulk(a[0]) if a else resp.simple("PONG")

    def cmd_echo(self, a):
        return resp.bulk(a[0])

    def cmd_quit(self, a):
        return resp.simple("OK")

    def cmd_select(self, a):
        db = a[0]
        if db not in self._registry_dbs():
            return resp.error(f"DB {db} does not exist")
        self._cur.db = db
        return resp.simple("OK")

    def cmd_createdb(self, a):
        if not a[0] or "\x00" in a[0]:
            return resp.error("invalid database name")
        self._registry_add(a[0])
        return resp.simple("OK")

    def cmd_listdb(self, a):
        return resp.array(self._registry_dbs())

    def cmd_deletedb(self, a):
        db = a[0]
        dbs = self._registry_dbs()
        if db not in dbs:
            return resp.error(f"DB {db} does not exist")
        if db == "0":
            return resp.error("cannot delete DB 0")
        for rk, f in self._all_rows(db):
            self.session.delete(self.table, {"rkey": rk, "field": f})
        self.session.delete(self.table, {"rkey": _DB_REGISTRY, "field": db})
        self.session.flush()
        return resp.simple("OK")

    def cmd_command(self, a):
        return resp.array([])

    def cmd_info(self, a):
        return resp.bulk(f"# Server\nredis_compat:yedis\n"
                         f"commands_served:{self.commands_served}\n")

    def cmd_role(self, a):
        return resp.array(["master"])

    def cmd_auth(self, a):
        pw = self.config.get("requirepass")
        if pw is None:
            return resp.error(
                "Client sent AUTH, but no password is set")
        if a[0] != pw:
            return resp.error("invalid password")
        self._cur.authed = True
        return resp.simple("OK")

    def cmd_config(self, a):
        sub = a[0].upper()
        if sub == "SET":
            # cmd_* handlers run under self._lock: handle()/handle_batch
            # dispatch them via getattr("cmd_" + name), which the call
            # graph cannot resolve into an edge.
            # yb-lint: disable=iraces/guarded-read-unguarded-write
            self.config[a[1].lower()] = a[2]
            return resp.simple("OK")
        if sub == "GET":
            k = a[1].lower()
            if k in self.config:
                return resp.array([k, self.config[k]])
            return resp.array([])
        return resp.error(f"unknown CONFIG subcommand {a[0]}")

    def cmd_cluster(self, a):
        if a and a[0].upper() == "INFO":
            return resp.bulk("cluster_enabled:0\r\ncluster_state:ok\r\n")
        return resp.array([])

    def cmd_debugsleep(self, a):
        time.sleep(float(a[0]))
        return resp.simple("OK")

    def cmd_monitor(self, a, conn=None):
        if conn is not None:
            # Runs under self._lock via handle()'s getattr dispatch,
            # invisible to the call graph (see cmd_config).
            # yb-lint: disable=iraces/unguarded-shared-write
            self._monitors.add(conn)
        return resp.simple("OK")
    cmd_monitor.wants_conn = True

    def cmd_flushdb(self, a):
        for rk, f in self._all_rows(self._cur.db):
            self.session.delete(self.table, {"rkey": rk, "field": f})
        self.session.flush()
        return resp.simple("OK")

    def cmd_flushall(self, a):
        for rk, f in self._all_rows(None):
            self.session.delete(self.table, {"rkey": rk, "field": f})
        self.session.flush()
        return resp.simple("OK")

    # -- pubsub --------------------------------------------------------------
    def cmd_publish(self, a):
        channel, message = a[0], a[1]
        n = 0
        for conn, st in list(self._subscribers.items()):
            if getattr(conn, "closed", False):
                del self._subscribers[conn]
                continue
            if channel in st.subs:
                self._push(conn, resp.array(["message", channel, message]))
                n += 1
            for pat in st.psubs:
                if fnmatch.fnmatchcase(channel, pat):
                    self._push(conn, resp.array(
                        ["pmessage", pat, channel, message]))
                    n += 1
        return resp.integer(n)

    def _sub_frames(self, conn, chans, pats, subscribe: bool) -> bytes:
        st = self._cur
        out = []
        for ch in chans:
            if subscribe:
                st.subs.add(ch)
            else:
                st.subs.discard(ch)
            out.append(resp.array(
                ["subscribe" if subscribe else "unsubscribe", ch,
                 len(st.subs) + len(st.psubs)]))
        for p in pats:
            if subscribe:
                st.psubs.add(p)
            else:
                st.psubs.discard(p)
            out.append(resp.array(
                ["psubscribe" if subscribe else "punsubscribe", p,
                 len(st.subs) + len(st.psubs)]))
        if conn is not None:
            if st.subs or st.psubs:
                self._subscribers[conn] = st
            else:
                self._subscribers.pop(conn, None)
        return b"".join(out)

    def cmd_subscribe(self, a, conn=None):
        return self._sub_frames(conn, a, [], True)
    cmd_subscribe.wants_conn = True

    def cmd_unsubscribe(self, a, conn=None):
        chans = a if a else sorted(self._cur.subs)
        return self._sub_frames(conn, chans, [], False)
    cmd_unsubscribe.wants_conn = True

    def cmd_psubscribe(self, a, conn=None):
        return self._sub_frames(conn, [], a, True)
    cmd_psubscribe.wants_conn = True

    def cmd_punsubscribe(self, a, conn=None):
        pats = a if a else sorted(self._cur.psubs)
        return self._sub_frames(conn, [], pats, False)
    cmd_punsubscribe.wants_conn = True

    def cmd_pubsub(self, a):
        sub = a[0].upper()
        states = [st for c, st in self._subscribers.items()
                  if not getattr(c, "closed", False)]
        if sub == "CHANNELS":
            pat = a[1] if len(a) > 1 else "*"
            chans = sorted({ch for st in states for ch in st.subs
                            if fnmatch.fnmatchcase(ch, pat)})
            return resp.array(chans)
        if sub == "NUMSUB":
            out = []
            for ch in a[1:]:
                out.extend([ch, sum(1 for st in states if ch in st.subs)])
            return resp.array(out)
        if sub == "NUMPAT":
            return resp.integer(
                len({p for st in states for p in st.psubs}))
        return resp.error(f"unknown PUBSUB subcommand {a[0]}")

    # -- strings -------------------------------------------------------------
    def cmd_set(self, a):
        key, value = a[0], a[1]
        ttl_us = None
        i = 2
        nx = xx = False
        while i < len(a):
            opt = a[i].upper()
            if opt == "EX":
                ttl_us = int(float(a[i + 1]) * 1_000_000)
                i += 2
            elif opt == "PX":
                ttl_us = int(float(a[i + 1]) * 1_000)
                i += 2
            elif opt == "NX":
                nx = True
                i += 1
            elif opt == "XX":
                xx = True
                i += 1
            else:
                return resp.error("syntax error")
        if nx or xx:
            cur = self._get(key, "")
            if (nx and cur is not None) or (xx and cur is None):
                return resp.bulk(None)
        self._put(key, "", value, ttl_us)
        return resp.simple("OK")

    def cmd_setex(self, a):
        self._put(a[0], "", a[2], int(float(a[1]) * 1_000_000))
        return resp.simple("OK")

    def cmd_psetex(self, a):
        self._put(a[0], "", a[2], int(float(a[1]) * 1_000))
        return resp.simple("OK")

    def cmd_setnx(self, a):
        if self._get(a[0], "") is not None:
            return resp.integer(0)
        self._put(a[0], "", a[1])
        return resp.integer(1)

    def cmd_get(self, a):
        return resp.bulk(self._get(a[0], ""))

    def cmd_getset(self, a):
        old = self._get(a[0], "")
        self._put(a[0], "", a[1])
        return resp.bulk(old)

    def cmd_append(self, a):
        cur = self._get(a[0], "") or ""
        new = cur + a[1]
        self._put(a[0], "", new)
        return resp.integer(len(new))

    def cmd_strlen(self, a):
        v = self._get(a[0], "")
        return resp.integer(len(v) if v else 0)

    def cmd_getrange(self, a):
        v = self._get(a[0], "") or ""
        start, end = int(a[1]), int(a[2])
        n = len(v)
        if start < 0:
            start = max(n + start, 0)
        if end < 0:
            end = n + end
        return resp.bulk(v[start:end + 1] if end >= start else "")

    def cmd_setrange(self, a):
        key, off, chunk = a[0], int(a[1]), a[2]
        if off < 0:
            return resp.error("offset is out of range")
        cur = self._get(key, "") or ""
        if len(cur) < off:
            cur = cur + "\x00" * (off - len(cur))
        new = cur[:off] + chunk + cur[off + len(chunk):]
        self._put(key, "", new)
        return resp.integer(len(new))

    def cmd_mget(self, a):
        # Same batched serving path as pipelined GET runs: one native
        # multiget (or one ts.scan_batch) instead of a scan per key.
        return resp.array(self._get_values(list(a)))

    def cmd_mset(self, a):
        if not a or len(a) % 2:
            return resp.error("wrong number of arguments for 'mset' command")
        for i in range(0, len(a), 2):
            self._put(a[i], "", a[i + 1], flush=False)
        self.session.flush()
        return resp.simple("OK")

    def cmd_incr(self, a):
        return self._incrby(a[0], "", 1)

    def cmd_incrby(self, a):
        return self._incrby(a[0], "", int(a[1]))

    def cmd_decr(self, a):
        return self._incrby(a[0], "", -1)

    def cmd_decrby(self, a):
        return self._incrby(a[0], "", -int(a[1]))

    def _incrby(self, key, field, by):
        cur = self._get(key, field)
        if cur is not None:
            try:
                cur = int(cur)
            except ValueError:
                return resp.error(
                    "value is not an integer or out of range")
        new = (cur or 0) + by
        self._put(key, field, str(new))
        return resp.integer(new)

    def cmd_del(self, a):
        n = 0
        for key in a:
            rows = self._fields(key)
            for field, _v in rows:
                self._del(key, field, flush=False)
            if rows:
                n += 1
        self.session.flush()
        return resp.integer(n)

    def cmd_exists(self, a):
        return resp.integer(sum(1 for k in a if self._fields(k)))

    def cmd_rename(self, a):
        src, dst = a[0], a[1]
        rows = self._fields(src)
        if not rows:
            return resp.error("no such key")
        for field, _v in self._fields(dst):
            self._del(dst, field, flush=False)
        for field, value in rows:
            self._put(dst, field, value, flush=False)
            self._del(src, field, flush=False)
        self.session.flush()
        return resp.simple("OK")

    # -- TTL -----------------------------------------------------------------
    def _set_ttl(self, key: str, ttl_us: int | None) -> bytes:
        rows = self._fields(key)
        if not rows:
            return resp.integer(0)
        if ttl_us is not None and ttl_us <= 0:
            return self.cmd_del([key])
        for field, value in rows:
            self._put(key, field, value, ttl_us, flush=False)
        self.session.flush()
        return resp.integer(1)

    def cmd_expire(self, a):
        return self._set_ttl(a[0], int(float(a[1]) * 1_000_000))

    def cmd_pexpire(self, a):
        return self._set_ttl(a[0], int(float(a[1]) * 1_000))

    def cmd_expireat(self, a):
        return self._set_ttl(
            a[0], int((float(a[1]) - time.time()) * 1_000_000))

    def cmd_pexpireat(self, a):
        return self._set_ttl(
            a[0], int(float(a[1]) * 1_000 - time.time() * 1_000_000))

    def cmd_persist(self, a):
        return self._set_ttl(a[0], None)

    def cmd_ttl(self, a):
        # Without surfacing expire_ht through the read path this reports
        # -1 (no TTL) for live keys, -2 for missing (reference's contract
        # subset).
        return resp.integer(-1 if self._fields(a[0]) else -2)

    def cmd_pttl(self, a):
        return resp.integer(-1 if self._fields(a[0]) else -2)

    def cmd_keys(self, a):
        pattern = a[0] if a else "*"
        prefix = self._cur.db + "\x00"
        spec = ScanSpec(projection=["rkey"])
        rows = self.session.scan(self.table, spec).rows
        keys = sorted({r[0][len(prefix):] for r in rows
                       if r[0].startswith(prefix)})
        return resp.array([k for k in keys
                           if fnmatch.fnmatchcase(k, pattern)])

    # -- hashes --------------------------------------------------------------
    def cmd_hset(self, a):
        key = a[0]
        if len(a) < 3 or len(a) % 2 == 0:
            return resp.error("wrong number of arguments for 'hset' command")
        n = 0
        for i in range(1, len(a), 2):
            if self._get(key, _HASH + a[i]) is None:
                n += 1
            self._put(key, _HASH + a[i], a[i + 1], flush=False)
        self.session.flush()
        return resp.integer(n)

    def cmd_hmset(self, a):
        self.cmd_hset(a)
        return resp.simple("OK")

    def cmd_hget(self, a):
        return resp.bulk(self._get(a[0], _HASH + a[1]))

    def cmd_hmget(self, a):
        return resp.array([self._get(a[0], _HASH + f) for f in a[1:]])

    def cmd_hincrby(self, a):
        return self._incrby(a[0], _HASH + a[1], int(a[2]))

    def cmd_hstrlen(self, a):
        v = self._get(a[0], _HASH + a[1])
        return resp.integer(len(v) if v else 0)

    def cmd_hdel(self, a):
        n = 0
        for f in a[1:]:
            if self._get(a[0], _HASH + f) is not None:
                self._del(a[0], _HASH + f)
                n += 1
        return resp.integer(n)

    def cmd_hexists(self, a):
        return resp.integer(
            0 if self._get(a[0], _HASH + a[1]) is None else 1)

    def cmd_hgetall(self, a):
        out = []
        for f, v in self._typed(a[0], _HASH):
            out.extend([f, v])
        return resp.array(out)

    def cmd_hkeys(self, a):
        return resp.array([f for f, _v in self._typed(a[0], _HASH)])

    def cmd_hvals(self, a):
        return resp.array([v for _f, v in self._typed(a[0], _HASH)])

    def cmd_hlen(self, a):
        return resp.integer(len(self._typed(a[0], _HASH)))

    # -- sets ----------------------------------------------------------------
    def cmd_sadd(self, a):
        key = a[0]
        n = 0
        for m in a[1:]:
            if self._get(key, _SET + m) is None:
                n += 1
            self._put(key, _SET + m, "", flush=False)
        self.session.flush()
        return resp.integer(n)

    def cmd_srem(self, a):
        n = 0
        for m in a[1:]:
            if self._get(a[0], _SET + m) is not None:
                self._del(a[0], _SET + m)
                n += 1
        return resp.integer(n)

    def cmd_smembers(self, a):
        return resp.array(sorted(f for f, _v in self._typed(a[0], _SET)))

    def cmd_sismember(self, a):
        return resp.integer(
            0 if self._get(a[0], _SET + a[1]) is None else 1)

    def cmd_scard(self, a):
        return resp.integer(len(self._typed(a[0], _SET)))

    # -- sorted sets ---------------------------------------------------------
    def _zitems(self, key):
        """[(score, member)] sorted by (score, member)."""
        items = [(float(v), f) for f, v in self._typed(key, _ZSET)]
        items.sort()
        return items

    def cmd_zadd(self, a):
        key = a[0]
        i = 1
        ch = False
        while i < len(a) and a[i].upper() in ("NX", "XX", "CH", "INCR"):
            if a[i].upper() == "CH":
                ch = True
                i += 1
            else:
                return resp.error(
                    f"ZADD option {a[i]} is not supported")
        pairs = a[i:]
        if not pairs or len(pairs) % 2:
            return resp.error("syntax error")
        added = changed = 0
        for j in range(0, len(pairs), 2):
            score = float(pairs[j])
            member = pairs[j + 1]
            old = self._get(key, _ZSET + member)
            if old is None:
                added += 1
            elif float(old) != score:
                changed += 1
            self._put(key, _ZSET + member, repr(score), flush=False)
        self.session.flush()
        return resp.integer(added + changed if ch else added)

    def cmd_zrem(self, a):
        n = 0
        for m in a[1:]:
            if self._get(a[0], _ZSET + m) is not None:
                self._del(a[0], _ZSET + m)
                n += 1
        return resp.integer(n)

    def cmd_zscore(self, a):
        v = self._get(a[0], _ZSET + a[1])
        return resp.bulk(None if v is None else _fmt_score(float(v)))

    def cmd_zcard(self, a):
        return resp.integer(len(self._typed(a[0], _ZSET)))

    def _zrange_out(self, items, withscores):
        out = []
        for score, member in items:
            out.append(member)
            if withscores:
                out.append(_fmt_score(score))
        return resp.array(out)

    def _rank_slice(self, items, start, stop):
        n = len(items)
        if start < 0:
            start = max(n + start, 0)
        if stop < 0:
            stop = n + stop
        return items[start:stop + 1] if stop >= start else []

    def cmd_zrange(self, a):
        withscores = len(a) > 3 and a[3].upper() == "WITHSCORES"
        items = self._rank_slice(self._zitems(a[0]), int(a[1]), int(a[2]))
        return self._zrange_out(items, withscores)

    def cmd_zrevrange(self, a):
        withscores = len(a) > 3 and a[3].upper() == "WITHSCORES"
        items = self._rank_slice(self._zitems(a[0])[::-1],
                                 int(a[1]), int(a[2]))
        return self._zrange_out(items, withscores)

    @staticmethod
    def _score_bound(s: str, is_min: bool):
        """min/max bound -> (value, exclusive)."""
        excl = s.startswith("(")
        if excl:
            s = s[1:]
        if s in ("-inf", "+inf", "inf"):
            return float(s.replace("+", "")), excl
        return float(s), excl

    def cmd_zrangebyscore(self, a):
        lo, lo_x = self._score_bound(a[1], True)
        hi, hi_x = self._score_bound(a[2], False)
        withscores = len(a) > 3 and a[3].upper() == "WITHSCORES"
        items = [(s, m) for s, m in self._zitems(a[0])
                 if (s > lo if lo_x else s >= lo)
                 and (s < hi if hi_x else s <= hi)]
        return self._zrange_out(items, withscores)

    # -- lists (reference v1.2.4 surface: push/pop/len) ----------------------
    def _list_items(self, key):
        """[(index, value)] in list order."""
        return sorted((int(f) - _LIST_OFF, v)
                      for f, v in self._typed(key, _LIST))

    def cmd_lpush(self, a):
        items = self._list_items(a[0])
        left = items[0][0] if items else 0
        for i, v in enumerate(a[1:]):
            self._put(a[0], _LIST + f"{left - 1 - i + _LIST_OFF:019d}", v,
                      flush=False)
        self.session.flush()
        return resp.integer(len(items) + len(a) - 1)

    def cmd_rpush(self, a):
        items = self._list_items(a[0])
        right = items[-1][0] if items else 0
        for i, v in enumerate(a[1:]):
            self._put(a[0], _LIST + f"{right + 1 + i + _LIST_OFF:019d}", v,
                      flush=False)
        self.session.flush()
        return resp.integer(len(items) + len(a) - 1)

    def cmd_lpop(self, a):
        items = self._list_items(a[0])
        if not items:
            return resp.bulk(None)
        idx, v = items[0]
        self._del(a[0], _LIST + f"{idx + _LIST_OFF:019d}")
        return resp.bulk(v)

    def cmd_rpop(self, a):
        items = self._list_items(a[0])
        if not items:
            return resp.bulk(None)
        idx, v = items[-1]
        self._del(a[0], _LIST + f"{idx + _LIST_OFF:019d}")
        return resp.bulk(v)

    def cmd_llen(self, a):
        return resp.integer(len(self._typed(a[0], _LIST)))

    # -- time series ---------------------------------------------------------
    def cmd_tsadd(self, a):
        key = a[0]
        pairs = a[1:]
        if not pairs or len(pairs) % 2:
            return resp.error("wrong number of arguments for 'tsadd' command")
        for i in range(0, len(pairs), 2):
            self._put(key, _TS + _enc_ts(int(pairs[i])), pairs[i + 1],
                      flush=False)
        self.session.flush()
        return resp.simple("OK")

    def cmd_tsget(self, a):
        return resp.bulk(self._get(a[0], _TS + _enc_ts(int(a[1]))))

    def cmd_tsrem(self, a):
        n = 0
        for ts in a[1:]:
            if self._get(a[0], _TS + _enc_ts(int(ts))) is not None:
                self._del(a[0], _TS + _enc_ts(int(ts)))
                n += 1
        return resp.integer(n)

    def cmd_tscard(self, a):
        return resp.integer(len(self._typed(a[0], _TS)))

    def _ts_bound(self, s: str, lo: bool) -> int:
        if s in ("-inf", "+inf", "inf"):
            return (-_TS_OFF) if s == "-inf" else _TS_OFF - 1
        return int(s)

    def _ts_range(self, key, lo, hi):
        return [(_dec_ts(_TS + f), v) for f, v in self._typed(key, _TS)
                if lo <= _dec_ts(_TS + f) <= hi]

    def cmd_tsrangebytime(self, a):
        lo = self._ts_bound(a[1], True)
        hi = self._ts_bound(a[2], False)
        out = []
        for ts, v in self._ts_range(a[0], lo, hi):
            out.extend([str(ts), v])
        return resp.array(out)

    def cmd_tsrevrangebytime(self, a):
        lo = self._ts_bound(a[1], True)
        hi = self._ts_bound(a[2], False)
        out = []
        for ts, v in reversed(self._ts_range(a[0], lo, hi)):
            out.extend([str(ts), v])
        return resp.array(out)

    def cmd_tslastn(self, a):
        n = int(a[1])
        items = self._ts_range(a[0], -_TS_OFF, _TS_OFF - 1)[-n:]
        out = []
        for ts, v in items:
            out.extend([str(ts), v])
        return resp.array(out)


class RedisServer:
    """RESP wire server over the messenger (the yb-tserver's port-6379
    proxy, tablet_server_main.cc:191)."""

    def __init__(self, client: YBClient, messenger: Messenger | None = None,
                 **kwargs):
        self._own_messenger = messenger is None
        self.messenger = messenger or Messenger(name="redis")
        self.service = RedisServiceImpl(client, messenger=self.messenger,
                                        **kwargs)

    def listen(self, host: str = "127.0.0.1", port: int = 0):
        def handler(conn, method, args):
            if method == "redis_batch":
                return self.service.handle_batch(args, conn)
            return self.service.handle(args, conn)
        handler.takes_conn = True

        from yugabyte_db_tpu.yql.redis.resp import RedisConnectionContext

        return self.messenger.listen(host, port, handler,
                                     context_factory=RedisConnectionContext)

    def shutdown(self) -> None:
        if self._own_messenger:
            self.messenger.shutdown()
