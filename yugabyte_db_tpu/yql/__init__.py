"""YQL API frontends: cql/ (Cassandra QL), redis/ (RESP), pgsql/ (YSQL).

Reference analog: src/yb/yql — the query-language layer above the client
(cql/ql parser+analyzer+executor, redisserver, pggate/postgres).
"""
