"""CQLServer: the Cassandra native-protocol proxy over the messenger.

Reference analog: src/yb/yql/cql/cqlserver/ — CQLServer (cql_server.cc)
riding the shared rpc::Messenger through a pluggable ConnectionContext
(CQLConnectionContext, cql_rpc.cc), CQLServiceImpl + CQLProcessor
dispatching requests (cql_service.cc, cql_processor.cc), and the
prepared-statement cache (cql_statement.cc).

The service executes statements through yql.cql.QLProcessor against any
Cluster seam — the in-process LocalCluster or the distributed client
adapter (client_cluster.ClientCluster), which is how the reference's CQL
proxy speaks to tservers through its embedded YBClient.
"""

from __future__ import annotations

import hashlib
import threading

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.rpc.messenger import ConnectionContext, Messenger
from yugabyte_db_tpu.utils.metrics import (count_swallowed,
                                           observe_serve_batch)
from yugabyte_db_tpu.utils.status import (AlreadyPresent, InvalidArgument,
                                          NotFound)
from yugabyte_db_tpu.yql.cql import ast
from yugabyte_db_tpu.yql.cql import wire_protocol as W
from yugabyte_db_tpu.yql.cql.parser import Parser
from yugabyte_db_tpu.yql.cql.processor import (QLProcessor, ResultSet,
                                               Unauthorized)


class CQLConnectionContext(ConnectionContext):
    """Parses CQL frames off the socket. Calls are handed to the service
    as (stream, "cql", (opcode, body)); responses are raw frame bytes."""

    ordered_responses = True  # one CQL statement at a time per connection

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> list:
        self._buf.extend(data)
        calls = []
        while True:
            if len(self._buf) < W.HEADER.size:
                return calls
            version, flags, stream, opcode, length = W.HEADER.unpack_from(
                self._buf, 0)
            if length < 0 or length > 64 * 1024 * 1024:
                raise ValueError(f"CQL frame too large: {length}")
            end = W.HEADER.size + length
            if len(self._buf) < end:
                return calls
            body = bytes(self._buf[W.HEADER.size:end])
            del self._buf[:end]
            calls.append((stream, "cql", (opcode, body)))

    def serialize(self, response) -> bytes:
        stream, status, body = response
        if status == "ok":
            return body
        return W.error_frame(stream, W.ERR_SERVER, str(body))


class PreparedStatement:
    __slots__ = ("stmt_id", "query", "stmt", "bind_cols", "table",
                 "keyspace")

    def __init__(self, stmt_id, query, stmt, bind_cols, keyspace, table):
        self.stmt_id = stmt_id
        self.query = query
        self.stmt = stmt
        self.bind_cols = bind_cols
        self.keyspace = keyspace
        self.table = table


class CQLServiceImpl:
    """Executes CQL frames. One instance per server; the prepared cache
    is shared across connections keyed by statement id (md5 of the query,
    like cql_statement.cc). Each CONNECTION owns its QLProcessor —
    keyspace state and in-flight bind params are per-session, and the
    messenger runs one statement at a time per connection
    (ordered_responses), so processor state never races across workers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._prepared: dict[bytes, PreparedStatement] = {}
        # ROWS metadata-header cache for the batch serving path:
        # (id(stmt), keyspace, columns) -> (stmt, header bytes). The
        # header (kind/flags/colspecs) is identical for every frame of a
        # statement; only nrows + rows_data vary. The stmt ref pins the
        # id; a rename/projection change shifts the columns key.
        self._rows_hdr: dict = {}

    # -- frame dispatch ------------------------------------------------------
    def handle_call(self, processor: QLProcessor, stream: int, opcode: int,
                    body: bytes) -> bytes:
        from yugabyte_db_tpu.utils.flags import FLAGS

        try:
            if opcode == W.OP_STARTUP:
                if FLAGS.get("use_cassandra_authentication"):
                    w = W.Writer()
                    w.string("org.apache.cassandra.auth."
                             "PasswordAuthenticator")
                    return W.frame(W.OP_AUTHENTICATE, stream, w.getvalue())
                return W.frame(W.OP_READY, stream, b"")
            if opcode == W.OP_AUTH_RESPONSE:
                # SASL PLAIN token: \x00<user>\x00<password>.
                token = W.Reader(body).bytes_() or b""
                parts = token.split(b"\x00")
                if len(parts) != 3:
                    return W.error_frame(stream, W.ERR_PROTOCOL,
                                         "malformed auth token")
                user = parts[1].decode("utf-8", "surrogateescape")
                password = parts[2].decode("utf-8", "surrogateescape")
                if not processor.cluster.auth_store().check_login(
                        user, password):
                    return W.error_frame(
                        stream, W.ERR_BAD_CREDENTIALS,
                        "Provided username or password is incorrect")
                processor.login_role = user
                w = W.Writer()
                w.bytes_(None)
                return W.frame(W.OP_AUTH_SUCCESS, stream, w.getvalue())
            if opcode == W.OP_OPTIONS:
                w = W.Writer()
                w.short(2)
                w.string("CQL_VERSION").string_list(["3.4.4"])
                w.string("COMPRESSION").string_list([])
                return W.frame(W.OP_SUPPORTED, stream, w.getvalue())
            if opcode == W.OP_REGISTER:
                return W.frame(W.OP_READY, stream, b"")
            if opcode == W.OP_QUERY:
                return self._query(processor, stream, body)
            if opcode == W.OP_PREPARE:
                return self._prepare(processor, stream, body)
            if opcode == W.OP_EXECUTE:
                return self._execute(processor, stream, body)
            return W.error_frame(stream, W.ERR_PROTOCOL,
                                 f"unsupported opcode {opcode:#x}")
        except InvalidArgument as e:
            return W.error_frame(stream, W.ERR_INVALID, str(e))
        except Unauthorized as e:
            return W.error_frame(stream, W.ERR_UNAUTHORIZED, str(e))
        except AlreadyPresent as e:
            return W.error_frame(stream, W.ERR_ALREADY_EXISTS, str(e))
        except NotFound as e:
            return W.error_frame(stream, W.ERR_INVALID, str(e))
        except Exception as e:  # noqa: BLE001 — surface as server error
            return W.error_frame(stream, W.ERR_SERVER,
                                 f"{type(e).__name__}: {e}")

    # -- QUERY ---------------------------------------------------------------
    def _read_query_params(self, r: W.Reader, bind_cols=None):
        """consistency + flags + optional values/page_size/paging_state."""
        r.short()  # consistency (ignored: the cluster owns consistency)
        flags = r.byte()
        params = []
        if flags & 0x01:  # values
            n = r.short()
            for i in range(n):
                raw = r.bytes_()
                dt = (bind_cols[i][1] if bind_cols and i < len(bind_cols)
                      else DataType.BINARY)
                params.append(W.decode_value(dt, raw))
        page_size = r.int32() if flags & 0x04 else None
        paging_state = r.bytes_() if flags & 0x08 else None
        return params, page_size, paging_state

    def _query(self, processor, stream: int, body: bytes) -> bytes:
        r = W.Reader(body)
        query = r.long_string()
        stmt, nmarkers = parse_with_markers(query)
        bind_cols = self._bind_columns(processor, stmt, nmarkers)
        params, page_size, paging_state = self._read_query_params(
            r, bind_cols)
        return self._run(processor, stream, stmt, params, page_size,
                         paging_state)

    # -- PREPARE / EXECUTE ---------------------------------------------------
    def _prepare(self, processor, stream: int, body: bytes) -> bytes:
        query = W.Reader(body).long_string()
        stmt, nmarkers = parse_with_markers(query)
        bind_cols = self._bind_columns(processor, stmt, nmarkers)
        stmt_id = hashlib.md5(query.encode()).digest()[:16]
        ks, table = self._stmt_target(stmt)
        with self._lock:
            self._prepared[stmt_id] = PreparedStatement(
                stmt_id, query, stmt, bind_cols, ks, table)
        return W.prepared_result(stream, stmt_id, ks, table, bind_cols)

    def _execute(self, processor, stream: int, body: bytes) -> bytes:
        r = W.Reader(body)
        stmt_id = r.short_bytes()
        with self._lock:
            ps = self._prepared.get(stmt_id)
        if ps is None:
            return W.error_frame(stream, W.ERR_UNPREPARED,
                                 "unknown prepared statement")
        params, page_size, paging_state = self._read_query_params(
            r, ps.bind_cols)
        return self._run(processor, stream, ps.stmt, params, page_size,
                         paging_state)

    def handle_execute_batch(self, processor: QLProcessor,
                             frames: list) -> bytes:
        """One pipelined burst of EXECUTE frames as ONE call — the CQL
        entry of the native request-batch serving path. ``frames`` is
        [(stream, body), ...] in arrival order; the return value is the
        reply frames concatenated in that same order (each carries its
        own stream id, so a single response body preserves pairing).
        Frames the batched wire path can't serve — unknown statement,
        non-point SELECT, writes, errors — run through handle_call one
        by one, which is exactly the pre-batch behavior."""
        observe_serve_batch("cql", len(frames))
        decoded: list = [None] * len(frames)  # (stmt, params, ps, pg)
        for fi, (stream, body) in enumerate(frames):
            try:
                r = W.Reader(body)
                stmt_id = r.short_bytes()
                with self._lock:
                    ps = self._prepared.get(stmt_id)
                if ps is None:
                    continue
                params, page_size, paging_state = self._read_query_params(
                    r, ps.bind_cols)
                decoded[fi] = (ps.stmt, params, page_size, paging_state)
            except Exception as e:  # noqa: BLE001 — handle_call below
                count_swallowed("cql.batch_decode", e)
        results: list = [None] * len(frames)
        items = [(fi, d) for fi, d in enumerate(decoded) if d is not None]
        if items:
            try:
                served = processor.execute_wire_point_batch(
                    [d for _fi, d in items])
            except Exception as e:  # noqa: BLE001 — per-frame fallback
                count_swallowed("cql.batch_execute", e)
                served = [None] * len(items)
            for (fi, d), rs in zip(items, served):
                if rs is None:
                    continue
                stream = frames[fi][0]
                hkey = (id(d[0]), processor.keyspace, tuple(rs.columns))
                hit = self._rows_hdr.get(hkey)
                if hit is not None and hit[0] is d[0]:
                    hdr = hit[1]
                    body_len = len(hdr) + 4 + len(rs.wire_data)
                    results[fi] = (
                        W.HEADER.pack(W.VERSION_RESP, 0, stream,
                                      W.OP_RESULT, body_len)
                        + hdr + rs.wire_rows.to_bytes(4, "big")
                        + rs.wire_data)
                    continue
                out = self._rows(processor, stream, d[0], rs)
                # Split the canonical frame around nrows+rows_data: the
                # leading metadata header is reusable verbatim, which
                # also guarantees cached replies stay byte-identical.
                hdr = out[W.HEADER.size:len(out) - 4 - len(rs.wire_data)]
                self._rows_hdr[hkey] = (d[0], hdr)
                results[fi] = out
        for fi, (stream, body) in enumerate(frames):
            if results[fi] is None:
                results[fi] = self.handle_call(processor, stream,
                                               W.OP_EXECUTE, body)
        return b"".join(results)

    # -- execution -----------------------------------------------------------
    def _run(self, processor, stream: int, stmt, params, page_size,
             paging_state) -> bytes:
        res = processor.execute(stmt, params=params,
                                page_size=page_size,
                                paging_state=paging_state,
                                wire_results=True)
        if isinstance(stmt, ast.UseKeyspace):
            return W.set_keyspace_result(stream, stmt.name)
        if isinstance(stmt, (ast.CreateKeyspace, ast.DropKeyspace)):
            change = ("CREATED" if isinstance(stmt, ast.CreateKeyspace)
                      else "DROPPED")
            return W.schema_change_result(stream, change, "KEYSPACE",
                                          stmt.name)
        if isinstance(stmt, ast.CreateTable):
            return W.schema_change_result(stream, "CREATED", "TABLE",
                                          processor.keyspace, stmt.name)
        if isinstance(stmt, ast.DropTable):
            return W.schema_change_result(stream, "DROPPED", "TABLE",
                                          processor.keyspace, stmt.name)
        if res is None:
            return W.void_result(stream)
        return self._rows(processor, stream, stmt, res)

    def _rows(self, processor, stream: int, stmt, res: ResultSet) -> bytes:
        table = getattr(stmt, "table", "") or ""
        dts = self._result_types(processor, stmt, res)
        if res.wire_data is not None:
            # Pre-serialized cells from the storage wire path: forward
            # verbatim under the metadata header (rows_data contract).
            return W.rows_result_wire(
                stream, processor.keyspace, table.split(".")[-1],
                list(zip(res.columns, dts)), res.wire_rows,
                res.wire_data, paging_state=res.paging_state)
        return W.rows_result(
            stream, processor.keyspace, table.split(".")[-1],
            list(zip(res.columns, dts)), res.rows,
            paging_state=res.paging_state)

    def _result_types(self, processor, stmt,
                      res: ResultSet) -> list[DataType]:
        table = getattr(stmt, "table", None)
        schema = None
        if table:
            try:
                handle = processor.cluster.table(processor._qualify(table))
                schema = handle.schema
            except Exception:  # noqa: BLE001
                schema = None
        out = []
        items = getattr(stmt, "items", None) or []
        for i, name in enumerate(res.columns):
            dt = None
            col = items[i].column if i < len(items) and \
                hasattr(items[i], "column") else name
            agg = items[i].agg_fn if i < len(items) and \
                hasattr(items[i], "agg_fn") else None
            if agg == "count":
                dt = DataType.INT64
            elif agg == "avg":
                dt = DataType.DOUBLE
            elif schema is not None and col and schema.has_column(col):
                dt = schema.column(col).dtype
                if agg == "sum":
                    # Sums widen: narrow ints overflow their own width.
                    dt = (DataType.DOUBLE
                          if dt in (DataType.FLOAT, DataType.DOUBLE)
                          else DataType.INT64)
            if dt is None and schema is not None and \
                    schema.has_column(name):
                dt = schema.column(name).dtype
            if dt is None:
                # Unresolvable columns degrade to text.
                dt = DataType.STRING
            out.append(dt)
        return out

    # -- bind metadata -------------------------------------------------------
    def _bind_columns(self, processor, stmt,
                      nmarkers: int) -> list[tuple[str, DataType]]:
        """(name, type) per ``?`` marker, in marker order, resolved from
        the statement's target table schema. Sized by the parser's true
        marker count so unnoted positions still get a (blob) slot."""
        markers: dict[int, tuple[str, DataType]] = {}
        table = getattr(stmt, "table", None)
        schema = None
        if table:
            try:
                handle = processor.cluster.table(processor._qualify(table))
                schema = handle.schema
            except Exception:  # noqa: BLE001
                schema = None

        def col_dt(col_name):
            if schema is not None and schema.has_column(col_name):
                return schema.column(col_name).dtype
            return DataType.BINARY

        def note(value, col_name):
            if isinstance(value, ast.BindMarker):
                markers[value.index] = (col_name, col_dt(col_name))
            elif isinstance(value, (list, tuple)):
                for v in value:
                    note(v, col_name)

        if isinstance(stmt, ast.Insert):
            for cname, v in zip(stmt.columns, stmt.values):
                note(v, cname)
        if isinstance(stmt, ast.Update):
            for cname, v in stmt.assignments:
                note(v, cname)
        for rel in getattr(stmt, "where", None) or []:
            note(rel.value, rel.column)
        lim = getattr(stmt, "limit", None)
        if isinstance(lim, ast.BindMarker):
            markers[lim.index] = ("[limit]", DataType.INT32)
        ttl = getattr(stmt, "ttl_seconds", None)
        if isinstance(ttl, ast.BindMarker):
            markers[ttl.index] = ("[ttl]", DataType.INT32)
        return [markers.get(i, (f"p{i}", DataType.BINARY))
                for i in range(nmarkers)]

    @staticmethod
    def _stmt_target(stmt) -> tuple[str, str]:
        table = getattr(stmt, "table", "") or ""
        if "." in table:
            ks, t = table.split(".", 1)
            return ks, t
        return "default", table


def parse_with_markers(query: str):
    """Parse one statement, returning (ast, number of ? markers)."""
    p = Parser(query)
    stmt = p.parse()
    return stmt, p.bind_count


class CQLServer:
    """Standalone CQL wire server: owns a messenger listener and a
    service over a Cluster seam. Each accepted connection gets its own
    QLProcessor (session keyspace + bind state), sharing the cluster and
    the prepared-statement cache."""

    def __init__(self, cluster, messenger: Messenger | None = None):
        self.cluster = cluster
        self.service = CQLServiceImpl()
        self._own_messenger = messenger is None
        self.messenger = messenger or Messenger(name="cql")

    def listen(self, host: str = "127.0.0.1", port: int = 0):
        # The messenger hands the handler (method, body) with the call id
        # (== CQL stream id) kept aside for response pairing; the stream
        # and the connection's processor also matter INSIDE the handler,
        # so the context tags both onto the body tuple.
        cluster = self.cluster

        def handler(_method, payload):
            processor, stream, opcode, body = payload
            if opcode == "execute_batch":
                return self.service.handle_execute_batch(processor, body)
            return self.service.handle_call(processor, stream, opcode, body)

        class _Ctx(CQLConnectionContext):
            def __init__(self):
                super().__init__()
                self.processor = QLProcessor(cluster)

            def feed(self, data):
                # Runs of pipelined EXECUTEs collapse into ONE
                # "execute_batch" call (the native request-batch serving
                # path). The single reply body carries one frame per
                # request frame, each tagged with its own stream id, so
                # response pairing survives the coalescing.
                calls = []
                run: list = []
                for stream, _m, (op, body) in super().feed(data):
                    if op == W.OP_EXECUTE:
                        run.append((stream, body))
                        continue
                    self._flush_run(calls, run)
                    calls.append(
                        (stream, "cql", (self.processor, stream, op, body)))
                self._flush_run(calls, run)
                return calls

            def _flush_run(self, calls, run):
                if not run:
                    return
                if len(run) == 1:
                    stream, body = run[0]
                    calls.append((stream, "cql",
                                  (self.processor, stream, W.OP_EXECUTE,
                                   body)))
                else:
                    stream = run[0][0]
                    calls.append((stream, "cql",
                                  (self.processor, stream, "execute_batch",
                                   list(run))))
                run.clear()

        return self.messenger.listen(host, port, handler,
                                     context_factory=_Ctx)

    def shutdown(self) -> None:
        if self._own_messenger:
            self.messenger.shutdown()
