"""YCQL: the Cassandra-compatible query language frontend.

Reference analog: src/yb/yql/cql/ql — QLProcessor (ql_processor.h:55) with
parse -> analyze -> execute phases (parser/parser_gram.y, sem/analyzer.cc,
exec/executor.cc). Here: a recursive-descent parser (no bison), a binder
against the catalog schema, and an executor that pushes scans/writes
through the client to tablets.
"""

from yugabyte_db_tpu.yql.cql.parser import parse_statement
from yugabyte_db_tpu.yql.cql.processor import QLProcessor
