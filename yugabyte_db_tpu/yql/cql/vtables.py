"""CQL system virtual tables — the driver-handshake surface.

Reference analog: the master's ~18 YQLVirtualTable implementations
(src/yb/master/yql_virtual_table.h:28; yql_local_vtable.cc,
yql_peers_vtable.cc, yql_keyspaces_vtable.cc, yql_tables_vtable.cc,
yql_columns_vtable.cc, ...) serving system.local / system.peers /
system_schema.* from catalog state through the same YQLStorageIf seam
as real tables. Stock Cassandra drivers read these on connect to build
cluster + schema metadata; without them no driver can handshake.

Rows are materialized from live processor/cluster state per query (the
reference regenerates vtable content per request too), then filtered by
the statement's WHERE conjuncts and projected.
"""

from __future__ import annotations

import uuid

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind
from yugabyte_db_tpu.utils.metrics import count_swallowed
from yugabyte_db_tpu.utils.status import InvalidArgument

# A stable fake host id per process (reference: the tserver's uuid).
_HOST_ID = str(uuid.uuid4())
_PARTITIONER = "org.apache.cassandra.dht.Murmur3Partitioner"

_CQL_TYPE_NAMES = {
    DataType.INT8: "tinyint", DataType.INT16: "smallint",
    DataType.INT32: "int", DataType.INT64: "bigint",
    DataType.STRING: "text", DataType.FLOAT: "float",
    DataType.DOUBLE: "double", DataType.BOOL: "boolean",
    DataType.BINARY: "blob", DataType.TIMESTAMP: "timestamp",
    DataType.COUNTER: "counter", DataType.JSONB: "jsonb",
    DataType.LIST: "list", DataType.SET: "set", DataType.MAP: "map",
}

VIRTUAL_TABLES = ("system.local", "system.peers",
                  "system_schema.keyspaces", "system_schema.tables",
                  "system_schema.columns", "system_schema.types")


def is_virtual(qualified: str) -> bool:
    return qualified in VIRTUAL_TABLES


def _local_rows(processor):
    return [{
        "key": "local",
        "bootstrapped": "COMPLETED",
        "broadcast_address": "127.0.0.1",
        "cluster_name": "local cluster",
        "cql_version": "3.4.4",
        "data_center": "datacenter1",
        "gossip_generation": 0,
        "host_id": _HOST_ID,
        "listen_address": "127.0.0.1",
        "native_protocol_version": "4",
        "partitioner": _PARTITIONER,
        "rack": "rack1",
        "release_version": "3.9-SNAPSHOT",
        "rpc_address": "127.0.0.1",
        "schema_version": _HOST_ID,
        "tokens": ["0"],
    }]


def _peers_rows(processor):
    """Other nodes. The in-process/local deployments serve everything
    from one address; a distributed ClientCluster reports its live
    tservers (reference: yql_peers_vtable.cc from TSDescriptors)."""
    rows = []
    client = getattr(processor.cluster, "client", None)
    if client is not None:
        try:
            tservers = client.list_tservers()
        except Exception:  # noqa: BLE001 — vtables degrade, never fail
            tservers = []
        for i, ts in enumerate(tservers[1:], start=2):
            addr = f"127.0.0.{i}"
            rows.append({
                "peer": addr, "data_center": "datacenter1",
                "host_id": str(uuid.uuid5(uuid.NAMESPACE_DNS,
                                          str(ts.get("uuid", i)))),
                "preferred_ip": addr, "rack": "rack1",
                "release_version": "3.9-SNAPSHOT", "rpc_address": addr,
                "schema_version": _HOST_ID, "tokens": [str(i)],
            })
    return rows


def _keyspace_names(processor) -> list[str]:
    names = set(processor.keyspaces)
    names.update({"system", "system_schema"})
    for t in processor.cluster.tables:
        if "." in t:
            names.add(t.split(".", 1)[0])
    return sorted(names)


def _keyspaces_rows(processor):
    return [{
        "keyspace_name": ks,
        "durable_writes": True,
        "replication": {
            "class": "org.apache.cassandra.locator.SimpleStrategy",
            "replication_factor": "3"},
    } for ks in _keyspace_names(processor)]


def _user_tables(processor):
    """(keyspace, table, schema) triples of real tables."""
    out = []
    for name in sorted(processor.cluster.tables):
        if "." not in name:
            continue
        ks, table = name.split(".", 1)
        try:
            schema = processor.cluster.table(name).schema
        except Exception as e:  # noqa: BLE001 — dropped concurrently
            count_swallowed("cql_vtables.table_schema", e)
            continue
        out.append((ks, table, schema))
    return out


def _tables_rows(processor):
    return [{
        "keyspace_name": ks, "table_name": table,
        "id": str(uuid.uuid5(uuid.NAMESPACE_DNS, f"{ks}.{table}")),
        "default_time_to_live": 0,
        "flags": ["compound"],
    } for ks, table, _schema in _user_tables(processor)]


def _columns_rows(processor):
    rows = []
    for ks, table, schema in _user_tables(processor):
        hash_cols = [c for c in schema.columns if c.kind == ColumnKind.HASH]
        range_cols = [c for c in schema.columns
                      if c.kind == ColumnKind.RANGE]
        for c in schema.columns:
            if c.kind == ColumnKind.HASH:
                kind, pos = "partition_key", hash_cols.index(c)
            elif c.kind == ColumnKind.RANGE:
                kind, pos = "clustering", range_cols.index(c)
            else:
                kind, pos = "regular", -1
            rows.append({
                "keyspace_name": ks, "table_name": table,
                "column_name": c.name,
                "clustering_order": ("asc" if kind == "clustering"
                                     else "none"),
                "column_name_bytes": c.name.encode(),
                "kind": kind, "position": pos,
                "type": _CQL_TYPE_NAMES.get(c.dtype, "text"),
            })
    return rows


def _types_rows(processor):
    """system_schema.types: the UDT registry, as stock drivers read it
    for schema metadata (reference: yql_types_vtable.cc)."""
    from yugabyte_db_tpu.models.datatypes import DataType

    rows = []
    try:
        types = processor.cluster.list_types()
    except Exception:  # noqa: BLE001 — masterless moment: empty listing
        types = {}
    for name, fields in sorted((types or {}).items()):
        ks, _, tname = name.rpartition(".")
        rows.append({
            "keyspace_name": ks or "default",
            "type_name": tname or name,
            "field_names": [f[0] for f in fields],
            "field_types": [
                _CQL_TYPE_NAMES.get(DataType(f[1]), "text")
                for f in fields],
        })
    return rows


_BUILDERS = {
    "system.local": _local_rows,
    "system.peers": _peers_rows,
    "system_schema.keyspaces": _keyspaces_rows,
    "system_schema.tables": _tables_rows,
    "system_schema.columns": _columns_rows,
    "system_schema.types": _types_rows,
}

# Column order when a vtable has no rows to infer from (drivers break
# on RowDescription-less empty results).
_EMPTY_COLUMNS = {
    "system.peers": ["peer", "data_center", "host_id", "preferred_ip",
                     "rack", "release_version", "rpc_address",
                     "schema_version", "tokens"],
    "system_schema.types": ["keyspace_name", "type_name", "field_names",
                            "field_types"],
}


def _matches(row: dict, rel) -> bool:
    v = row.get(rel.column)
    rv = rel.value
    if rel.op == "=":
        return v == rv
    if rel.op == "!=":
        return v != rv
    if rel.op == "IN":
        return v in rv
    if v is None or rv is None:
        return False
    return {"<": v < rv, "<=": v <= rv, ">": v > rv,
            ">=": v >= rv}[rel.op]


def virtual_select(processor, stmt):
    """Execute a SELECT against a system vtable; returns a ResultSet.
    Raises InvalidArgument for projections of unknown columns."""
    from yugabyte_db_tpu.yql.cql.processor import ResultSet

    qualified = processor._qualify(stmt.table)
    rows = _BUILDERS[qualified](processor)
    for rel in stmt.where:
        value = processor._resolve_marker(rel.value)
        rel = type(rel)(rel.column, rel.op, value)
        rows = [r for r in rows if _matches(r, rel)]
    if rows:
        all_cols = list(rows[0].keys())
    else:
        all_cols = _EMPTY_COLUMNS.get(qualified, [])
    if stmt.items is None:
        names = all_cols
    else:
        names = []
        for it in stmt.items:
            if it.agg_fn == "count" and it.column is None:
                return ResultSet(["count"], [(len(rows),)])
            if it.column is None or (rows and it.column not in rows[0]):
                raise InvalidArgument(
                    f"unknown column {it.column} in {qualified}")
            names.append(it.column)
    out = [tuple(r.get(n) for n in names) for r in rows]
    if stmt.limit is not None:
        out = out[:processor._require_nonneg_int(
            processor._resolve_marker(stmt.limit), "LIMIT")]
    return ResultSet(list(names), out)
