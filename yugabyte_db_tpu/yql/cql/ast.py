"""CQL parse-tree nodes.

Reference analog: the PT* node hierarchy of src/yb/yql/cql/ql/ptree/
(pt_select.h, pt_insert.h, pt_update.h, pt_delete.h, pt_create_table.h,
pt_create_keyspace.h, ...). Statements parse into these dataclasses, the
processor's binder resolves names against the catalog, and the executor
lowers them to storage operations.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from yugabyte_db_tpu.models.datatypes import DataType


@dataclass(frozen=True)
class BindMarker:
    """A ``?`` placeholder; resolved against execute-time params by
    position (reference: PTBindVar, src/yb/yql/cql/ql/ptree/pt_expr.h)."""

    index: int


@dataclass
class ColumnDef:
    name: str
    dtype: DataType
    is_static: bool = False
    udt: str | None = None         # declared user-defined type name


@dataclass
class CreateKeyspace:
    name: str
    if_not_exists: bool = False


@dataclass
class DropKeyspace:
    name: str
    if_exists: bool = False


@dataclass
class UseKeyspace:
    name: str


@dataclass
class CreateRole:
    """CREATE ROLE r [WITH PASSWORD = '..' [AND LOGIN = b] [AND
    SUPERUSER = b]] (reference: PTCreateRole / master CreateRole RPC,
    src/yb/master/master.proto:1383)."""

    name: str
    password: str | None = None
    can_login: bool = False
    superuser: bool = False
    if_not_exists: bool = False


@dataclass
class AlterRole:
    name: str
    password: str | None = None
    can_login: bool | None = None
    superuser: bool | None = None


@dataclass
class DropRole:
    name: str
    if_exists: bool = False


@dataclass
class GrantRevokeRole:
    """GRANT r TO m / REVOKE r FROM m (master.proto:1386)."""

    grant: bool
    role: str
    member: str


@dataclass
class GrantRevokePermission:
    """GRANT/REVOKE <perm> ON <resource> TO/FROM role
    (master.proto:1388). resource uses the hierarchical form of
    yugabyte_db_tpu.auth ("data", "data/ks", "data/ks/t", "roles",
    "roles/r")."""

    grant: bool
    permission: str            # ALL or one of auth.PERMISSIONS
    resource: str
    role: str


@dataclass
class ListRoles:
    pass


@dataclass
class ListPermissions:
    pass


@dataclass
class CreateTable:
    name: str                      # possibly keyspace-qualified "ks.t"
    columns: list[ColumnDef]
    hash_keys: list[str]
    range_keys: list[str]
    if_not_exists: bool = False
    properties: dict = field(default_factory=dict)  # WITH k = v (tablets=N)


@dataclass
class Batch:
    """BEGIN [UNLOGGED] BATCH ... APPLY BATCH: a client-grouped list of
    DML statements (per-tablet atomicity, reference: exec of PTListNode
    batches in executor.cc)."""

    statements: list
    logged: bool = True


@dataclass
class AlterTable:
    """ALTER TABLE t ADD col type | DROP col | RENAME a TO b."""

    name: str
    action: str                    # "add" | "drop" | "rename"
    column: str | None = None
    dtype: object = None           # DataType for "add"
    new_name: str | None = None    # for "rename"


@dataclass
class DropTable:
    name: str
    if_exists: bool = False


@dataclass
class CreateIndex:
    name: str                      # index name
    table: str                     # base table (possibly qualified)
    columns: list                  # indexed columns (compound hash)
    if_not_exists: bool = False
    include: list = field(default_factory=list)  # covered columns


@dataclass
class CreateType:
    """CREATE TYPE name (field type, ...) — reference:
    src/yb/yql/cql/ql/ptree/pt_create_type.cc."""

    name: str
    fields: list                   # [(field_name, DataType)]
    if_not_exists: bool = False


@dataclass
class DropType:
    name: str
    if_exists: bool = False


@dataclass
class DropIndex:
    name: str
    if_exists: bool = False


@dataclass
class Relation:
    """column <op> literal (op: = != < <= > >= IN)."""

    column: str
    op: str
    value: object


@dataclass
class SelectItem:
    """A projection item: a column, or an aggregate over a column/'*'/
    an arithmetic expression (storage.expr tree) — the TPC-H
    sum(price * (1 - disc)) shape."""

    column: str | None          # None for fn(*) / expression aggregates
    agg_fn: str | None = None   # count/sum/min/max/avg or None for plain col
    alias: str | None = None
    expr: object = None         # storage.expr tree for fn(<arith expr>)

    @property
    def output_name(self) -> str:
        if self.alias:
            return self.alias
        if self.agg_fn:
            return f"{self.agg_fn}({self.column or ('<expr>' if self.expr else '*')})"
        return self.column


@dataclass
class Select:
    table: str
    items: list[SelectItem] | None   # None = '*'
    where: list[Relation] = field(default_factory=list)
    limit: int | None = None
    allow_filtering: bool = False
    group_by: list[str] = field(default_factory=list)
    order_by: list[tuple] = field(default_factory=list)  # (name, desc)


@dataclass
class Insert:
    table: str
    columns: list[str]
    values: list[object]
    ttl_seconds: int | None = None
    if_not_exists: bool = False


@dataclass
class CollectionOp:
    """UPDATE SET rhs that edits a collection in place:
    v = v + [...], v = [...] + v (prepend), v = v - {...},
    v[idx_or_key] = x. Evaluated read-modify-write at the executor
    (the reference writes per-element subdocuments without a read —
    the observable end state matches for serialized writers)."""

    op: str            # "append" | "prepend" | "remove" | "setelem"
    operand: object    # the literal collection / element value
    index: object = None  # for "setelem": list index or map key


@dataclass
class Update:
    table: str
    assignments: list[tuple[str, object]]
    where: list[Relation]
    ttl_seconds: int | None = None


@dataclass
class Delete:
    table: str
    where: list[Relation]
    columns: list[str] | None = None   # DELETE col[, col] FROM — col deletes
