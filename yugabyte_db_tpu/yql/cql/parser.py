"""CQL recursive-descent parser (tokenizer + statement grammar).

Reference analog: the Bison/Flex grammar of src/yb/yql/cql/ql/parser/
(parser_gram.y, scanner_lex.l). The reference generates a ~30-statement
grammar; this covers the DDL/DML core (CREATE/DROP KEYSPACE|TABLE, USE,
INSERT, SELECT incl. aggregates, UPDATE, DELETE) and grows per statement.
"""

from __future__ import annotations

import re

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.storage.scan_spec import AGG_FNS as _AGG_FN_TUPLE
from yugabyte_db_tpu.utils.status import InvalidArgument
from yugabyte_db_tpu.yql.cql import ast

AGG_FNS = frozenset(_AGG_FN_TUPLE)

_TOKEN_RE = re.compile(r"""
    \s+
  | (?P<comment>--[^\n]*|//[^\n]*)
  | (?P<string>'(?:[^']|'')*')
  | (?P<blob>0[xX][0-9a-fA-F]*)
  | (?P<number>-?\d+\.\d+(?:[eE][+-]?\d+)?|-?\.\d+|-?\d+[eE][+-]?\d+|-?\d+)
  | (?P<name>[A-Za-z_][A-Za-z_0-9]*|"(?:[^"]|"")*")
  | (?P<op><=|>=|!=|=|<|>)
  | (?P<sym>[(),.;*?{}:\[\]+-])
""", re.VERBOSE)


class Token:
    __slots__ = ("kind", "text")

    def __init__(self, kind, text):
        self.kind = kind
        self.text = text

    def __repr__(self):
        return f"{self.kind}:{self.text}"


def tokenize(sql: str) -> list[Token]:
    out, pos = [], 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise InvalidArgument(f"CQL syntax error near {sql[pos:pos+20]!r}")
        pos = m.end()
        for kind in ("string", "blob", "number", "name", "op", "sym"):
            text = m.group(kind)
            if text is not None:
                out.append(Token(kind, text))
                break
    return out


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0
        self.bind_count = 0  # ``?`` markers seen, in appearance order

    # -- token helpers -----------------------------------------------------
    def peek(self) -> Token | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Token:
        t = self.peek()
        if t is None:
            raise InvalidArgument("unexpected end of statement")
        self.i += 1
        return t

    def at_kw(self, *kws) -> bool:
        t = self.peek()
        return (t is not None and t.kind == "name"
                and t.text.upper() in kws)

    def take_kw(self, *kws) -> bool:
        if self.at_kw(*kws):
            self.i += 1
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.take_kw(kw):
            raise InvalidArgument(f"expected {kw}, got {self.peek()}")

    def at_sym(self, s: str) -> bool:
        t = self.peek()
        return t is not None and t.kind in ("sym", "op") and t.text == s

    def take_sym(self, s: str) -> bool:
        if self.at_sym(s):
            self.i += 1
            return True
        return False

    def expect_sym(self, s: str) -> None:
        if not self.take_sym(s):
            raise InvalidArgument(f"expected {s!r}, got {self.peek()}")

    def ident(self) -> str:
        t = self.next()
        if t.kind != "name":
            raise InvalidArgument(f"expected identifier, got {t}")
        if t.text.startswith('"'):
            return t.text[1:-1].replace('""', '"')
        return t.text.lower()

    def qualified_name(self) -> str:
        name = self.ident()
        if self.take_sym("."):
            return f"{name}.{self.ident()}"
        return name

    def literal(self):
        # collection literals: [a, b] list; {a, b} set; {k: v, ...} map.
        # Bind markers are supported for WHOLE collections (v = ?) but
        # not for individual elements — element markers would persist
        # BindMarker objects as data (and sets can't sort them).
        def _no_marker(v):
            if isinstance(v, ast.BindMarker):
                raise InvalidArgument(
                    "bind markers are not allowed inside collection "
                    "literals; bind the whole collection instead")
            return v

        if self.take_sym("["):
            out = []
            while not self.take_sym("]"):
                out.append(_no_marker(self.literal()))
                self.take_sym(",")
            return out
        if self.at_sym("{"):
            self.next()
            if self.take_sym("}"):
                return {}  # empty braces: map (CQL's untyped empty {})
            first = _no_marker(self.literal())
            if self.take_sym(":"):
                m = {first: _no_marker(self.literal())}
                while self.take_sym(","):
                    k = _no_marker(self.literal())
                    self.expect_sym(":")
                    m[k] = _no_marker(self.literal())
                self.expect_sym("}")
                return dict(sorted(m.items()))  # normalized key order
            items = [first]
            while self.take_sym(","):
                items.append(_no_marker(self.literal()))
            self.expect_sym("}")
            return sorted(set(items))  # SET: normalized sorted list
        t = self.next()
        if t.kind == "sym" and t.text == "?":
            marker = ast.BindMarker(self.bind_count)
            self.bind_count += 1
            return marker
        if t.kind == "string":
            return t.text[1:-1].replace("''", "'")
        if t.kind == "blob":
            hexpart = t.text[2:]
            if len(hexpart) % 2:
                raise InvalidArgument(f"odd-length blob literal {t.text}")
            return bytes.fromhex(hexpart)
        if t.kind == "number":
            txt = t.text
            if any(c in txt for c in ".eE"):
                return float(txt)
            return int(txt)
        if t.kind == "name":
            up = t.text.upper()
            if up == "TRUE":
                return True
            if up == "FALSE":
                return False
            if up == "NULL":
                return None
        raise InvalidArgument(f"expected literal, got {t}")

    # -- statements --------------------------------------------------------
    def parse(self):
        t = self.peek()
        if t is None:
            raise InvalidArgument("empty statement")
        kw = t.text.upper() if t.kind == "name" else ""
        fn = {
            "CREATE": self._create, "DROP": self._drop, "USE": self._use,
            "INSERT": self._insert, "SELECT": self._select,
            "UPDATE": self._update, "DELETE": self._delete,
            "ALTER": self._alter, "BEGIN": self._batch,
            "GRANT": self._grant_revoke, "REVOKE": self._grant_revoke,
            "LIST": self._list,
        }.get(kw)
        if fn is None:
            raise InvalidArgument(f"unsupported statement {t.text!r}")
        stmt = fn()
        self.take_sym(";")
        if self.peek() is not None:
            raise InvalidArgument(f"trailing tokens at {self.peek()}")
        return stmt

    # -- roles / permissions (reference grammar: PTCreateRole,
    # PTGrantRevokePermission in parser_gram.y) -----------------------------
    _PERMS = ("ALL", "ALTER", "AUTHORIZE", "CREATE", "DESCRIBE", "DROP",
              "MODIFY", "SELECT")

    def _role_options(self):
        password, can_login, superuser = None, None, None
        if self.take_kw("WITH"):
            while True:
                opt = self.ident().upper()
                self.expect_sym("=")
                v = self.literal()
                if opt == "PASSWORD":
                    password = str(v)
                elif opt == "LOGIN":
                    can_login = bool(v)
                elif opt == "SUPERUSER":
                    superuser = bool(v)
                else:
                    raise InvalidArgument(f"unknown role option {opt}")
                if not self.take_kw("AND"):
                    break
        return password, can_login, superuser

    def _grant_revoke(self):
        grant = self.take_kw("GRANT")
        if not grant:
            self.expect_kw("REVOKE")
        t = self.peek()
        word = t.text.upper() if t is not None and t.kind == "name" else ""
        if word in self._PERMS and (
                self._peek_ahead_kw(1, "ON", "PERMISSION", "PERMISSIONS")):
            perm = self.ident().upper()
            self.take_kw("PERMISSION") or self.take_kw("PERMISSIONS")
            self.expect_kw("ON")
            resource = self._auth_resource()
            self.expect_kw("TO" if grant else "FROM")
            return ast.GrantRevokePermission(grant, perm, resource,
                                             self.ident())
        role = self.ident()
        self.expect_kw("TO" if grant else "FROM")
        return ast.GrantRevokeRole(grant, role, self.ident())

    def _peek_ahead_kw(self, n: int, *kws) -> bool:
        t = self.toks[self.i + n] if self.i + n < len(self.toks) else None
        return (t is not None and t.kind == "name"
                and t.text.upper() in kws)

    def _auth_resource(self) -> str:
        if self.take_kw("ALL"):
            if self.take_kw("KEYSPACES"):
                return "data"
            self.expect_kw("ROLES")
            return "roles"
        if self.take_kw("KEYSPACE"):
            return f"data/{self.ident()}"
        if self.take_kw("ROLE"):
            return f"roles/{self.ident()}"
        self.take_kw("TABLE")
        name = self.qualified_name()
        if "." in name:
            ks, table = name.split(".", 1)
            return f"data/{ks}/{table}"
        return f"data//{name}"   # keyspace resolved by the processor

    def _list(self):
        self.expect_kw("LIST")
        if self.take_kw("ROLES"):
            return ast.ListRoles()
        self.take_kw("ALL")
        self.expect_kw("PERMISSIONS")
        return ast.ListPermissions()

    def _alter(self):
        """ALTER TABLE t ... | ALTER ROLE r WITH ..."""
        self.expect_kw("ALTER")
        if self.take_kw("ROLE"):
            name = self.ident()
            password, can_login, superuser = self._role_options()
            return ast.AlterRole(name, password, can_login, superuser)
        self.expect_kw("TABLE")
        name = self.qualified_name()
        if self.take_kw("ADD"):
            col = self.ident()
            dtype = self._type()
            return ast.AlterTable(name, "add", col, dtype)
        if self.take_kw("DROP"):
            return ast.AlterTable(name, "drop", self.ident())
        if self.take_kw("RENAME"):
            old = self.ident()
            self.expect_kw("TO")
            return ast.AlterTable(name, "rename", old,
                                  new_name=self.ident())
        raise InvalidArgument(f"expected ADD/DROP/RENAME, got {self.peek()}")

    def _batch(self):
        """BEGIN [UNLOGGED|LOGGED|COUNTER] BATCH <dml>; ... APPLY BATCH
        (reference: PTInsertStmt lists under PTListNode in a batch tree).
        Batches group client-side; each statement routes to its tablet —
        per-tablet atomicity, like the reference without transactions."""
        self.expect_kw("BEGIN")
        logged = not self.take_kw("UNLOGGED")
        self.take_kw("LOGGED", "COUNTER")
        self.expect_kw("BATCH")
        stmts = []
        while not self.at_kw("APPLY"):
            t = self.peek()
            if t is None:
                raise InvalidArgument("unterminated BATCH (missing APPLY)")
            kw = t.text.upper()
            fn = {"INSERT": self._insert, "UPDATE": self._update,
                  "DELETE": self._delete}.get(kw)
            if fn is None:
                raise InvalidArgument(
                    f"only INSERT/UPDATE/DELETE allowed in BATCH, got {kw}")
            stmts.append(fn())
            self.take_sym(";")
        self.expect_kw("APPLY")
        self.expect_kw("BATCH")
        return ast.Batch(stmts, logged)

    def _if_not_exists(self) -> bool:
        if self.take_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def _if_exists(self) -> bool:
        if self.take_kw("IF"):
            self.expect_kw("EXISTS")
            return True
        return False

    def _create(self):
        self.expect_kw("CREATE")
        if self.take_kw("ROLE"):
            ine = self._if_not_exists()
            name = self.ident()
            password, can_login, superuser = self._role_options()
            return ast.CreateRole(name, password,
                                  bool(can_login), bool(superuser), ine)
        if self.take_kw("KEYSPACE", "SCHEMA"):
            ine = self._if_not_exists()
            name = self.ident()
            self._skip_with()
            return ast.CreateKeyspace(name, ine)
        if self.take_kw("TYPE"):
            ine = self._if_not_exists()
            tname = self.qualified_name()
            self.expect_sym("(")
            fields = [(self.ident(), self._type())]
            while self.take_sym(","):
                fields.append((self.ident(), self._type()))
            self.expect_sym(")")
            return ast.CreateType(tname, fields, ine)
        if self.take_kw("INDEX"):
            ine = self._if_not_exists()
            iname = self.ident()
            self.expect_kw("ON")
            table = self.qualified_name()
            self.expect_sym("(")
            columns = [self.ident()]
            while self.take_sym(","):
                columns.append(self.ident())
            self.expect_sym(")")
            include = []
            if self.take_kw("INCLUDE"):
                self.expect_sym("(")
                include.append(self.ident())
                while self.take_sym(","):
                    include.append(self.ident())
                self.expect_sym(")")
            return ast.CreateIndex(iname, table, columns, ine, include)
        self.expect_kw("TABLE")
        ine = self._if_not_exists()
        name = self.qualified_name()
        cols, hash_keys, range_keys = self._table_body()
        props = self._with_properties()
        return ast.CreateTable(name, cols, hash_keys, range_keys, ine, props)

    def _table_body(self):
        self.expect_sym("(")
        cols: list[ast.ColumnDef] = []
        hash_keys: list[str] = []
        range_keys: list[str] = []
        while True:
            if self.at_kw("PRIMARY"):
                self.next()
                self.expect_kw("KEY")
                self.expect_sym("(")
                if self.take_sym("("):   # ((h1, h2), r1, ...)
                    hash_keys.append(self.ident())
                    while self.take_sym(","):
                        hash_keys.append(self.ident())
                    self.expect_sym(")")
                else:                     # (h1, r1, ...)
                    hash_keys.append(self.ident())
                while self.take_sym(","):
                    range_keys.append(self.ident())
                self.expect_sym(")")
            else:
                cname = self.ident()
                dtype, udt = self._type_with_udt()
                is_static = bool(self.take_kw("STATIC"))
                if self.take_kw("PRIMARY"):
                    self.expect_kw("KEY")
                    hash_keys.append(cname)
                cols.append(ast.ColumnDef(cname, dtype, is_static, udt))
            if not self.take_sym(","):
                break
        self.expect_sym(")")
        if not hash_keys:
            raise InvalidArgument("table needs a primary key")
        return cols, hash_keys, range_keys

    def _type_with_udt(self):
        """A column type: native (possibly FROZEN<...>-wrapped) -> (dtype,
        None); an unknown name is a user-defined type reference ->
        (MAP, udt_name) — UDT values store as frozen field maps."""
        t = self.peek()
        if t is not None and t.kind == "name" and \
                t.text.upper() == "FROZEN":
            self.ident()
            self.expect_sym("<")
            inner = self._type_with_udt()
            self.expect_sym(">")
            dt, udt = inner
            if udt is None and dt in (DataType.LIST, DataType.SET,
                                      DataType.MAP, DataType.TUPLE):
                # frozen<collection>: immutable, byte-comparable, valid
                # in primary keys (reference: common.proto FROZEN +
                # primitive_value.h kFrozen key encoding).
                return DataType.FROZEN, None
            return inner  # frozen<udt> / frozen<scalar>: unchanged
        if t is not None and t.kind == "name":
            try:
                DataType.parse(t.text)
            except ValueError:
                return DataType.MAP, self.ident()
        return self._type(), None

    def _type(self) -> DataType:
        name = self.ident()
        try:
            dt = DataType.parse(name)
        except ValueError as e:
            raise InvalidArgument(str(e))
        if dt in (DataType.LIST, DataType.SET, DataType.MAP,
                  DataType.TUPLE) and self.take_sym("<"):
            # element types accepted and discarded: values are stored as
            # host containers; element validation is container-level
            self._type()
            while self.take_sym(","):
                self._type()
            self.expect_sym(">")
        return dt

    def _with_properties(self) -> dict:
        props = {}
        if self.take_kw("WITH"):
            while True:
                key = self.ident()
                self.expect_sym("=")
                props[key] = self.literal()
                if not self.take_kw("AND"):
                    break
        return props

    def _skip_with(self):
        # CREATE KEYSPACE ... WITH replication = {...}: accept and ignore.
        if self.take_kw("WITH"):
            while self.peek() is not None and not self.at_sym(";"):
                self.next()

    def _drop(self):
        self.expect_kw("DROP")
        if self.take_kw("ROLE"):
            ie = self._if_exists()
            return ast.DropRole(self.ident(), ie)
        if self.take_kw("KEYSPACE", "SCHEMA"):
            ie = self._if_exists()
            return ast.DropKeyspace(self.ident(), ie)
        if self.take_kw("INDEX"):
            ie = self._if_exists()
            return ast.DropIndex(self.ident(), ie)
        if self.take_kw("TYPE"):
            ie = self._if_exists()
            return ast.DropType(self.qualified_name(), ie)
        self.expect_kw("TABLE")
        ie = self._if_exists()
        return ast.DropTable(self.qualified_name(), ie)

    def _use(self):
        self.expect_kw("USE")
        return ast.UseKeyspace(self.ident())

    def _insert(self):
        self.expect_kw("INSERT")
        self.expect_kw("INTO")
        table = self.qualified_name()
        self.expect_sym("(")
        columns = [self.ident()]
        while self.take_sym(","):
            columns.append(self.ident())
        self.expect_sym(")")
        self.expect_kw("VALUES")
        self.expect_sym("(")
        values = [self.literal()]
        while self.take_sym(","):
            values.append(self.literal())
        self.expect_sym(")")
        ine = self._if_not_exists()
        ttl = self._using_ttl()
        if len(columns) != len(values):
            raise InvalidArgument("column/value count mismatch")
        return ast.Insert(table, columns, values, ttl, ine)

    def _using_ttl(self):
        if self.take_kw("USING"):
            self.expect_kw("TTL")
            ttl = self.literal()
            if not isinstance(ttl, ast.BindMarker) and (
                    not isinstance(ttl, int) or ttl < 0):
                raise InvalidArgument("TTL must be a non-negative integer")
            return ttl
        return None

    def _select(self):
        self.expect_kw("SELECT")
        items = None
        if not self.take_sym("*"):
            items = [self._select_item()]
            while self.take_sym(","):
                items.append(self._select_item())
        self.expect_kw("FROM")
        table = self.qualified_name()
        where = self._where_opt()
        group_by = []
        if self.take_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.ident())
            while self.take_sym(","):
                group_by.append(self.ident())
        order_by = []
        if self.take_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                name = self.ident()
                desc = False
                if self.take_kw("DESC"):
                    desc = True
                else:
                    self.take_kw("ASC")
                order_by.append((name, desc))
                if not self.take_sym(","):
                    break
        limit = None
        if self.take_kw("LIMIT"):
            limit = self.literal()
            if not isinstance(limit, ast.BindMarker) and (
                    not isinstance(limit, int) or limit < 0):
                raise InvalidArgument("LIMIT must be a non-negative integer")
        allow = False
        if self.take_kw("ALLOW"):
            self.expect_kw("FILTERING")
            allow = True
        return ast.Select(table, items, where, limit, allow,
                          group_by, order_by)

    def _select_item(self) -> ast.SelectItem:
        name = self.ident()
        if name in AGG_FNS and self.at_sym("("):
            self.next()
            if self.take_sym("*"):
                item = ast.SelectItem(None, agg_fn=name)
            else:
                expr = self._arith_expr()
                from yugabyte_db_tpu.storage.expr import Col
                if isinstance(expr, Col):
                    item = ast.SelectItem(expr.name, agg_fn=name)
                else:
                    item = ast.SelectItem(None, agg_fn=name, expr=expr)
            self.expect_sym(")")
        else:
            item = ast.SelectItem(name)
        if self.take_kw("AS"):
            item.alias = self.ident()
        return item

    def _arith_expr(self):
        """Arithmetic over columns and integer constants: + - * with the
        usual precedence and parentheses (storage.expr tree)."""
        from yugabyte_db_tpu.storage.expr import BinOp, Const

        left = self._arith_term()
        while True:
            if self.take_sym("+"):
                left = BinOp("+", left, self._arith_term())
            elif self.take_sym("-"):
                left = BinOp("-", left, self._arith_term())
            else:
                t = self.peek()
                # "a -5": the lexer folds the sign into the number.
                if t is not None and t.kind == "number" and \
                        t.text.startswith("-") and "." not in t.text:
                    self.next()
                    left = BinOp("+", left, Const(int(t.text)))
                else:
                    return left

    def _arith_term(self):
        from yugabyte_db_tpu.storage.expr import BinOp

        left = self._arith_factor()
        while self.take_sym("*"):
            left = BinOp("*", left, self._arith_factor())
        return left

    def _arith_factor(self):
        from yugabyte_db_tpu.storage.expr import Col, Const

        if self.take_sym("("):
            e = self._arith_expr()
            self.expect_sym(")")
            return e
        t = self.peek()
        if t is not None and t.kind == "number":
            self.next()
            if any(c in t.text for c in ".eE"):
                raise InvalidArgument(
                    "only integer constants in pushed-down expressions")
            return Const(int(t.text))
        return Col(self.ident())


    def _where_opt(self) -> list[ast.Relation]:
        if not self.take_kw("WHERE"):
            return []
        return self._relation_list()

    def _where_required(self) -> list[ast.Relation]:
        self.expect_kw("WHERE")
        return self._relation_list()

    def _relation_list(self) -> list[ast.Relation]:
        rels = [self._relation()]
        while self.take_kw("AND"):
            rels.append(self._relation())
        return rels

    def _relation(self) -> ast.Relation:
        col = self.ident()
        t = self.next()
        if t.kind == "name" and t.text.upper() == "IN":
            self.expect_sym("(")
            vals = [self.literal()]
            while self.take_sym(","):
                vals.append(self.literal())
            self.expect_sym(")")
            return ast.Relation(col, "IN", tuple(vals))
        if t.kind != "op":
            raise InvalidArgument(f"expected comparison operator, got {t}")
        return ast.Relation(col, t.text, self.literal())

    def _update(self):
        self.expect_kw("UPDATE")
        table = self.qualified_name()
        ttl = self._using_ttl()
        self.expect_kw("SET")
        assigns = [self._assignment()]
        while self.take_sym(","):
            assigns.append(self._assignment())
        return ast.Update(table, assigns, self._where_required(), ttl)

    def _assignment(self):
        col = self.ident()
        if self.take_sym("["):
            idx = self.literal()
            self.expect_sym("]")
            self.expect_sym("=")
            return (col, ast.CollectionOp("setelem", self.literal(),
                                          index=idx))
        self.expect_sym("=")
        # collection/counter edits reference the column itself:
        # v = v + [...], v = [...] + v, v = v - {...}, c = c + 1
        t = self.peek()
        if t is not None and t.kind == "name" and t.text.lower() == col \
                and self.i + 1 < len(self.toks):
            nxt = self.toks[self.i + 1]
            if nxt.text in "+-":
                self.ident()
                op = "append" if self.next().text == "+" else "remove"
                return (col, ast.CollectionOp(op, self.literal()))
            if nxt.kind == "number" and nxt.text.startswith("-"):
                # 'c = c -2': the tokenizer fused the sign into the
                # number; this is still a subtraction
                self.ident()
                v = self.literal()
                return (col, ast.CollectionOp("remove", -v))
        value = self.literal()
        if self.at_sym("+"):
            self.next()
            name = self.ident()
            if name != col:
                raise InvalidArgument(
                    f"prepend must reference {col}, got {name}")
            return (col, ast.CollectionOp("prepend", value))
        return (col, value)

    def _delete(self):
        self.expect_kw("DELETE")
        columns = None
        if not self.at_kw("FROM"):
            columns = [self.ident()]
            while self.take_sym(","):
                columns.append(self.ident())
        self.expect_kw("FROM")
        table = self.qualified_name()
        return ast.Delete(table, self._where_required(), columns)


def parse_statement(sql: str):
    """Parse one CQL statement -> ast node."""
    return Parser(sql).parse()
