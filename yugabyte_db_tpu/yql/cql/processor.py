"""QLProcessor: parse -> bind -> execute CQL against tablets.

Reference analog: ql::QLProcessor (src/yb/yql/cql/ql/ql_processor.h:55) with
its Prepare (parse+analyze) and Execute phases; execution lowers statements
to per-tablet read/write operations the way exec/executor.cc builds
QLReadRequestPB/QLWriteRequestPB and routes them through the client
(Batcher groups ops per tablet, src/yb/client/batcher.h:80).

The storage seam here is the ``Cluster`` protocol (create/drop/route/scan);
``LocalCluster`` runs tablets in-process (the MiniCluster test shape), and
the distributed client implements the same surface on top of the master
catalog + tserver RPCs.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from dataclasses import dataclass, field

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.encoding import (encode_doc_key_prefix,
                                             encode_key_component,
                                             prefix_successor)
from yugabyte_db_tpu.models.partition import (PartitionSchema,
                                              compute_hash_code)
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.storage.scan_spec import AggSpec, Predicate, ScanSpec
from yugabyte_db_tpu.tablet.tablet import Tablet, TabletMetadata
from yugabyte_db_tpu.utils.hybrid_time import HybridClock
from yugabyte_db_tpu.utils.metrics import count_swallowed
from yugabyte_db_tpu.utils.status import (AlreadyPresent, InvalidArgument,
                                          NotFound)
from yugabyte_db_tpu.yql.cql import ast
from yugabyte_db_tpu.yql.cql.parser import parse_statement


@dataclass
class ResultSet:
    """Rows returned to the driver (reference: QLRowBlock serialized into
    the CQL RESULT message)."""

    columns: list[str] = field(default_factory=list)
    rows: list[tuple] = field(default_factory=list)
    # Continuation token when a page filled before the scan finished
    # (reference: QLPagingStatePB riding the RESULT message).
    paging_state: bytes | None = None
    # Wire path: when set, the result is pre-serialized CQL cell bytes
    # (wire_rows rows) the server forwards verbatim — rows stays empty
    # (the rows_data contract, src/yb/common/ql_rowblock.h:66).
    wire_data: bytes | None = None
    wire_rows: int = 0

    def __iter__(self):
        return iter(self.rows)

    def dicts(self) -> list[dict]:
        return [dict(zip(self.columns, r)) for r in self.rows]


# -- cluster seam ------------------------------------------------------------

@dataclass
class TableHandle:
    name: str
    schema: Schema
    partition_schema: PartitionSchema
    tablets: list[Tablet]
    indexes: list = field(default_factory=list)  # [{"name","column","index_table"}]


class LocalCluster:
    """In-process tablet host: every table is num_tablets Tablets in one
    process (reference test shape: MiniCluster,
    src/yb/integration-tests/mini_cluster.h:92)."""

    def __init__(self, data_root: str | None = None, num_tablets: int = 4,
                 engine: str = "cpu", fsync: bool = False,
                 engine_options: dict | None = None):
        self._own_dir = data_root is None
        self.data_root = data_root or tempfile.mkdtemp(prefix="yb_tpu_")
        self.num_tablets = num_tablets
        self.engine = engine
        self.engine_options = engine_options
        self.fsync = fsync
        self.clock = HybridClock()
        self.tables: dict[str, TableHandle] = {}
        # User-defined types: name -> [(field, dtype int)].
        self.types: dict[str, list] = {}
        # SQL views (name -> defining query SQL) and sequences
        # (name -> next value) — in-process registries; the distributed
        # seam replicates them through the master catalog.
        self.views: dict[str, str] = {}
        self.sequences: dict[str, int] = {}
        # CQL keyspaces — cluster-wide (shared by every session; the
        # distributed seam replicates them through the master catalog).
        self.user_keyspaces: set[str] = set()
        self._seq_lock = __import__("threading").Lock()
        from yugabyte_db_tpu.auth import RoleStore

        self._auth = RoleStore()
        if engine == "tpu":
            import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401

    def auth_store(self):
        return self._auth

    def auth_op(self, op: dict) -> None:
        self._auth.apply(op)

    # -- keyspaces (shared across sessions) ---------------------------------
    def create_keyspace(self, name: str) -> None:
        from yugabyte_db_tpu.utils.status import AlreadyPresent

        if name in self.user_keyspaces:
            raise AlreadyPresent(f"keyspace {name} exists")
        self.user_keyspaces.add(name)

    def drop_keyspace(self, name: str) -> None:
        self.user_keyspaces.discard(name)

    def list_keyspaces(self) -> set:
        return set(self.user_keyspaces)

    def create_table(self, name: str, schema: Schema,
                     num_tablets: int | None = None) -> TableHandle:
        if name in self.tables:
            raise AlreadyPresent(f"table {name} exists")
        n = num_tablets or self.num_tablets
        pschema = PartitionSchema(n, hash_partitioned=schema.num_hash > 0)
        tablets = []
        for i, part in enumerate(pschema.create_partitions()):
            meta = TabletMetadata(
                tablet_id=f"{name}-t{i:04d}", table_name=name, schema=schema,
                partition_start=part.start, partition_end=part.end,
                engine=self.engine)
            tablets.append(Tablet.create(
                meta, os.path.join(self.data_root, name), clock=self.clock,
                fsync=self.fsync, engine_options=self.engine_options))
        handle = TableHandle(name, schema, pschema, tablets)
        self.tables[name] = handle
        return handle

    def drop_table(self, name: str) -> None:
        handle = self.tables.pop(name, None)
        if handle is None:
            raise NotFound(f"table {name} not found")
        for t in handle.tablets:
            t.close()
        shutil.rmtree(os.path.join(self.data_root, name), ignore_errors=True)

    def table(self, name: str) -> TableHandle:
        if name not in self.tables:
            raise NotFound(f"table {name} not found")
        return self.tables[name]

    def tablet_for_hash(self, handle: TableHandle, hash_code: int) -> Tablet:
        idx = handle.partition_schema.partition_index_for_hash(hash_code)
        return handle.tablets[idx]

    def create_index(self, base: TableHandle, name: str,
                     columns, include=()) -> str:
        from yugabyte_db_tpu.index import index_schema, index_table_name

        if isinstance(columns, str):
            columns = [columns]

        itable = index_table_name(base.name, columns, name)
        ischema = index_schema(base.schema, columns, itable, include)
        self.create_table(itable, ischema, num_tablets=len(base.tablets))
        base.indexes.append({"name": name, "column": columns[0],
                             "columns": list(columns),
                             "include": list(include),
                             "index_table": itable})
        return itable

    def drop_index(self, base: TableHandle, name: str) -> None:
        idx = next(i for i in base.indexes if i["name"] == name)
        base.indexes.remove(idx)
        self.drop_table(idx["index_table"])

    # -- user-defined types -------------------------------------------------
    def create_type(self, name: str, fields: list) -> None:
        self.types[name] = [tuple(f) for f in fields]

    def drop_type(self, name: str) -> None:
        for h in self.tables.values():
            for c in h.schema.columns:
                if c.udt == name:
                    raise InvalidArgument(
                        f"type {name} in use by table {h.name}")
        self.types.pop(name, None)

    def get_type(self, name: str):
        return self.types.get(name)

    def list_types(self) -> dict:
        return dict(self.types)

    # -- views / sequences --------------------------------------------------
    def create_view(self, name: str, query_sql: str,
                    replace: bool = False) -> None:
        if not replace and name in self.views:
            raise AlreadyPresent(f"view {name} exists")
        self.views[name] = query_sql

    def drop_view(self, name: str) -> None:
        if name not in self.views:
            raise NotFound(f"view {name} not found")
        del self.views[name]

    def get_view(self, name: str):
        return self.views.get(name)

    def create_sequence(self, name: str) -> None:
        with self._seq_lock:
            if name in self.sequences:
                raise AlreadyPresent(f"sequence {name} exists")
            self.sequences[name] = 1

    def drop_sequence(self, name: str) -> None:
        with self._seq_lock:
            if name not in self.sequences:
                raise NotFound(f"sequence {name} not found")
            del self.sequences[name]

    def sequence_next(self, name: str, n: int = 1) -> int:
        """Allocate ``n`` values; returns the first (PG nextval blocks
        may leave holes — same contract)."""
        with self._seq_lock:
            if name not in self.sequences:
                raise NotFound(f"sequence {name} not found")
            base = self.sequences[name]
            self.sequences[name] = base + n
            return base

    def alter_table(self, handle: TableHandle, new_schema: Schema) -> None:
        for t in handle.tablets:
            t.alter_schema(new_schema)
        handle.schema = new_schema

    def maintain_indexes(self, handle: TableHandle, base_key_values: dict,
                         old_values: dict | None, row) -> None:
        """Apply index mutations for one base write (the LocalCluster
        analog of the tserver leader's Tablet::UpdateQLIndexes hook)."""
        from yugabyte_db_tpu.index import index_mutations

        for itable, _is, hc, rv in index_mutations(
                handle.schema, handle.indexes, base_key_values,
                old_values, row):
            ih = self.table(itable)
            self.tablet_for_hash(ih, hc).write([rv])

    def close(self) -> None:
        for h in list(self.tables.values()):
            for t in h.tablets:
                t.close()
        self.tables.clear()
        if self._own_dir:
            shutil.rmtree(self.data_root, ignore_errors=True)


# -- the processor -----------------------------------------------------------

class Unauthorized(Exception):
    """Role lacks the permission a statement requires (fails closed;
    reference: UnauthorizedException from the CQL analyzer)."""


@dataclass
class _SelectPlan:
    """Planned SELECT routing: one tablet (hash fully bound) or fanout,
    plus the pushdown payload."""

    single: bool
    hash_code: int | None
    lower: bytes
    upper: bytes
    predicates: list
    projection: list | None
    aggregates: list
    group_by: list


@dataclass
class _PointStmtPlan:
    """Params-independent half of a prepared point SELECT's plan, cached
    per statement for the request-batch serving path: the '='-bound key
    relations (values still carry the bind markers), the projection, and
    the resolved handle. Per frame only coerce + encode + route remain."""

    stmt: object          # pins the statement so id() can't alias
    schema: object        # replan sentinel: compared by identity
    handle: object
    hash_rels: list       # [(ColumnSchema, ast.Relation)] hash order
    range_rels: list      # [(ColumnSchema, ast.Relation)] prefix order
    projection: list
    names: list


@dataclass
class _PointBounds:
    """Per-frame output of the cached point plan — the fields the batch
    serving loop reads (duck-typed subset of _SelectPlan)."""

    lower: bytes
    upper: bytes
    predicates: list


class QLProcessor:
    """One CQL session: keyspace state + statement execution.

    ``login_role`` is the authenticated role (set by the wire server's
    auth handshake). Enforcement is active when the
    ``use_cassandra_authentication`` flag is on: every statement then
    requires the matching permission on its resource, checked against
    the cluster's replicated role store (fails closed; reference:
    enforcement in the CQL analyzer against the auth vtables)."""

    _BUILTIN_KEYSPACES = frozenset({"default", "system"})

    def __init__(self, cluster: LocalCluster, login_role: str | None = None):
        self.cluster = cluster
        self.keyspace = "default"
        self.login_role = login_role
        # Structural plan cache for the request-batch serving path:
        # (id(stmt), keyspace) -> _PointStmtPlan. Statements live in the
        # server's prepared cache, so ids are stable; each entry pins its
        # stmt anyway so a collected id can never alias.
        self._point_stmt_cache: dict = {}

    @property
    def keyspaces(self) -> set:
        """All known keyspaces: the built-ins plus the cluster-wide
        registry (shared across connections — the reference keeps
        namespaces in the master sys catalog)."""
        return set(self._BUILTIN_KEYSPACES) | self.cluster.list_keyspaces()

    # -- entry points ------------------------------------------------------
    def execute(self, sql, params: list | None = None,
                page_size: int | None = None,
                paging_state: bytes | None = None,
                wire_results: bool = False) -> ResultSet | None:
        """Run one statement. ``sql`` may be a string or a pre-parsed AST
        (the prepared-statement cache passes ASTs). ``params`` binds ``?``
        markers by position; ``page_size``/``paging_state`` drive SELECT
        paging (reference: QLProcessor::RunAsync with a paged
        StatementParameters, ql_processor.h:86). ``wire_results=True``
        (the CQL socket server) lets eligible SELECTs return
        pre-serialized cell bytes (ResultSet.wire_data) instead of row
        tuples — the rows_data contract."""
        stmt = parse_statement(sql) if isinstance(sql, str) else sql
        self._params = params or []
        self._page_size = page_size
        self._paging_state = paging_state
        self._wire_results = wire_results
        self._enforce(stmt)
        fn = {
            ast.CreateKeyspace: self._exec_create_keyspace,
            ast.DropKeyspace: self._exec_drop_keyspace,
            ast.UseKeyspace: self._exec_use,
            ast.CreateTable: self._exec_create_table,
            ast.DropTable: self._exec_drop_table,
            ast.AlterTable: self._exec_alter_table,
            ast.CreateIndex: self._exec_create_index,
            ast.DropIndex: self._exec_drop_index,
            ast.CreateType: self._exec_create_type,
            ast.DropType: self._exec_drop_type,
            ast.Insert: self._exec_insert,
            ast.Update: self._exec_update,
            ast.Delete: self._exec_delete,
            ast.Select: self._exec_select,
            ast.Batch: self._exec_batch,
            ast.CreateRole: self._exec_create_role,
            ast.AlterRole: self._exec_alter_role,
            ast.DropRole: self._exec_drop_role,
            ast.GrantRevokeRole: self._exec_grant_revoke_role,
            ast.GrantRevokePermission: self._exec_grant_revoke_perm,
            ast.ListRoles: self._exec_list_roles,
            ast.ListPermissions: self._exec_list_permissions,
        }[type(stmt)]
        return fn(stmt)

    # -- authorization -----------------------------------------------------
    def _table_resource(self, name: str) -> str:
        ks, table = self._qualify(name).split(".", 1)
        return f"data/{ks}/{table}"

    def _stmt_permission(self, stmt):
        """(permission, resource) a statement requires, or None."""
        if isinstance(stmt, ast.Select):
            from yugabyte_db_tpu.yql.cql import vtables

            # Any authenticated role may read the system vtables (the
            # driver handshake path; Cassandra behaves the same).
            if vtables.is_virtual(self._qualify(stmt.table)):
                return None
            return ("SELECT", self._table_resource(stmt.table))
        if isinstance(stmt, (ast.Insert, ast.Update, ast.Delete)):
            return ("MODIFY", self._table_resource(stmt.table))
        if isinstance(stmt, ast.Batch):
            for s in stmt.statements:
                self._check_perm(*self._stmt_permission(s))
            return None
        if isinstance(stmt, ast.CreateTable):
            ks = self._qualify(stmt.name).split(".", 1)[0]
            return ("CREATE", f"data/{ks}")
        if isinstance(stmt, ast.DropTable):
            return ("DROP", self._table_resource(stmt.name))
        if isinstance(stmt, ast.AlterTable):
            return ("ALTER", self._table_resource(stmt.name))
        if isinstance(stmt, ast.CreateIndex):
            return ("ALTER", self._table_resource(stmt.table))
        if isinstance(stmt, ast.DropIndex):
            return ("ALTER", "data")
        if isinstance(stmt, ast.CreateKeyspace):
            return ("CREATE", "data")
        if isinstance(stmt, ast.DropKeyspace):
            return ("DROP", f"data/{stmt.name}")
        if isinstance(stmt, (ast.CreateRole, ast.AlterRole, ast.DropRole,
                             ast.GrantRevokeRole,
                             ast.GrantRevokePermission)):
            return ("AUTHORIZE", "roles")
        return None  # USE, LIST: any authenticated role

    def _enforce(self, stmt) -> None:
        from yugabyte_db_tpu.utils.flags import FLAGS

        if not FLAGS.get("use_cassandra_authentication"):
            return
        if self.login_role is None:
            raise Unauthorized("not authenticated")
        need = self._stmt_permission(stmt)
        if need is not None:
            self._check_perm(*need)

    def _check_perm(self, perm: str, resource: str) -> None:
        if not self.cluster.auth_store().authorize(
                self.login_role, perm, resource):
            raise Unauthorized(
                f"role {self.login_role} has no {perm} permission on "
                f"{resource}")

    # -- role DDL ----------------------------------------------------------
    def _exec_create_role(self, stmt: ast.CreateRole):
        from yugabyte_db_tpu import auth as A

        op = {"op": "auth_create_role", "name": stmt.name,
              "can_login": stmt.can_login, "superuser": stmt.superuser,
              "salted_hash": (A.hash_password(stmt.password)
                              if stmt.password is not None else "")}
        try:
            self.cluster.auth_op(op)
        except (AlreadyPresent, InvalidArgument):
            if not stmt.if_not_exists:
                raise
        return None

    def _exec_alter_role(self, stmt: ast.AlterRole):
        from yugabyte_db_tpu import auth as A

        op = {"op": "auth_alter_role", "name": stmt.name}
        if stmt.password is not None:
            op["salted_hash"] = A.hash_password(stmt.password)
        if stmt.can_login is not None:
            op["can_login"] = stmt.can_login
        if stmt.superuser is not None:
            op["superuser"] = stmt.superuser
        self.cluster.auth_op(op)
        return None

    def _exec_drop_role(self, stmt: ast.DropRole):
        try:
            self.cluster.auth_op({"op": "auth_drop_role",
                                  "name": stmt.name})
        except (NotFound, InvalidArgument):
            if not stmt.if_exists:
                raise
        return None

    def _exec_grant_revoke_role(self, stmt: ast.GrantRevokeRole):
        self.cluster.auth_op({
            "op": "auth_grant_role" if stmt.grant else "auth_revoke_role",
            "role": stmt.role, "member": stmt.member})
        return None

    def _exec_grant_revoke_perm(self, stmt: ast.GrantRevokePermission):
        resource = stmt.resource
        if resource.startswith("data//"):
            # unqualified table: resolve against the session keyspace
            resource = f"data/{self.keyspace}/{resource[len('data//'):]}"
        self.cluster.auth_op({
            "op": "auth_grant_perm" if stmt.grant else "auth_revoke_perm",
            "role": stmt.role, "resource": resource,
            "perm": stmt.permission})
        return None

    def _exec_list_roles(self, _stmt):
        rows = [(r.name, r.can_login, r.superuser,
                 sorted(r.member_of))
                for r in self.cluster.auth_store().list_roles()]
        return ResultSet(["role", "can_login", "is_superuser",
                          "member_of"], rows)

    def _exec_list_permissions(self, _stmt):
        return ResultSet(["role", "resource", "permission"],
                         self.cluster.auth_store().list_perms())

    # -- name resolution ---------------------------------------------------
    def _qualify(self, name: str) -> str:
        return name if "." in name else f"{self.keyspace}.{name}"

    # -- DDL ---------------------------------------------------------------
    def _exec_create_keyspace(self, stmt: ast.CreateKeyspace):
        if stmt.name in self.keyspaces:
            if not stmt.if_not_exists:
                raise AlreadyPresent(f"keyspace {stmt.name} exists")
            return None
        try:
            self.cluster.create_keyspace(stmt.name)
        except AlreadyPresent:
            # Lost a create race: same end state.
            if not stmt.if_not_exists:
                raise
        return None

    def _exec_drop_keyspace(self, stmt: ast.DropKeyspace):
        if stmt.name in self._BUILTIN_KEYSPACES:
            raise InvalidArgument(
                f"keyspace {stmt.name} cannot be dropped")
        if stmt.name not in self.keyspaces:
            if not stmt.if_exists:
                raise NotFound(f"keyspace {stmt.name} not found")
            return None
        in_use = [t for t in self.cluster.tables
                  if t.startswith(stmt.name + ".")]
        if in_use:
            raise InvalidArgument(f"keyspace {stmt.name} is not empty")
        try:
            self.cluster.drop_keyspace(stmt.name)
        except NotFound:
            # Lost a drop race: same end state.
            if not stmt.if_exists:
                raise
        return None

    def _exec_use(self, stmt: ast.UseKeyspace):
        if stmt.name not in self.keyspaces:
            raise NotFound(f"keyspace {stmt.name} not found")
        self.keyspace = stmt.name
        return None

    def _exec_create_table(self, stmt: ast.CreateTable):
        name = self._qualify(stmt.name)
        if name in self.cluster.tables:
            if stmt.if_not_exists:
                return None
            raise AlreadyPresent(f"table {name} exists")
        by_name = {c.name: c for c in stmt.columns}
        for k in stmt.hash_keys + stmt.range_keys:
            if k not in by_name:
                raise InvalidArgument(f"primary key column {k} not defined")
        cols = []
        for c in stmt.columns:
            if c.name in stmt.hash_keys:
                kind = ColumnKind.HASH
            elif c.name in stmt.range_keys:
                kind = ColumnKind.RANGE
            elif c.is_static:
                kind = ColumnKind.STATIC
            else:
                kind = ColumnKind.REGULAR
            if kind in (ColumnKind.HASH, ColumnKind.RANGE) and \
                    c.dtype in (DataType.FLOAT, DataType.DOUBLE):
                raise InvalidArgument(
                    f"floating-point column {c.name} cannot be a key column")
            udt = None
            if getattr(c, "udt", None):
                udt = self._qualify(c.udt) if "." not in c.udt else c.udt
                if self.cluster.get_type(udt) is None:
                    raise InvalidArgument(f"unknown type {c.udt}")
                if kind != ColumnKind.REGULAR:
                    raise InvalidArgument(
                        f"UDT column {c.name} cannot be a key column")
            cols.append(ColumnSchema(c.name, c.dtype, kind,
                                     nullable=kind == ColumnKind.REGULAR,
                                     udt=udt))
        schema = Schema(cols, table_id=name)
        num_tablets = stmt.properties.get("tablets")
        self.cluster.create_table(name, schema, num_tablets)
        return None

    def _exec_alter_table(self, stmt: ast.AlterTable):
        """Schema evolution by stable column ids (reference:
        catalog_manager AlterTable -> tablet AlterSchema). ADD columns are
        NULL for existing rows; DROP retires the id (never reused);
        RENAME touches no data."""
        from yugabyte_db_tpu.yql.common import evolve_schema

        handle = self.cluster.table(self._qualify(stmt.name))
        self.cluster.alter_table(handle, evolve_schema(
            handle, stmt.action, stmt.column, stmt.dtype, stmt.new_name))
        return None

    def _exec_batch(self, stmt: ast.Batch):
        """Execute a BATCH's statements in order. Statements grouped per
        tablet are atomic per tablet; cross-tablet batches are not atomic
        (the reference's non-transactional batches behave the same)."""
        for sub in stmt.statements:
            self.execute(sub, params=self._params)
        return None

    def _exec_drop_table(self, stmt: ast.DropTable):
        name = self._qualify(stmt.name)
        try:
            self.cluster.drop_table(name)
        except NotFound:
            if not stmt.if_exists:
                raise
        return None

    # -- secondary indexes --------------------------------------------------
    # -- user-defined types -------------------------------------------------
    def _exec_create_type(self, stmt: ast.CreateType):
        name = self._qualify(stmt.name)
        if self.cluster.get_type(name) is not None:
            if stmt.if_not_exists:
                return None
            raise AlreadyPresent(f"type {name} exists")
        seen = set()
        for fname, _dt in stmt.fields:
            if fname in seen:
                raise InvalidArgument(f"duplicate field {fname}")
            seen.add(fname)
        self.cluster.create_type(
            name, [(f, int(dt)) for f, dt in stmt.fields])
        return None

    def _exec_drop_type(self, stmt: ast.DropType):
        name = self._qualify(stmt.name)
        if self.cluster.get_type(name) is None:
            if stmt.if_exists:
                return None
            raise NotFound(f"type {name} not found")
        self.cluster.drop_type(name)
        return None

    def _exec_create_index(self, stmt: ast.CreateIndex):
        handle = self.cluster.table(self._qualify(stmt.table))
        if any(i["name"] == stmt.name
               for i in getattr(handle, "indexes", [])):
            if stmt.if_not_exists:
                return None
            raise AlreadyPresent(f"index {stmt.name} exists")
        if len(set(stmt.columns)) != len(stmt.columns):
            raise InvalidArgument("duplicate indexed column")
        for col in list(stmt.columns) + list(stmt.include):
            if not handle.schema.has_column(col):
                raise InvalidArgument(f"unknown column {col}")
            if handle.schema.column(col).is_key:
                raise InvalidArgument(f"cannot index key column {col}")
        for col in stmt.include:
            if col in stmt.columns:
                raise InvalidArgument(
                    f"covered column {col} is already indexed")
        itable = self.cluster.create_index(handle, stmt.name,
                                           list(stmt.columns),
                                           list(stmt.include))
        self._backfill_index(handle, list(stmt.columns), itable,
                             list(stmt.include))
        return None

    def _exec_drop_index(self, stmt: ast.DropIndex):
        for name in list(self.cluster.tables):
            try:
                handle = self.cluster.table(name)
            except NotFound:
                continue
            for idx in getattr(handle, "indexes", []):
                if idx["name"] == stmt.name:
                    self.cluster.drop_index(handle, stmt.name)
                    return None
        if not stmt.if_exists:
            raise NotFound(f"index {stmt.name} not found")
        return None

    def _backfill_index(self, handle: TableHandle, columns,
                        itable: str, include=()) -> None:
        """Populate the index from existing base rows. Writes land
        through the normal index-table write path; concurrent base
        writes during the scan are covered by their own maintenance."""
        from yugabyte_db_tpu.index import index_entry

        if isinstance(columns, str):
            columns = [columns]
        include = list(include)
        ih = self.cluster.table(itable)
        key_names = [c.name for c in handle.schema.key_columns]
        nk = len(key_names)
        proj = key_names + list(columns) + include
        for tablet in handle.tablets:
            spec = ScanSpec(read_ht=tablet.read_time().value,
                            projection=proj)
            res = tablet.scan(spec)
            for row in res.rows:
                values = list(row[nk:nk + len(columns)])
                if any(v is None for v in values):
                    continue
                base_kv = dict(zip(key_names, row[:nk]))
                covered = dict(zip(include, row[nk + len(columns):]))
                hc, rv = index_entry(ih.schema, values, base_kv, covered)
                self.cluster.tablet_for_hash(ih, hc).write([rv])

    def _index_for_predicates(self, handle, predicates):
        """(index info, [eq preds in index-column order]) when EVERY
        indexed column is '='-bound (compound-hash lookups need the full
        hash tuple; reference: index selection in pt_select.cc)."""
        from yugabyte_db_tpu.index import normalize_index

        eq = {p.column: p for p in predicates if p.op == "="}
        for idx in getattr(handle, "indexes", []):
            ni = normalize_index(idx)
            if ni["columns"] and all(c in eq for c in ni["columns"]):
                return ni, [eq[c] for c in ni["columns"]]
        return None, None

    def _run_index_lookup(self, handle, stmt, plan, idx, preds):
        """Index-driven SELECT: hash-routed scan of the index table for
        base PKs, then base-row point reads re-verifying predicates (a
        stale index entry — possible while an index write has landed but
        its base write failed — filters out here). A COVERED query —
        projection and remaining predicates within indexed + key +
        INCLUDE columns — is answered from the index table alone, never
        touching the base table (reference: index-only scans over
        IndexInfo's covered columns, src/yb/common/index.h; SELECT
        planning in src/yb/yql/cql/ql/ptree/pt_select.cc). Contract
        note: the reference maintains indexes transactionally, so
        index-only results are always consistent; here maintenance is
        index-write-first best-effort, so a covered read can briefly
        surface an entry whose base write failed mid-flight — the
        non-covered path's base re-verification filters those, covered
        reads trade that window for never touching the base table."""
        ih = self.cluster.table(idx["index_table"])
        ischema = ih.schema
        values = [self._coerce(handle.schema.column(p.column), p.value)
                  for p in preds]
        kv = {p.column: v for p, v in zip(preds, values)}
        hc = compute_hash_code(ischema, kv)
        prefix = encode_doc_key_prefix(
            hc, [(kv[c.name], c.dtype) for c in ischema.hash_columns], [])
        key_names = [c.name for c in handle.schema.key_columns]

        projection = plan.projection or [c.name for c in
                                         handle.schema.columns]
        names = ([it.output_name for it in stmt.items] if stmt.items
                 else list(projection))
        limit = self._coerce_limit(stmt.limit)
        itablet = self.cluster.tablet_for_hash(ih, hc)

        eq_cols = {p.column for p in preds}
        index_cols = {c.name for c in ischema.columns}
        residual = [p for p in plan.predicates if p.column not in eq_cols]
        covered = (set(projection) <= index_cols and
                   all(p.column in index_cols for p in residual))
        if covered:
            ires = itablet.scan(ScanSpec(
                lower=prefix, upper=prefix_successor(prefix),
                read_ht=itablet.read_time().value,
                predicates=residual, projection=list(projection),
                limit=limit))
            out = ResultSet(columns=names)
            out.rows.extend(ires.rows)
            return out

        ires = itablet.scan(ScanSpec(
            lower=prefix, upper=prefix_successor(prefix),
            read_ht=itablet.read_time().value, projection=key_names))
        out = ResultSet(columns=names)
        for irow in ires.rows:
            base_kv = dict(zip(key_names, irow))
            bkey, btablet = self._key_and_tablet(handle, base_kv)
            bres = btablet.scan(ScanSpec(
                lower=bkey, upper=bkey + b"\x00",
                read_ht=btablet.read_time().value,
                predicates=plan.predicates, projection=projection,
                limit=1))
            out.rows.extend(bres.rows)
            if limit is not None and len(out.rows) >= limit:
                break
        return out

    # -- bind markers --------------------------------------------------------
    def _resolve_marker(self, value):
        """BindMarker -> the positional param; other values pass through."""
        if isinstance(value, ast.BindMarker):
            try:
                return self._params[value.index]
            except IndexError:
                raise InvalidArgument(
                    f"bind marker ${value.index} has no value "
                    f"({len(self._params)} params supplied)") from None
        return value

    @staticmethod
    def _require_nonneg_int(value, what: str):
        if value is not None and (not isinstance(value, int)
                                  or isinstance(value, bool) or value < 0):
            raise InvalidArgument(f"{what} must be a non-negative integer")
        return value

    # -- writes ------------------------------------------------------------
    def _coerce(self, col: ColumnSchema, value):
        from yugabyte_db_tpu.yql.common import coerce_udt, coerce_value

        value = self._resolve_marker(value)
        if col.udt:
            fields = self.cluster.get_type(col.udt)
            if fields is None:
                raise InvalidArgument(f"unknown type {col.udt}")
            return coerce_udt(col, value, fields)
        return coerce_value(col, value)

    def _key_and_tablet(self, handle: TableHandle, key_values: dict):
        from yugabyte_db_tpu.yql.common import key_and_tablet

        return key_and_tablet(self.cluster, handle, key_values)

    def _expire_ht(self, ttl_seconds):
        ttl_seconds = self._require_nonneg_int(
            self._resolve_marker(ttl_seconds), "TTL")
        if ttl_seconds is None:
            return MAX_HT
        now = self.cluster.clock.now()
        from yugabyte_db_tpu.utils.hybrid_time import HybridTime
        return HybridTime.from_micros(
            now.physical_micros + ttl_seconds * 1_000_000,
            now.logical).value

    def _exec_insert(self, stmt: ast.Insert):
        handle = self.cluster.table(self._qualify(stmt.table))
        schema = handle.schema
        provided = dict(zip(stmt.columns, stmt.values))
        for cname in provided:
            if not schema.has_column(cname):
                raise InvalidArgument(f"unknown column {cname}")
        key_values, columns = {}, {}
        for c in schema.key_columns:
            if c.name not in provided or provided[c.name] is None:
                raise InvalidArgument(f"missing key column {c.name}")
            key_values[c.name] = self._coerce(c, provided[c.name])
        for c in schema.value_columns:
            if c.name in provided:
                columns[c.col_id] = self._coerce(c, provided[c.name])
        key, tablet = self._key_and_tablet(handle, key_values)
        if stmt.if_not_exists:
            # Conditional insert: CQL returns an [applied] row. (The
            # reference runs this as a read-modify-write inside the tablet,
            # cql_operation.cc QLWriteOperation::ApplyForRegularColumns.)
            spec = ScanSpec(lower=key, upper=key + b"\xff",
                            read_ht=tablet.read_time().value, limit=1)
            if tablet.scan(spec).rows:
                return ResultSet(columns=["[applied]"], rows=[(False,)])
            self._write_row(handle, key_values, key, tablet, RowVersion(
                key, ht=0, liveness=True, columns=columns,
                expire_ht=self._expire_ht(stmt.ttl_seconds)))
            return ResultSet(columns=["[applied]"], rows=[(True,)])
        self._write_row(handle, key_values, key, tablet, RowVersion(
            key, ht=0, liveness=True, columns=columns,
            expire_ht=self._expire_ht(stmt.ttl_seconds)))
        return None

    def _bound_key_values(self, schema: Schema, where: list[ast.Relation],
                          require_full_key: bool) -> tuple[dict, list]:
        """Split WHERE into full-PK equality bindings + leftover relations."""
        key_values, leftover = {}, []
        key_names = {c.name for c in schema.key_columns}
        for rel in where:
            if rel.column in key_names and rel.op == "=" and \
                    rel.column not in key_values:
                key_values[rel.column] = rel.value
            else:
                leftover.append(rel)
        if require_full_key:
            missing = key_names - set(key_values)
            if missing:
                raise InvalidArgument(
                    f"DML requires the full primary key; missing {sorted(missing)}")
            if leftover:
                raise InvalidArgument(
                    "non-key relations not allowed in UPDATE/DELETE WHERE")
        coerced = {}
        for c in schema.key_columns:
            if c.name in key_values:
                coerced[c.name] = self._coerce(c, key_values[c.name])
        return coerced, leftover

    def _exec_update(self, stmt: ast.Update):
        handle = self.cluster.table(self._qualify(stmt.table))
        schema = handle.schema
        key_values, _ = self._bound_key_values(schema, stmt.where, True)

        def is_counter_op(col, v):
            return (isinstance(v, ast.CollectionOp)
                    and col.dtype.is_integer
                    and v.op in ("append", "remove")
                    and isinstance(self._resolve_marker(v.operand), int))

        # Collection edits (v = v + [...], v[k] = x) are read-modify-write
        # against the current row state; counter increments are NOT — they
        # ship as deltas the tablet leader resolves atomically under its
        # write serialization lock (Tablet.resolve_increments).
        coll_cols = [cname for cname, v in stmt.assignments
                     if isinstance(v, ast.CollectionOp)
                     and not is_counter_op(schema.column(cname), v)]
        old_row = {}
        if coll_cols:
            key0, tablet0 = self._key_and_tablet(handle, key_values)
            res = tablet0.scan(ScanSpec(
                lower=key0, upper=key0 + b"\x00",
                read_ht=tablet0.read_time().value, projection=coll_cols,
                limit=1))
            if res.rows:
                old_row = dict(zip(res.columns, res.rows[0]))
        columns = {}
        increments = {}
        for cname, value in stmt.assignments:
            if not schema.has_column(cname):
                raise InvalidArgument(f"unknown column {cname}")
            col = schema.column(cname)
            if col.is_key:
                raise InvalidArgument(f"cannot SET key column {cname}")
            if is_counter_op(col, value):
                delta = self._resolve_marker(value.operand)
                increments[col.col_id] = (
                    delta if value.op == "append" else -delta)
            elif isinstance(value, ast.CollectionOp):
                columns[col.col_id] = self._apply_collection_op(
                    col, old_row.get(cname), value)
            else:
                columns[col.col_id] = self._coerce(col, value)
        key, tablet = self._key_and_tablet(handle, key_values)
        # CQL UPDATE is an upsert of the SET columns (no liveness marker:
        # the row exists only while some column is live — reference
        # semantics of UPDATE vs INSERT in DocDB).
        self._write_row(handle, key_values, key, tablet, RowVersion(
            key, ht=0, columns=columns, increments=increments,
            expire_ht=self._expire_ht(stmt.ttl_seconds)))
        return None

    def _apply_collection_op(self, col: ColumnSchema, old,
                             op: ast.CollectionOp):
        """Evaluate one collection edit against the row's current value
        (reference: per-element subdocument writes in cql_operation.cc;
        the observable end state is the same for serialized writers)."""
        dt = col.dtype
        operand = self._resolve_marker(op.operand)
        if op.op == "setelem":
            idx = self._resolve_marker(op.index)
            if dt == DataType.MAP:
                m = dict(old or {})
                m[idx] = operand
                return dict(sorted(m.items()))
            if dt == DataType.LIST:
                if old is None or not isinstance(idx, int) or \
                        not 0 <= idx < len(old):
                    raise InvalidArgument(
                        f"list index {idx!r} out of bounds for {col.name}")
                out = list(old)
                out[idx] = operand
                return out
            raise InvalidArgument(f"{col.name} is not a list or map")
        if op.op == "prepend":
            if dt != DataType.LIST:
                raise InvalidArgument(f"can only prepend to a list")
            return list(operand) + list(old or [])
        if op.op == "append":
            if dt == DataType.LIST:
                return list(old or []) + list(operand)
            if dt == DataType.SET:
                return sorted(set(old or []) | set(operand))
            if dt == DataType.MAP:
                return dict(sorted({**(old or {}), **operand}.items()))
        if op.op == "remove":
            if dt == DataType.LIST:
                drop = set(operand)
                return [v for v in (old or []) if v not in drop]
            if dt == DataType.SET:
                return sorted(set(old or []) - set(operand))
            if dt == DataType.MAP:
                keys = set(operand if not isinstance(operand, dict)
                           else operand.keys())
                return dict(sorted((k, v) for k, v in (old or {}).items()
                                   if k not in keys))
        raise InvalidArgument(
            f"unsupported collection op on {col.name} ({dt.name})")

    def _exec_delete(self, stmt: ast.Delete):
        handle = self.cluster.table(self._qualify(stmt.table))
        schema = handle.schema
        key_values, _ = self._bound_key_values(schema, stmt.where, True)
        key, tablet = self._key_and_tablet(handle, key_values)
        if stmt.columns:
            columns = {}
            for cname in stmt.columns:
                if not schema.has_column(cname):
                    raise InvalidArgument(f"unknown column {cname}")
                col = schema.column(cname)
                if col.is_key:
                    raise InvalidArgument(f"cannot DELETE key column {cname}")
                columns[col.col_id] = None   # column tombstone
            self._write_row(handle, key_values, key, tablet,
                            RowVersion(key, ht=0, columns=columns))
        else:
            self._write_row(handle, key_values, key, tablet,
                            RowVersion(key, ht=0, tombstone=True))
        return None

    def _write_row(self, handle, key_values: dict, key: bytes, tablet,
                   row: RowVersion) -> None:
        """Write one row, maintaining secondary indexes when the cluster
        seam does maintenance locally (LocalCluster); the distributed
        seam's tserver leaders maintain indexes in their own write path."""
        if getattr(handle, "indexes", None) and \
                getattr(self.cluster, "maintain_indexes", None):
            from yugabyte_db_tpu.index import normalize_index

            indexed_cids = set()
            for i in handle.indexes:
                ni = normalize_index(i)
                for cname in ni["columns"] + ni["include"]:
                    indexed_cids.add(handle.schema.column(cname).col_id)
            if row.tombstone or (indexed_cids & row.columns.keys()):
                # Local maintenance only runs over real in-process
                # Tablets, which own the canonical old-state read.
                old = tablet.current_row_values(key)
                self.cluster.maintain_indexes(handle, key_values, old, row)
        tablet.write([row])

    # -- reads -------------------------------------------------------------
    def _exec_select(self, stmt: ast.Select):
        from yugabyte_db_tpu.yql.cql import vtables

        if vtables.is_virtual(self._qualify(stmt.table)):
            return vtables.virtual_select(self, stmt)
        handle = self.cluster.table(self._qualify(stmt.table))
        schema = handle.schema
        plan = self._plan_select(handle, stmt)
        ordered = bool(getattr(stmt, "order_by", None))
        if ordered and self._page_size:
            raise InvalidArgument("ORDER BY cannot combine with paging")
        if plan.aggregates:
            return self._run_aggregate(handle, stmt, plan)
        # SQL order of operations: ORDER BY sorts the FULL result, LIMIT
        # truncates afterwards — so an ordered select scans unlimited and
        # slices post-sort.
        import dataclasses as _dc
        scan_stmt = _dc.replace(stmt, limit=None) if ordered else stmt
        if not plan.single:
            idx, pred = self._index_for_predicates(handle, plan.predicates)
            if idx is not None:
                res = self._apply_order_by(stmt, self._run_index_lookup(
                    handle, scan_stmt, plan, idx, pred))
                return self._slice_limit(stmt, res) if ordered else res
        if not ordered and getattr(self, "_wire_results", False) and \
                self._wire_eligible(handle, stmt, plan):
            return self._run_rows(handle, scan_stmt, plan, wire=True)
        res = self._apply_order_by(
            stmt, self._run_rows(handle, scan_stmt, plan))
        return self._slice_limit(stmt, res) if ordered else res

    def _wire_eligible(self, handle, stmt, plan) -> bool:
        """Plain row SELECTs whose projection is scalar columns ride the
        wire path: tablets return serialized CQL cell bytes the server
        forwards verbatim (reference: rows_data,
        src/yb/common/ql_rowblock.h:66 -> cql_processor.cc). Aggregates,
        ORDER BY, aliased/rewritten items, and opaque-typed columns
        (collections/UDTs serialize driver-specifically) take the row
        path."""
        if plan.aggregates or getattr(stmt, "order_by", None):
            return False
        schema = handle.schema
        projection = plan.projection or [c.name for c in schema.columns]
        if stmt.items and [it.output_name for it in stmt.items] != \
                list(projection):
            return False  # aliases: names differ from engine columns
        for name in projection:
            col = schema.column(name)
            dt = col.dtype
            if not dt.is_fixed_width and dt not in (DataType.STRING,
                                                    DataType.BINARY):
                return False
            if getattr(col, "udt", None):
                return False
        # Route capability: both seams' tablet objects expose scan_wire;
        # probe one representative instead of resolving the target set
        # (which _run_rows resolves again right after).
        ts = handle.tablets
        return bool(ts) and hasattr(ts[0], "scan_wire")

    def _slice_limit(self, stmt, rs: ResultSet) -> ResultSet:
        limit = self._coerce_limit(stmt.limit)
        if limit is not None:
            rs.rows = rs.rows[:limit]
        return rs

    def _plan_select(self, handle: TableHandle, stmt: ast.Select):
        schema = handle.schema
        hash_names = [c.name for c in schema.hash_columns]
        range_cols = schema.range_columns

        eq = {}
        rest: list[ast.Relation] = []
        for rel in stmt.where:
            col = rel.column
            if not schema.has_column(col):
                raise InvalidArgument(f"unknown column {col} in WHERE")
            if rel.op == "=" and col not in eq and (
                    col in hash_names or
                    col in [c.name for c in range_cols]):
                eq[col] = self._coerce(schema.column(col), rel.value)
            else:
                rest.append(rel)

        # Single-tablet point/range plan when every hash column is '='-bound.
        single = all(name in eq for name in hash_names) and schema.num_hash
        hash_code = None
        lower = b""
        upper = b""
        if single:
            hash_code = compute_hash_code(
                schema, {n: eq[n] for n in hash_names})
            hashed = [(eq[n], schema.column(n).dtype) for n in hash_names]
            # Extend the prefix with leading '='-bound range columns.
            bound_ranges = []
            i = 0
            while i < len(range_cols) and range_cols[i].name in eq:
                c = range_cols[i]
                bound_ranges.append((eq[c.name], c.dtype))
                i += 1
            prefix = encode_doc_key_prefix(hash_code, hashed, bound_ranges)
            lower, upper = prefix, prefix_successor(prefix)
            # '='-bound range columns past the first unbound one can't join
            # the prefix; re-emit them as row predicates.
            consumed = set(hash_names) | {c.name for c in range_cols[:i]}
            for name, v in eq.items():
                if name not in consumed:
                    rest.append(ast.Relation(name, "=", v))
            # One more range column may carry inequalities tightening bounds.
            if i < len(range_cols):
                nxt = range_cols[i]
                keep = []
                for rel in rest:
                    if rel.column != nxt.name or rel.op in ("IN", "!="):
                        keep.append(rel)
                        continue
                    v = self._coerce(nxt, rel.value)
                    comp = encode_key_component(v, nxt.dtype)
                    if rel.op in (">", ">="):
                        cand = prefix + (prefix_successor(comp)
                                         if rel.op == ">" else comp)
                        lower = max(lower, cand)
                    elif rel.op in ("<", "<="):
                        cand = prefix + (prefix_successor(comp)
                                         if rel.op == "<=" else comp)
                        if upper == b"" or (cand != b"" and cand < upper):
                            upper = cand
                    elif rel.op == "=":
                        lo = prefix + comp
                        lower = max(lower, lo)
                        cand = prefix + prefix_successor(comp)
                        if upper == b"" or (cand != b"" and cand < upper):
                            upper = cand
                rest = keep
        else:
            # eq bindings on range cols without hash bindings: filter later.
            for name, v in eq.items():
                rest.append(ast.Relation(name, "=", v))

        predicates = []
        for rel in rest:
            col = schema.column(rel.column)
            value = (tuple(self._coerce(col, v) for v in rel.value)
                     if rel.op == "IN" else self._coerce(col, rel.value))
            predicates.append(Predicate(rel.column, rel.op, value))

        group_by = list(getattr(stmt, "group_by", []) or [])
        for g in group_by:
            if not schema.has_column(g):
                raise InvalidArgument(f"unknown GROUP BY column {g}")
        aggregates = []
        if stmt.items and any(it.agg_fn for it in stmt.items):
            from yugabyte_db_tpu.storage.expr import columns_of
            for it in stmt.items:
                if it.agg_fn:
                    continue
                if it.column not in group_by:
                    raise InvalidArgument(
                        "plain columns in an aggregate SELECT must appear "
                        "in GROUP BY")
            for it in stmt.items:
                if not it.agg_fn:
                    continue
                if it.column and not schema.has_column(it.column):
                    raise InvalidArgument(f"unknown column {it.column}")
                if it.expr is not None:
                    for cname in columns_of(it.expr):
                        if not schema.has_column(cname):
                            raise InvalidArgument(f"unknown column {cname}")
                aggregates.append(AggSpec(it.agg_fn, it.column,
                                          expr=it.expr,
                                          label=it.output_name))
        elif group_by:
            raise InvalidArgument("GROUP BY requires aggregate items")

        projection = None
        if stmt.items and not aggregates:
            for it in stmt.items:
                if not schema.has_column(it.column):
                    raise InvalidArgument(f"unknown column {it.column}")
            projection = [it.column for it in stmt.items]

        return _SelectPlan(bool(single), hash_code, lower, upper,
                           predicates, projection, aggregates, group_by)

    def _target_tablets(self, handle: TableHandle, plan):
        if plan.single and handle.schema.num_hash:
            return [self.cluster.tablet_for_hash(handle, plan.hash_code)]
        return handle.tablets

    def _run_rows(self, handle: TableHandle, stmt: ast.Select, plan,
                  wire: bool = False):
        from yugabyte_db_tpu.utils import codec

        schema = handle.schema
        projection = plan.projection or [c.name for c in schema.columns]
        if stmt.items:
            names = [it.output_name for it in stmt.items]
        else:
            names = list(projection)
        out = ResultSet(columns=names)
        wire_parts: list[bytes] = []
        tablets = self._target_tablets(handle, plan)
        # Paging token: (tablet index, resume key, LIMIT budget left,
        # pinned read time) — the QLPagingStatePB shape
        # (next_partition_key + next_row_key + remaining limit +
        # read_time, so every page reads the same snapshot).
        start_idx = 0
        resume = plan.lower
        limit = self._coerce_limit(stmt.limit)
        read_ht = None
        if self._paging_state:
            start_idx, resume, limit, read_ht = codec.decode(
                self._paging_state)
        page_left = self._page_size

        def finish():
            if wire:
                out.wire_data = b"".join(wire_parts)
            return out

        for idx in range(start_idx, len(tablets)):
            tablet = tablets[idx]
            lower = resume if idx == start_idx else plan.lower
            while True:
                sub_limit = self._min_opt(limit, page_left)
                spec = ScanSpec(
                    lower=lower, upper=plan.upper,
                    read_ht=(read_ht if read_ht is not None
                             else tablet.read_time().value),
                    predicates=plan.predicates,
                    projection=projection, limit=sub_limit)
                if wire:
                    res = tablet.scan_wire(spec)
                    wire_parts.append(res.data)
                    out.wire_rows += res.nrows
                    n = res.nrows
                    resume_key = res.resume
                else:
                    res = tablet.scan(spec)
                    out.rows.extend(res.rows)
                    n = len(res.rows)
                    resume_key = res.resume_key
                if read_ht is None:
                    # Pin the first sub-scan's (server-chosen) read time
                    # for the rest of the scan and for later pages.
                    read_ht = getattr(res, "read_ht", None) or spec.read_ht
                if limit is not None:
                    limit -= n
                    if limit <= 0:
                        return finish()
                if page_left is not None:
                    page_left -= n
                    if page_left <= 0:
                        # Page full: remember where the scan resumes.
                        if resume_key is not None:
                            out.paging_state = codec.encode(
                                [idx, resume_key, limit, read_ht])
                        elif idx + 1 < len(tablets):
                            out.paging_state = codec.encode(
                                [idx + 1, plan.lower, limit, read_ht])
                        return finish()
                if resume_key is None:
                    break
                lower = resume_key
        return finish()

    def _coerce_limit(self, limit):
        return self._require_nonneg_int(self._resolve_marker(limit),
                                        "LIMIT")

    @staticmethod
    def _min_opt(a, b):
        if a is None:
            return b
        if b is None:
            return a
        return min(a, b)

    def _point_stmt_plan(self, stmt):
        """Build (and cache) the params-independent plan of a prepared
        point SELECT: which columns the '='-bound WHERE covers, the
        projection, and wire eligibility. Returns None when the
        statement's shape can never ride the batch path. The per-frame
        remainder is just coerce + key encode + hash route."""
        from yugabyte_db_tpu.yql.cql import vtables

        if type(stmt) is not ast.Select or stmt.limit is not None \
                or getattr(stmt, "order_by", None):
            return None
        if vtables.is_virtual(self._qualify(stmt.table)):
            return None
        handle = self.cluster.table(self._qualify(stmt.table))
        schema = handle.schema
        hash_cols = schema.hash_columns
        if not hash_cols:
            return None
        # Every relation must be '=' on a distinct key column, covering
        # all hash columns plus a PREFIX of the range columns — exactly
        # the shape _plan_select turns into [prefix, successor(prefix))
        # with no residual predicates and no bound tightening.
        by_col = {}
        for rel in stmt.where:
            if rel.op != "=" or rel.column in by_col:
                return None
            by_col[rel.column] = rel
        if any(c.name not in by_col for c in hash_cols):
            return None
        range_prefix = []
        rest = set(by_col) - {c.name for c in hash_cols}
        for c in schema.range_columns:
            if c.name not in rest:
                break
            rest.discard(c.name)
            range_prefix.append(c)
        if rest:
            return None
        probe = _SelectPlan(True, 0, b"", b"", [], None, [], [])
        projection = None
        if stmt.items:
            for it in stmt.items:
                if it.agg_fn or not schema.has_column(it.column):
                    return None
            projection = [it.column for it in stmt.items]
        probe.projection = projection
        if not self._wire_eligible(handle, stmt, probe):
            return None
        projection = projection or [c.name for c in schema.columns]
        names = ([it.output_name for it in stmt.items] if stmt.items
                 else list(projection))
        return _PointStmtPlan(
            stmt, schema, handle,
            [(c, by_col[c.name]) for c in hash_cols],
            [(c, by_col[c.name]) for c in range_prefix],
            projection, names)

    def execute_wire_point_batch(self, items: list) -> list:
        """Batched serving of prepared point SELECTs — the CQL side of
        the native request-batch serving path (docs/serving-path.md).

        Each item is (stmt, params, page_size, paging_state), one per
        pipelined EXECUTE frame. Frames whose plan is a single-tablet
        wire-eligible read with no predicates, aggregates, LIMIT, or
        paging are grouped per tablet and served through ONE
        scan_wire_many batch per tablet; every other frame gets None in
        its slot and the caller runs the canonical execute(). Replies
        are byte-identical to the per-op path: the bounds and specs
        below are exactly what _plan_select/_run_rows would build for
        these statements (limit None, page budget None), served by the
        same page server. The params-independent planning is cached per
        prepared statement (_point_stmt_plan); a schema change drops the
        entry and replans.
        """
        out: list = [None] * len(items)
        groups: dict = {}
        cache = self._point_stmt_cache
        for i, (stmt, params, page_size, paging_state) in enumerate(items):
            if page_size is not None or paging_state:
                continue
            ckey = (id(stmt), self.keyspace)
            try:
                sp = cache.get(ckey, False)
                if sp is not False and sp is not None and \
                        sp.schema is not self.cluster.table(
                            self._qualify(stmt.table)).schema:
                    sp = False  # schema changed: replan
                if sp is False:
                    sp = cache[ckey] = self._point_stmt_plan(stmt)
                if sp is None:
                    continue
                self._params = params or []
                self._page_size = None
                self._paging_state = None
                self._wire_results = True
                self._enforce(stmt)
                eq = {c.name: self._coerce(c, rel.value)
                      for c, rel in sp.hash_rels}
                hash_code = compute_hash_code(sp.schema, eq)
                prefix = encode_doc_key_prefix(
                    hash_code,
                    [(eq[c.name], c.dtype) for c, _rel in sp.hash_rels],
                    [(self._coerce(c, rel.value), c.dtype)
                     for c, rel in sp.range_rels])
                tablet = self.cluster.tablet_for_hash(sp.handle, hash_code)
                if not hasattr(tablet, "scan_wire_many"):
                    continue
            except Exception as e:  # noqa: BLE001 — the execute()
                # fallback re-raises this error canonically per frame.
                count_swallowed("cql.batch_plan", e)
                continue
            bounds = _PointBounds(prefix, prefix_successor(prefix), [])
            # RemoteTablet handles are constructed per lookup: group by
            # the underlying tablet id so one RPC serves the tablet.
            key = getattr(getattr(tablet, "loc", None), "tablet_id",
                          None) or id(tablet)
            g = groups.get(key)
            if g is None:
                g = groups[key] = (tablet, [])
            g[1].append((i, sp.names, sp.projection, bounds))

        for tablet, frames in groups.values():
            read_ht = tablet.read_time().value
            specs = [ScanSpec(lower=plan.lower, upper=plan.upper,
                              read_ht=read_ht,
                              predicates=plan.predicates,
                              projection=projection, limit=None)
                     for _i, _names, projection, plan in frames]
            try:
                pages = tablet.scan_wire_many(specs)
            except Exception as e:  # noqa: BLE001 — whole group falls
                count_swallowed("cql.batch_serve", e)  # back to execute()
                continue
            for (i, names, projection, plan), spec, page in zip(
                    frames, specs, pages):
                parts = [page.data]
                nrows = page.nrows
                resume = page.resume
                read_ht = getattr(page, "read_ht", None) or spec.read_ht
                try:
                    while resume is not None:
                        # Continuation pages pin the batch's read time —
                        # the same snapshot rule as _run_rows paging.
                        res = tablet.scan_wire(ScanSpec(
                            lower=resume, upper=plan.upper,
                            read_ht=read_ht, predicates=plan.predicates,
                            projection=projection, limit=None))
                        parts.append(res.data)
                        nrows += res.nrows
                        resume = res.resume
                except Exception as e:  # noqa: BLE001 — rerun this
                    count_swallowed("cql.batch_continue", e)  # frame
                    continue
                rs = ResultSet(columns=names)
                rs.wire_data = b"".join(parts)
                rs.wire_rows = nrows
                out[i] = rs
        return out

    def _run_aggregate(self, handle: TableHandle, stmt: ast.Select, plan):
        """Fan the aggregate out per tablet, combine partials host-side —
        grouped or not (reference: per-tablet partial agg merged above the
        scan, src/yb/docdb/pgsql_operation.cc:473 + exec/eval_aggr.cc).
        avg lowers to sum+count so the combine stays exact."""
        lowered: list[AggSpec] = []
        shape = []  # ("plain", idx) | ("avg", sum_idx, count_idx)
        for a in plan.aggregates:
            if a.fn == "avg":
                shape.append(("avg", len(lowered), len(lowered) + 1))
                lowered.append(AggSpec("sum", a.column, expr=a.expr))
                lowered.append(AggSpec("count", a.column, expr=a.expr))
            else:
                shape.append(("plain", len(lowered), None))
                lowered.append(a)

        gb = plan.group_by
        ngb = len(gb)
        groups: dict[tuple, list[list]] = {}
        for tablet in self._target_tablets(handle, plan):
            spec = ScanSpec(lower=plan.lower, upper=plan.upper,
                            read_ht=tablet.read_time().value,
                            predicates=plan.predicates, aggregates=lowered,
                            group_by=gb or None)
            for row in tablet.scan(spec).rows:
                gkey = tuple(row[:ngb])
                groups.setdefault(gkey, []).append(list(row[ngb:]))
        if not groups and not gb:
            groups[()] = []

        out_rows = []
        for gkey in sorted(groups, key=lambda g: tuple(
                (v is None, v) for v in g)):
            partials = groups[gkey]
            row = list(gkey)
            for kind, i, j in shape:
                if kind == "avg":
                    s = self._combine([p[i] for p in partials], "sum")
                    n = self._combine([p[j] for p in partials], "count")
                    row.append(None if not n else s / n)
                else:
                    fn = lowered[i].fn
                    row.append(self._combine([p[i] for p in partials], fn))
            out_rows.append(tuple(row))
        # Column order follows the SELECT items; group values prepend in
        # GROUP BY order, then reorder to the projection if it differs.
        names = gb + [it.output_name for it in stmt.items if it.agg_fn]
        rs = ResultSet(columns=names, rows=out_rows)
        rs = self._project_grouped(stmt, gb, rs)
        return self._slice_limit(stmt, self._apply_order_by(stmt, rs))

    @staticmethod
    def _project_grouped(stmt, gb, rs: ResultSet) -> ResultSet:
        """Reorder (group cols + aggs) into the SELECT item order."""
        if not stmt.items:
            return rs
        want = [it.output_name for it in stmt.items]
        if want == rs.columns:
            return rs
        try:
            idxs = [rs.columns.index(
                it.output_name if it.agg_fn else it.column)
                for it in stmt.items]
        except ValueError:
            return rs
        return ResultSet(columns=want,
                         rows=[tuple(r[i] for i in idxs) for r in rs.rows])

    def _apply_order_by(self, stmt, rs: ResultSet) -> ResultSet:
        order = list(getattr(stmt, "order_by", []) or [])
        if not order:
            return rs
        for name, _d in order:
            if name not in rs.columns:
                raise InvalidArgument(f"ORDER BY column {name} not in output")
        for name, desc in reversed(order):
            i = rs.columns.index(name)
            rs.rows.sort(key=lambda r: (r[i] is None, r[i]), reverse=desc)
        return rs

    @staticmethod
    def _combine(vals, fn):
        vals = [v for v in vals if v is not None]
        if fn == "count":
            return sum(vals) if vals else 0
        if not vals:
            return None
        if fn == "sum":
            return sum(vals)
        return max(vals) if fn == "max" else min(vals)
