"""ClientCluster: the QLProcessor's Cluster seam over the distributed
client — how the CQL proxy reaches real tservers.

Reference analog: the CQL server's embedded YBClient/YBSession path
(src/yb/yql/cql/ql/exec/executor.cc building ops routed through
src/yb/client/batcher.cc). The processor only needs: create/drop/table
lookup, hash->tablet routing, and per-tablet objects exposing
write(rows) / scan(spec) / read_time() — RemoteTablet implements those
as tserver RPCs through the client's MetaCache + TabletInvoker."""

from __future__ import annotations

from yugabyte_db_tpu.client.client import YBClient
from yugabyte_db_tpu.models.partition import PartitionSchema
from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.storage import wire
from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec
from yugabyte_db_tpu.utils.hybrid_time import HybridClock, HybridTime
from yugabyte_db_tpu.utils.status import AlreadyPresent, NotFound


class RemoteTablet:
    """One tablet as seen through the client: the duck-type the
    QLProcessor drives (Tablet's read surface + write)."""

    def __init__(self, client: YBClient, table_name: str, loc):
        self.client = client
        self.table_name = table_name
        self.loc = loc

    def read_time(self) -> HybridTime:
        # The tserver picks its safe time when read_ht arrives as MAX
        # (tablet_server._h_ts_scan), exactly like a fresh scan.
        return HybridTime.max()

    def write(self, rows: list[RowVersion],
              if_not_exists: bool = False) -> None:
        from yugabyte_db_tpu.client.client import TabletOpFailed

        payload = {"rows": wire.encode_rows(rows)}
        if if_not_exists:
            payload["if_not_exists"] = True
        try:
            self.client.tablet_rpc(self.table_name, self.loc, "ts.write",
                                   payload)
        except TabletOpFailed as e:
            if getattr(e, "resp", {}).get("code") == "duplicate_key":
                raise AlreadyPresent(
                    "duplicate key value violates unique constraint") \
                    from None
            raise

    def scan(self, spec: ScanSpec) -> ScanResult:
        resp = self.client.tablet_rpc(
            self.table_name, self.loc, "ts.scan",
            {"spec": wire.encode_spec(spec)})
        res = wire.decode_result(resp)
        # Expose the server-chosen read time so paged scans pin one
        # snapshot (processor._run_rows reads it off the result).
        res.read_ht = resp.get("read_ht")
        return res

    def scan_wire(self, spec: ScanSpec, fmt: str = "cql"):
        """Scan returning serialized page bytes the proxy forwards
        verbatim (rows_data contract; tserver _h_ts_scan_wire)."""
        from yugabyte_db_tpu.storage.host_page import WirePage

        resp = self.client.tablet_rpc(
            self.table_name, self.loc, "ts.scan_wire",
            {"spec": wire.encode_spec(spec), "fmt": fmt})
        pg = WirePage(resp.get("columns"), resp["data"], resp["nrows"],
                      resp.get("resume"), 0)
        pg.read_ht = resp.get("read_ht")
        return pg

    def scan_wire_many(self, specs: list[ScanSpec], fmt: str = "cql"):
        """Batched wire scans in ONE ts.scan_wire_batch RPC — the read
        hop of the native request-batch serving path. Pages align with
        specs; the single server-chosen read time rides on each page."""
        from yugabyte_db_tpu.storage.host_page import WirePage

        resp = self.client.tablet_rpc(
            self.table_name, self.loc, "ts.scan_wire_batch",
            {"specs": [wire.encode_spec(s) for s in specs], "fmt": fmt})
        pages = []
        for p in resp["pages"]:
            pg = WirePage(p.get("columns"), p["data"], p["nrows"],
                          p.get("resume"), 0)
            pg.read_ht = resp.get("read_ht")
            pages.append(pg)
        return pages


class RemoteTable:
    def __init__(self, client: YBClient, name: str, schema: Schema,
                 indexes: list | None = None):
        self.client = client
        self.name = name
        self.schema = schema
        self.indexes = list(indexes or [])
        self.partition_schema = PartitionSchema(
            1, hash_partitioned=schema.num_hash > 0)  # routing via MetaCache

    @property
    def tablets(self) -> list[RemoteTablet]:
        locs = self.client.meta_cache.locations(self.name)
        return [RemoteTablet(self.client, self.name, loc)
                for loc in locs.tablets]


class ClientCluster:
    """Cluster seam over YBClient (the distributed deployment)."""

    def __init__(self, client: YBClient, num_tablets: int = 4,
                 replication_factor: int = 3, engine: str = "cpu"):
        self.client = client
        self.num_tablets = num_tablets
        self.replication_factor = replication_factor
        self.engine = engine
        # TTL expiry hybrid times are computed proxy-side from this clock
        # (same shape as LocalCluster's shared clock).
        self.clock = HybridClock()
        self._tables: dict[str, RemoteTable] = {}
        self._auth_cache = None
        self._auth_cache_at = 0.0

    def auth_store(self):
        """Short-TTL mirror of the master's role store (the client-side
        caching the reference's CQL auth does against system_auth)."""
        import time as _t

        from yugabyte_db_tpu.auth import RoleStore

        now = _t.monotonic()
        if self._auth_cache is None or now - self._auth_cache_at > 1.0:
            resp = self.client.master_rpc("master.get_auth", {})
            self._auth_cache = RoleStore.from_dict(resp["auth"])
            self._auth_cache_at = now
        return self._auth_cache

    def auth_op(self, op: dict) -> None:
        resp = self.client.master_rpc("master.auth_op", {"auth": op})
        if resp.get("code") != "ok":
            from yugabyte_db_tpu.utils.status import InvalidArgument

            raise InvalidArgument(resp.get("message", "auth op failed"))
        self._auth_cache = None

    @property
    def tables(self) -> dict:
        """Existing table names (the processor's existence checks)."""
        return {t["name"]: t for t in self.client.list_tables()}

    def create_table(self, name: str, schema: Schema,
                     num_tablets: int | None = None) -> RemoteTable:
        try:
            self.client.create_table(
                name, list(schema.columns),
                num_tablets=num_tablets or self.num_tablets,
                replication_factor=self.replication_factor,
                engine=self.engine)
        except Exception as e:  # noqa: BLE001
            if "already_present" in str(e):
                raise AlreadyPresent(f"table {name} exists") from e
            raise
        t = RemoteTable(self.client, name, schema)
        self._tables[name] = t
        return t

    def drop_table(self, name: str) -> None:
        self._tables.pop(name, None)
        resp = self.client.master_rpc("master.delete_table",
                                      {"name": name})
        if resp.get("code") == "not_found":
            raise NotFound(f"table {name} not found")
        if resp.get("code") != "ok":
            raise RuntimeError(f"drop_table {name}: {resp}")
        self.client.meta_cache.invalidate(name)

    def table(self, name: str) -> RemoteTable:
        t = self._tables.get(name)
        if t is None:
            resp = self.client.master_rpc("master.get_table",
                                          {"name": name})
            if resp.get("code") != "ok":
                raise NotFound(f"table {name} not found")
            t = RemoteTable(self.client, name,
                            Schema.from_dict(resp["schema"]),
                            resp.get("indexes"))
            self._tables[name] = t
        return t

    def alter_table(self, handle: RemoteTable, new_schema: Schema) -> None:
        self.client.alter_table(handle.name, new_schema.to_dict())
        handle.schema = new_schema

    def create_index(self, base: RemoteTable, name: str,
                     columns, include=()) -> str:
        if isinstance(columns, str):
            columns = [columns]
        itable = self.client.create_index(base.name, columns, name,
                                          include)
        base.indexes.append({"name": name, "column": columns[0],
                             "columns": list(columns),
                             "include": list(include),
                             "index_table": itable})
        return itable

    # -- user-defined types -------------------------------------------------
    def create_type(self, name: str, fields: list) -> None:
        from yugabyte_db_tpu.utils.status import InvalidArgument

        resp = self.client.master_rpc("master.type_op", {
            "action": "create", "name": name,
            "fields": [list(f) for f in fields]})
        if resp.get("code") not in ("ok", "already_present"):
            raise InvalidArgument(f"create type {name}: {resp}")
        self._types_cache = None

    def drop_type(self, name: str) -> None:
        from yugabyte_db_tpu.utils.status import InvalidArgument

        resp = self.client.master_rpc("master.type_op", {
            "action": "drop", "name": name})
        if resp.get("code") != "ok":
            raise InvalidArgument(f"drop type {name}: {resp}")
        self._types_cache = None

    def get_type(self, name: str):
        # The fetched registry is authoritative until a local type op
        # invalidates it — unknown names don't refetch per lookup.
        cache = getattr(self, "_types_cache", None)
        if cache is None:
            cache = self.list_types()
        return cache.get(name)

    def list_types(self) -> dict:
        resp = self.client.master_rpc("master.list_types", {})
        cache = self._types_cache = {
            n: [tuple(f) for f in fs]
            for n, fs in resp.get("types", {}).items()}
        return cache

    # -- keyspaces (shared registry through the master catalog) --------------
    def create_keyspace(self, name: str) -> None:
        from yugabyte_db_tpu.utils.status import AlreadyPresent

        resp = self._misc_op("create_keyspace", {"name": name})
        if resp.get("code") == "already_present":
            raise AlreadyPresent(f"keyspace {name} exists")
        if resp.get("code") != "ok":
            raise RuntimeError(f"create keyspace {name}: {resp}")

    def drop_keyspace(self, name: str) -> None:
        from yugabyte_db_tpu.utils.status import NotFound

        resp = self._misc_op("drop_keyspace", {"name": name})
        if resp.get("code") == "not_found":
            raise NotFound(f"keyspace {name} not found")

    def list_keyspaces(self) -> set:
        resp = self._misc_op("list_keyspaces", {})
        return set(resp.get("keyspaces", ()))

    # -- views / sequences --------------------------------------------------
    def _misc_op(self, action: str, payload: dict) -> dict:
        resp = self.client.master_rpc("master.misc_op",
                                      dict(payload, action=action))
        return resp

    def create_view(self, name: str, query_sql: str,
                    replace: bool = False) -> None:
        from yugabyte_db_tpu.utils.status import AlreadyPresent

        resp = self._misc_op("create_view", {
            "name": name, "query": query_sql, "replace": replace})
        if resp.get("code") == "already_present":
            raise AlreadyPresent(f"view {name} exists")
        if resp.get("code") != "ok":
            raise RuntimeError(f"create view {name}: {resp}")

    def drop_view(self, name: str) -> None:
        from yugabyte_db_tpu.utils.status import NotFound

        resp = self._misc_op("drop_view", {"name": name})
        if resp.get("code") == "not_found":
            raise NotFound(f"view {name} not found")

    def get_view(self, name: str):
        resp = self._misc_op("get_view", {"name": name})
        return resp.get("query") if resp.get("code") == "ok" else None

    def create_sequence(self, name: str) -> None:
        from yugabyte_db_tpu.utils.status import AlreadyPresent

        resp = self._misc_op("create_sequence", {"name": name})
        if resp.get("code") == "already_present":
            raise AlreadyPresent(f"sequence {name} exists")
        if resp.get("code") != "ok":
            raise RuntimeError(f"create sequence {name}: {resp}")

    def drop_sequence(self, name: str) -> None:
        from yugabyte_db_tpu.utils.status import NotFound

        resp = self._misc_op("drop_sequence", {"name": name})
        if resp.get("code") == "not_found":
            raise NotFound(f"sequence {name} not found")

    def sequence_next(self, name: str, n: int = 1) -> int:
        from yugabyte_db_tpu.utils.status import NotFound

        resp = self._misc_op("sequence_next", {"name": name, "n": n})
        if resp.get("code") == "not_found":
            raise NotFound(f"sequence {name} not found")
        if resp.get("code") != "ok":
            raise RuntimeError(f"nextval {name}: {resp}")
        return resp["base"]

    def drop_index(self, base: RemoteTable, name: str) -> None:
        idx = next(i for i in base.indexes if i["name"] == name)
        resp = self.client.master_rpc("master.drop_index", {
            "table": base.name, "name": name})
        if resp.get("code") != "ok":
            raise NotFound(f"index {name}: {resp}")
        base.indexes.remove(idx)

    # On the distributed path the base tablet's LEADER maintains indexes
    # in its write handler (tablet_server._maintain_indexes) — the
    # reference's placement — so the processor-side hook is absent.
    maintain_indexes = None

    def tablet_for_hash(self, handle: RemoteTable,
                        hash_code: int) -> RemoteTablet:
        loc = self.client.meta_cache.lookup_by_hash(handle.name, hash_code)
        return RemoteTablet(self.client, handle.name, loc)

    def transaction_manager(self):
        """The shared TransactionManager over this cluster's client
        (reference: the TransactionManager the SQL layer's PgTxnManager
        drives, pg_txn_manager.cc) — distributed seam only."""
        if getattr(self, "_txn_manager", None) is None:
            from yugabyte_db_tpu.client.transaction import TransactionManager

            self._txn_manager = TransactionManager(self.client)
            self._txn_manager.ensure_status_table()
        return self._txn_manager

    def open_yb_table(self, name: str):
        """A client YBTable handle (the transaction API's table type)."""
        return self.client.open_table(name)

    def close(self) -> None:
        self._tables.clear()
