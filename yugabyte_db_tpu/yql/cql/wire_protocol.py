"""CQL native binary protocol v4: frame codec + value (de)serialization.

Reference analog: src/yb/yql/cql/cqlserver/cql_message.{h,cc} — the frame
header (version/flags/stream/opcode/length), the request opcodes
(STARTUP/OPTIONS/QUERY/PREPARE/EXECUTE), and the RESULT payload kinds
(Void/Rows/SetKeyspace/Prepared/SchemaChange). Implements the subset a
standard v4 driver exercises for DDL + DML with prepared statements and
result paging; no compression, no auth, no events.
"""

from __future__ import annotations

import struct

from yugabyte_db_tpu.models.datatypes import DataType

VERSION_REQ = 0x04
VERSION_RESP = 0x84
HEADER = struct.Struct(">BBhBi")   # version, flags, stream, opcode, length

# Opcodes (protocol v4 §2.4)
OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_OPTIONS = 0x05
OP_SUPPORTED = 0x06
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_PREPARE = 0x09
OP_EXECUTE = 0x0A
OP_REGISTER = 0x0B
OP_EVENT = 0x0C
OP_AUTH_CHALLENGE = 0x0E
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

# RESULT kinds (§4.2.5)
RESULT_VOID = 0x0001
RESULT_ROWS = 0x0002
RESULT_SET_KEYSPACE = 0x0003
RESULT_PREPARED = 0x0004
RESULT_SCHEMA_CHANGE = 0x0005

# Error codes (§9)
ERR_SERVER = 0x0000
ERR_PROTOCOL = 0x000A
ERR_BAD_CREDENTIALS = 0x0100
ERR_UNAUTHORIZED = 0x2100
ERR_INVALID = 0x2200
ERR_ALREADY_EXISTS = 0x2400
ERR_UNPREPARED = 0x2500

# Data type option ids (§6)
T_BIGINT = 0x0002
T_BLOB = 0x0003
T_BOOLEAN = 0x0004
T_COUNTER = 0x0005
T_DECIMAL = 0x0006
T_DOUBLE = 0x0007
T_FLOAT = 0x0008
T_INT = 0x0009
T_TIMESTAMP = 0x000B
T_UUID = 0x000C
T_VARCHAR = 0x000D
T_VARINT = 0x000E
T_TIMEUUID = 0x000F
T_INET = 0x0010
T_DATE = 0x0011
T_TIME = 0x0012
T_SMALLINT = 0x0013
T_TINYINT = 0x0014

_DT_TO_CQL = {
    DataType.INT8: T_TINYINT,
    DataType.INT16: T_SMALLINT,
    DataType.INT32: T_INT,
    DataType.INT64: T_BIGINT,
    DataType.FLOAT: T_FLOAT,
    DataType.DOUBLE: T_DOUBLE,
    DataType.BOOL: T_BOOLEAN,
    DataType.STRING: T_VARCHAR,
    DataType.BINARY: T_BLOB,
    DataType.TIMESTAMP: T_TIMESTAMP,
    DataType.COUNTER: T_COUNTER,
    DataType.DECIMAL: T_DECIMAL,
    DataType.VARINT: T_VARINT,
    DataType.UUID: T_UUID,
    DataType.TIMEUUID: T_TIMEUUID,
    DataType.INET: T_INET,
    DataType.DATE: T_DATE,
    DataType.TIME: T_TIME,
    # TUPLE/FROZEN ship as blobs (self-describing element payloads);
    # full 0x0031 tuple metadata would need per-element type plumbing.
}

_INT_WIDTH = {T_TINYINT: 1, T_SMALLINT: 2, T_INT: 4, T_BIGINT: 8,
              T_COUNTER: 8, T_TIMESTAMP: 8}


def cql_type_id(dt: DataType) -> int:
    return _DT_TO_CQL.get(dt, T_BLOB)


# -- primitive readers/writers (§3) -----------------------------------------

class Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise ValueError("truncated CQL frame body")
        self.pos += n
        return b

    def byte(self) -> int:
        return self._take(1)[0]

    def short(self) -> int:
        return struct.unpack(">H", self._take(2))[0]

    def int32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def long_string(self) -> str:
        n = self.int32()
        return self._take(n).decode("utf-8")

    def string(self) -> str:
        return self._take(self.short()).decode("utf-8")

    def bytes_(self) -> bytes | None:
        n = self.int32()
        if n < 0:
            return None
        return self._take(n)

    def short_bytes(self) -> bytes:
        return self._take(self.short())

    def string_map(self) -> dict:
        return {self.string(): self.string() for _ in range(self.short())}


class Writer:
    def __init__(self):
        self.parts: list[bytes] = []

    def byte(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">B", v))
        return self

    def short(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">H", v))
        return self

    def int32(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">i", v))
        return self

    def string(self, s: str) -> "Writer":
        b = s.encode("utf-8")
        self.parts.append(struct.pack(">H", len(b)) + b)
        return self

    def long_string(self, s: str) -> "Writer":
        b = s.encode("utf-8")
        self.parts.append(struct.pack(">i", len(b)) + b)
        return self

    def bytes_(self, b: bytes | None) -> "Writer":
        if b is None:
            self.parts.append(struct.pack(">i", -1))
        else:
            self.parts.append(struct.pack(">i", len(b)) + b)
        return self

    def short_bytes(self, b: bytes) -> "Writer":
        self.parts.append(struct.pack(">H", len(b)) + b)
        return self

    def string_list(self, items) -> "Writer":
        self.short(len(items))
        for s in items:
            self.string(s)
        return self

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


def frame(opcode: int, stream: int, body: bytes) -> bytes:
    return HEADER.pack(VERSION_RESP, 0, stream, opcode, len(body)) + body


def error_frame(stream: int, code: int, message: str) -> bytes:
    w = Writer().int32(code).string(message)
    return frame(OP_ERROR, stream, w.getvalue())


# -- typed values (§6) -------------------------------------------------------

def encode_value(dt: DataType, v) -> bytes | None:
    """Python value -> CQL serialized bytes (None -> null). The cell
    format definition lives in models.wirefmt (shared with the native
    wire page server)."""
    from yugabyte_db_tpu.models.wirefmt import cql_cell

    return cql_cell(dt, v)


def decode_value(dt: DataType, b: bytes | None):
    """CQL serialized bytes -> Python value (None stays None)."""
    if b is None:
        return None
    from yugabyte_db_tpu.utils.status import InvalidArgument

    tid = cql_type_id(dt)
    if tid in _INT_WIDTH:
        # Fixed-width cells must be exactly their width (§6): reject a
        # mis-typed bind instead of reinterpreting its bytes.
        if len(b) != _INT_WIDTH[tid]:
            raise InvalidArgument(
                f"expected {_INT_WIDTH[tid]} bytes for type {dt.name}, "
                f"got {len(b)}")
        return int.from_bytes(b, "big", signed=True)
    if tid == T_BOOLEAN:
        if len(b) != 1:
            raise InvalidArgument(
                f"expected 1 byte for BOOLEAN, got {len(b)}")
        return b != b"\x00"
    if tid == T_DOUBLE:
        if len(b) != 8:
            raise InvalidArgument(
                f"expected 8 bytes for DOUBLE, got {len(b)}")
        return struct.unpack(">d", b)[0]
    if tid == T_FLOAT:
        if len(b) != 4:
            raise InvalidArgument(
                f"expected 4 bytes for FLOAT, got {len(b)}")
        return struct.unpack(">f", b)[0]
    if tid == T_VARCHAR:
        return b.decode("utf-8")
    if tid == T_VARINT:
        return int.from_bytes(b, "big", signed=True)
    if tid == T_DECIMAL:
        import decimal

        scale = struct.unpack(">i", b[:4])[0]
        unscaled = int.from_bytes(b[4:], "big", signed=True)
        return decimal.Decimal(unscaled).scaleb(-scale)
    if tid in (T_UUID, T_TIMEUUID):
        import uuid as _uuid

        from yugabyte_db_tpu.models.datatypes import TimeUuid

        u = _uuid.UUID(bytes=b)
        return TimeUuid(u) if tid == T_TIMEUUID else u
    if tid == T_INET:
        from yugabyte_db_tpu.models.datatypes import Inet

        return Inet(b)
    if tid == T_DATE:
        import datetime

        days = struct.unpack(">I", b)[0] - (1 << 31)
        return datetime.date(1970, 1, 1) + datetime.timedelta(days=days)
    if tid == T_TIME:
        import datetime

        ns = struct.unpack(">q", b)[0]
        us, _ = divmod(ns, 1000)
        s, us = divmod(us, 10**6)
        m, s = divmod(s, 60)
        h, m = divmod(m, 60)
        return datetime.time(h, m, s, us)
    return b


# -- RESULT payloads ---------------------------------------------------------

def rows_result(stream: int, keyspace: str, table: str,
                columns: list[tuple[str, DataType]], rows: list[tuple],
                paging_state: bytes | None = None) -> bytes:
    w = Writer().int32(RESULT_ROWS)
    flags = 0x0001  # global_tables_spec
    if paging_state is not None:
        flags |= 0x0002  # has_more_pages
    w.int32(flags).int32(len(columns))
    if paging_state is not None:
        w.bytes_(paging_state)
    w.string(keyspace).string(table)
    for name, dt in columns:
        w.string(name).short(cql_type_id(dt))
    w.int32(len(rows))
    for row in rows:
        for (name, dt), v in zip(columns, row):
            w.bytes_(encode_value(dt, v))
    return frame(OP_RESULT, stream, w.getvalue())


def rows_result_wire(stream: int, keyspace: str, table: str,
                     columns: list[tuple[str, DataType]], nrows: int,
                     rows_data: bytes,
                     paging_state: bytes | None = None) -> bytes:
    """Rows RESULT from pre-serialized cell bytes (the rows_data
    contract: the storage layer emitted the cells; this adds only the
    metadata header). Byte-identical to rows_result over the same
    rows."""
    w = Writer().int32(RESULT_ROWS)
    flags = 0x0001  # global_tables_spec
    if paging_state is not None:
        flags |= 0x0002
    w.int32(flags).int32(len(columns))
    if paging_state is not None:
        w.bytes_(paging_state)
    w.string(keyspace).string(table)
    for name, dt in columns:
        w.string(name).short(cql_type_id(dt))
    w.int32(nrows)
    body = w.getvalue() + rows_data
    return frame(OP_RESULT, stream, body)


def void_result(stream: int) -> bytes:
    return frame(OP_RESULT, stream, Writer().int32(RESULT_VOID).getvalue())


def set_keyspace_result(stream: int, ks: str) -> bytes:
    w = Writer().int32(RESULT_SET_KEYSPACE).string(ks)
    return frame(OP_RESULT, stream, w.getvalue())


def schema_change_result(stream: int, change: str, target: str,
                         ks: str, name: str = "") -> bytes:
    w = Writer().int32(RESULT_SCHEMA_CHANGE)
    w.string(change).string(target).string(ks)
    if target != "KEYSPACE":
        w.string(name)
    return frame(OP_RESULT, stream, w.getvalue())


def prepared_result(stream: int, stmt_id: bytes, keyspace: str, table: str,
                    bind_cols: list[tuple[str, DataType]]) -> bytes:
    w = Writer().int32(RESULT_PREPARED).short_bytes(stmt_id)
    # bind metadata: global_tables_spec, no pk indices (v4 sends pk count)
    w.int32(0x0001).int32(len(bind_cols)).int32(0)  # flags, cols, pk count
    w.string(keyspace or "default").string(table or "")
    for name, dt in bind_cols:
        w.string(name).short(cql_type_id(dt))
    # result metadata: no_metadata flag (client uses the per-query one)
    w.int32(0x0004).int32(0)
    return frame(OP_RESULT, stream, w.getvalue())
