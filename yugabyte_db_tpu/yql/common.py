"""Helpers shared by the YQL frontends (CQL processor, SQL executor).

One implementation of value coercion and key->tablet routing so the two
frontends cannot drift (they lower to the same DocDB write/read ops;
reference: the shared QLValue coercion + partition routing both the CQL
executor and pggate use, src/yb/common/ql_value.h, partition.h:204).
"""

from __future__ import annotations

from decimal import InvalidOperation as decimal_InvalidOperation

from yugabyte_db_tpu.models.datatypes import DataType, python_value_matches
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnSchema
from yugabyte_db_tpu.utils.status import InvalidArgument


def coerce_udt(col: ColumnSchema, value, fields):
    """Coerce a UDT literal ({field: value} map) against the type's
    declared fields: unknown fields rejected, missing fields NULL, each
    field coerced to its declared type; normalized to declared field
    order so replicas/serializers agree."""
    if value is None:
        return None
    if not isinstance(value, dict):
        raise InvalidArgument(
            f"bad value {value!r} for {col.name} (UDT {col.udt})")
    declared = {f[0] for f in fields}
    for k in value:
        if k not in declared:
            raise InvalidArgument(
                f"unknown field {k!r} for UDT {col.udt}")
    out = {}
    for fname, fdtype in fields:
        v = value.get(fname)
        if v is None:
            out[fname] = None
            continue
        fcol = ColumnSchema(f"{col.name}.{fname}", DataType(fdtype))
        out[fname] = coerce_value(fcol, v)
    return out


def coerce_value(col: ColumnSchema, value):
    """Coerce a resolved (marker-free) literal to the column's type."""
    if value is None:
        return None
    dt = col.dtype
    if dt == DataType.JSONB and isinstance(value, str):
        import json

        try:
            value = json.loads(value)
        except ValueError as e:
            raise InvalidArgument(f"invalid json for {col.name}: {e}")
    if dt.is_integer and isinstance(value, bool):
        raise InvalidArgument(f"bad value for {col.name}")
    if dt in (DataType.DOUBLE, DataType.FLOAT) and \
            isinstance(value, int) and not isinstance(value, bool):
        value = float(value)
    if dt == DataType.BINARY and isinstance(value, str):
        value = value.encode("utf-8")
    # Extended scalar surface: accept the natural literal spellings and
    # normalize to the rich storage value (reference type parsing:
    # src/yb/util/decimal.cc, date_time.cc, net/inetaddress.cc).
    try:
        if dt == DataType.DECIMAL:
            import decimal

            if isinstance(value, (int, float, str)):
                value = decimal.Decimal(str(value))
            if isinstance(value, decimal.Decimal) and \
                    (value.is_nan() or value.is_infinite()):
                raise InvalidArgument(
                    f"non-finite DECIMAL for {col.name}")
        elif dt == DataType.VARINT and isinstance(value, str):
            value = int(value)
        elif dt in (DataType.UUID, DataType.TIMEUUID) and \
                isinstance(value, str):
            import uuid as _uuid

            from yugabyte_db_tpu.models.datatypes import TimeUuid

            u = _uuid.UUID(value)
            value = TimeUuid(u) if dt == DataType.TIMEUUID else u
        elif dt == DataType.TIMEUUID:
            import uuid as _uuid

            from yugabyte_db_tpu.models.datatypes import TimeUuid

            if isinstance(value, _uuid.UUID):
                value = TimeUuid(value)
        elif dt == DataType.INET and isinstance(value, (str, bytes)):
            from yugabyte_db_tpu.models.datatypes import Inet

            value = Inet(value)
        elif dt == DataType.DATE and isinstance(value, str):
            import datetime

            value = datetime.date.fromisoformat(value)
        elif dt == DataType.TIME and isinstance(value, str):
            import datetime

            value = datetime.time.fromisoformat(value)
        elif dt == DataType.TUPLE and isinstance(value, (list, tuple)):
            value = tuple(value)
        elif dt == DataType.FROZEN and isinstance(value, (set, frozenset)):
            value = sorted(value, key=lambda v: (type(v).__name__, v))
        elif dt == DataType.FROZEN and isinstance(value, dict):
            value = dict(sorted(value.items(),
                                key=lambda kv: (type(kv[0]).__name__,
                                                kv[0])))
    except (ValueError, TypeError, decimal_InvalidOperation) as e:
        raise InvalidArgument(
            f"bad {dt.name} literal for {col.name}: {e}") from None
    if not python_value_matches(dt, value):
        raise InvalidArgument(
            f"bad value {value!r} for {col.name} ({dt.name})")
    # Normalize containers so every replica and every client serializes
    # them identically (SET: sorted unique list; MAP: sorted key order).
    if dt == DataType.SET:
        value = sorted(set(value))
    elif dt == DataType.MAP:
        value = dict(sorted(value.items()))
    elif dt == DataType.JSONB:
        value = _normalize_json(value)
    return value


def _normalize_json(v):
    """Recursively sort object keys so identical JSON values serialize
    identically on every replica (reference: jsonb.cc's sorted key
    layout)."""
    if isinstance(v, dict):
        return {k: _normalize_json(v[k]) for k in sorted(v)}
    if isinstance(v, list):
        return [_normalize_json(x) for x in v]
    return v


def evolve_schema(handle, action: str, column: str | None,
                  dtype=None, new_name: str | None = None):
    """Compute the next schema version for an ALTER TABLE action (shared
    by both frontends): ADD -> NULL for existing rows, DROP retires the
    id (never reused) and is refused while the column is indexed,
    RENAME touches no data."""
    schema = handle.schema
    try:
        if action == "add":
            return schema.with_added_column(column, dtype)
        if action == "drop":
            from yugabyte_db_tpu.index import normalize_index

            for i in getattr(handle, "indexes", []):
                ni = normalize_index(i)
                if column in ni["columns"] or column in ni["include"]:
                    raise InvalidArgument(
                        f"column {column} is indexed; drop the index first")
            return schema.with_dropped_column(column)
        return schema.with_renamed_column(column, new_name)
    except (ValueError, KeyError) as e:
        raise InvalidArgument(str(e)) from None


def key_and_tablet(cluster, handle, key_values: dict):
    """Encode the primary key and route to the owning tablet (hash
    tables route by hash code; range tables have a single tablet)."""
    schema = handle.schema
    hash_code = compute_hash_code(schema, key_values)
    key = schema.encode_primary_key(key_values, hash_code)
    tablet = (cluster.tablet_for_hash(handle, hash_code)
              if schema.num_hash else handle.tablets[0])
    return key, tablet
