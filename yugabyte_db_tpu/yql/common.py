"""Helpers shared by the YQL frontends (CQL processor, SQL executor).

One implementation of value coercion and key->tablet routing so the two
frontends cannot drift (they lower to the same DocDB write/read ops;
reference: the shared QLValue coercion + partition routing both the CQL
executor and pggate use, src/yb/common/ql_value.h, partition.h:204).
"""

from __future__ import annotations

from yugabyte_db_tpu.models.datatypes import DataType, python_value_matches
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnSchema
from yugabyte_db_tpu.utils.status import InvalidArgument


def coerce_value(col: ColumnSchema, value):
    """Coerce a resolved (marker-free) literal to the column's type."""
    if value is None:
        return None
    dt = col.dtype
    if dt.is_integer and isinstance(value, bool):
        raise InvalidArgument(f"bad value for {col.name}")
    if dt in (DataType.DOUBLE, DataType.FLOAT) and \
            isinstance(value, int) and not isinstance(value, bool):
        value = float(value)
    if dt == DataType.BINARY and isinstance(value, str):
        value = value.encode("utf-8")
    if not python_value_matches(dt, value):
        raise InvalidArgument(
            f"bad value {value!r} for {col.name} ({dt.name})")
    return value


def key_and_tablet(cluster, handle, key_values: dict):
    """Encode the primary key and route to the owning tablet (hash
    tables route by hash code; range tables have a single tablet)."""
    schema = handle.schema
    hash_code = compute_hash_code(schema, key_values)
    key = schema.encode_primary_key(key_values, hash_code)
    tablet = (cluster.tablet_for_hash(handle, hash_code)
              if schema.num_hash else handle.tablets[0])
    return key, tablet
