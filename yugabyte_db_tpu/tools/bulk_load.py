"""yb-bulk-load: high-throughput offline row import.

Reference analog: src/yb/tools/yb-bulk_load.cc +
yb-generate_partitions — rows are partitioned by hash code into
per-tablet groups client-side, then shipped as large per-tablet write
batches in parallel (the ImportData flow without the offline SSTable
intermediate: the engines build their columnar runs from the same
entries either way).

  python -m yugabyte_db_tpu.tools.bulk_load --master 127.0.0.1:7100 \
      --table kv data.csv
"""

from __future__ import annotations

import argparse
import csv
import sys
import time

from yugabyte_db_tpu.client.client import YBClient
from yugabyte_db_tpu.client.session import YBSession
from yugabyte_db_tpu.models.datatypes import DataType


def _coerce_csv(dt: DataType, text: str):
    if text is None or text == "":  # short row (restval) or empty cell
        return None
    if dt.is_integer:
        return int(text)
    if dt in (DataType.FLOAT, DataType.DOUBLE):
        return float(text)
    if dt == DataType.BOOL:
        return text.lower() in ("1", "t", "true", "yes")
    if dt == DataType.BINARY:
        return bytes.fromhex(text)
    if dt == DataType.JSONB:
        import json

        return json.loads(text)
    return text


def load_csv(client: YBClient, table_name: str, csv_path: str,
             batch: int = 512, progress=None) -> int:
    """Stream a CSV (header row = column names) into a table. Returns
    rows written. The session batcher groups per tablet and issues the
    per-tablet writes in parallel."""
    table = client.open_table(table_name)
    cols = {c.name: c for c in table.schema.columns}
    session = YBSession(client)
    n = 0
    with open(csv_path, newline="") as f:
        reader = csv.DictReader(f)
        missing = [c for c in (reader.fieldnames or []) if c not in cols]
        if missing:
            raise SystemExit(f"unknown columns in CSV header: {missing}")
        for lineno, rec in enumerate(reader, start=2):
            if None in rec:  # more fields than the header declares
                raise SystemExit(
                    f"{csv_path}:{lineno}: row has more fields than the "
                    f"header")
            session.insert(table, {
                name: _coerce_csv(cols[name].dtype, text)
                for name, text in rec.items()})
            n += 1
            if session.pending_ops >= batch:
                session.flush()
                if progress:
                    progress(n)
    session.flush()
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="yb-bulk-load")
    ap.add_argument("--master", required=True,
                    help="comma-separated master host:port")
    ap.add_argument("--table", required=True)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("csv", help="CSV file with a header row")
    args = ap.parse_args(argv)
    client = YBClient.connect(args.master)
    t0 = time.perf_counter()
    n = load_csv(client, args.table, args.csv, args.batch,
                 progress=lambda k: print(f"\r{k} rows...", end="",
                                          file=sys.stderr))
    dt = time.perf_counter() - t0
    print(f"\nloaded {n} rows in {dt:.1f}s ({n / dt:.0f} rows/s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
