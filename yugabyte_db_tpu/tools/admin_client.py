"""AdminClient: the cluster-administration RPC surface behind yb-admin.

Reference analog: src/yb/tools/yb-admin_client.cc (ClusterAdminClient) —
list tables/tablets/tservers, change a tablet's Raft config, leader
stepdown, flush/compact, delete table — over the same master/tserver
RPCs the regular client uses.
"""

from __future__ import annotations

import time

from yugabyte_db_tpu.consensus.transport import TransportError


class AdminError(Exception):
    pass


class AdminClient:
    """Thin admin wrapper over a cluster Transport.

    Works over both the in-process LocalTransport (tests) and
    SocketTransport (real daemons); ``connect()`` bootstraps the latter
    from a master address the way yb-admin's -master_addresses does.
    """

    def __init__(self, transport, master_uuids: list[str]):
        self.transport = transport
        self.master_uuids = list(master_uuids)

    @classmethod
    def connect(cls, master_addrs: str) -> "AdminClient":
        """Bootstrap over TCP from comma-separated master ``host:port``
        addresses (yb-admin's -master_addresses). Pass ALL masters of a
        multi-master cluster so the leader is reachable whichever node
        holds it; tserver addresses are learned from the master's
        registry."""
        from yugabyte_db_tpu.rpc import SocketTransport

        transport = SocketTransport()
        uuids = []
        for addr in master_addrs.split(","):
            addr = addr.strip()
            if not addr:
                continue
            if ":" not in addr:
                raise AdminError(f"bad master address {addr!r} "
                                 "(want host:port)")
            host, port = addr.rsplit(":", 1)
            boot_uuid = f"master@{addr}"
            transport.set_address(boot_uuid, host, int(port))
            uuids.append(boot_uuid)
        if not uuids:
            raise AdminError("no master addresses given")
        c = cls(transport, uuids)
        c.refresh_addresses()
        return c

    def refresh_addresses(self) -> None:
        """Learn tserver uuid -> address mappings (socket mode)."""
        if not hasattr(self.transport, "set_address"):
            return
        for d in self.list_tservers():
            addr = d.get("addr")
            if isinstance(addr, (list, tuple)) and len(addr) == 2:
                self.transport.set_address(d["uuid"], addr[0], int(addr[1]))

    # -- master RPCs ---------------------------------------------------------
    def master_rpc(self, method: str, payload: dict | None = None,
                   timeout_s: float = 10.0) -> dict:
        """Try masters until one answers as leader (yb-admin's leader
        master discovery loop)."""
        deadline = time.monotonic() + timeout_s
        last = None
        while time.monotonic() < deadline:
            for m in list(self.master_uuids):
                try:
                    resp = self.transport.send(m, method, payload or {},
                                               timeout=2.0)
                except TransportError as e:
                    last = str(e)
                    continue
                if resp.get("code") == "not_leader":
                    hint = resp.get("leader_hint")
                    if hint and hint in self.master_uuids:
                        self.master_uuids.remove(hint)
                        self.master_uuids.insert(0, hint)
                    last = "not_leader"
                    continue
                return resp
            time.sleep(0.1)
        raise AdminError(f"no leader master answered {method}: {last}")

    def list_tables(self) -> list[dict]:
        return self.master_rpc("master.list_tables")["tables"]

    def list_tservers(self) -> list[dict]:
        return self.master_rpc("master.list_tservers")["tservers"]

    def table_locations(self, table: str) -> list[dict]:
        resp = self.master_rpc("master.get_table_locations",
                               {"name": table})
        if resp.get("code") != "ok":
            raise AdminError(f"table {table}: {resp.get('code')}")
        # Socket mode: keep the address book current with the replica
        # addresses the master reports (covers tservers that joined after
        # connect()).
        if hasattr(self.transport, "set_address"):
            for t in resp["tablets"]:
                for r in t["replicas"]:
                    addr = r.get("addr")
                    if isinstance(addr, (list, tuple)) and len(addr) == 2:
                        self.transport.set_address(r["uuid"], addr[0],
                                                   int(addr[1]))
        return resp["tablets"]

    def delete_table(self, table: str) -> None:
        resp = self.master_rpc("master.delete_table", {"name": table})
        if resp.get("code") != "ok":
            raise AdminError(f"delete {table}: {resp.get('code')}")

    def split_tablet(self, table: str, tablet_id: str,
                     timeout_s: float = 30.0) -> dict:
        """Manually split one tablet at its median resident key
        (yb-admin split_tablet): the master drives the whole seal →
        fork → seed → commit protocol and answers with the child
        tablet ids."""
        resp = self.master_rpc("master.split_tablet",
                               {"table": table, "tablet_id": tablet_id,
                                "timeout": timeout_s},
                               timeout_s=timeout_s + 5.0)
        if resp.get("code") != "ok":
            raise AdminError(
                f"split_tablet {tablet_id}: "
                f"{resp.get('message', resp.get('code'))}")
        return resp

    def rebalance(self) -> dict:
        """Run one forced leader-balancing pass on the master
        (yb-admin's rebalance trigger); returns the move made (if any)
        plus the per-tserver leader counts."""
        resp = self.master_rpc("master.rebalance", {})
        if resp.get("code") != "ok":
            raise AdminError(
                f"rebalance: {resp.get('message', resp.get('code'))}")
        return resp

    def locate_tablet(self, tablet_id: str) -> dict:
        resp = self.master_rpc("master.locate_tablet",
                               {"tablet_id": tablet_id})
        if resp.get("code") != "ok":
            raise AdminError(f"tablet {tablet_id}: {resp.get('code')}")
        return resp

    # -- tserver RPCs --------------------------------------------------------
    def _leader_rpc(self, tablet_id: str, method: str, payload: dict,
                    timeout_s: float = 10.0) -> dict:
        """Send to the tablet's leader, following not_leader hints and
        failing over to other replicas when the reported leader is down
        (re-fetching the location each round — it may have moved)."""
        deadline = time.monotonic() + timeout_s
        last = "unreachable"
        while True:
            loc = self.locate_tablet(tablet_id)
            hint = loc.get("leader")
            candidates = ([hint] if hint else []) + [
                r for r in loc["replicas"] if r != hint
            ]
            for target in candidates:
                try:
                    resp = self.transport.send(target, method, payload,
                                               timeout=3.0)
                except TransportError as e:
                    last = str(e)
                    continue
                if resp.get("code") == "not_leader":
                    last = "not_leader"
                    h = resp.get("leader_hint")
                    already = candidates[:candidates.index(target)]
                    if (h and h != target and h in loc["replicas"]
                            and h not in already):
                        try:
                            resp = self.transport.send(h, method, payload,
                                                       timeout=3.0)
                            if resp.get("code") != "not_leader":
                                return resp
                        except TransportError as e:
                            last = str(e)
                    continue
                return resp
            if time.monotonic() >= deadline:
                raise AdminError(
                    f"{method} on {tablet_id}: no leader reachable ({last})")
            time.sleep(0.2)

    def change_config(self, tablet_id: str, peers: list[str]) -> None:
        resp = self._leader_rpc(tablet_id, "ts.change_config",
                                {"tablet_id": tablet_id, "peers": peers})
        if resp.get("code") != "ok":
            raise AdminError(f"change_config: {resp.get('code')}")

    def leader_stepdown(self, tablet_id: str, target: str) -> None:
        resp = self._leader_rpc(tablet_id, "ts.transfer_leadership",
                                {"tablet_id": tablet_id, "target": target})
        if resp.get("code") != "ok":
            raise AdminError(f"leader_stepdown: {resp.get('code')}")

    def flush_table(self, table: str) -> int:
        n = 0
        for t in self.table_locations(table):
            self._leader_rpc(t["tablet_id"], "ts.flush",
                             {"tablet_id": t["tablet_id"]})
            n += 1
        return n

    def compact_table(self, table: str, history_cutoff_ht: int = 0) -> int:
        n = 0
        for t in self.table_locations(table):
            self._leader_rpc(t["tablet_id"], "ts.compact",
                             {"tablet_id": t["tablet_id"],
                              "history_cutoff_ht": history_cutoff_ht})
            n += 1
        return n

    def snapshot_table(self, table: str, snapshot_id: str,
                       op: str = "create_snapshot") -> int:
        """Run a snapshot op (create/restore/delete) on every tablet of a
        table (reference: the snapshot RPCs of backup.proto driven by
        yb-admin create_snapshot)."""
        n = 0
        for t in self.table_locations(table):
            resp = self._leader_rpc(t["tablet_id"], "ts.snapshot_op",
                                    {"tablet_id": t["tablet_id"],
                                     "snapshot_id": snapshot_id, "op": op})
            if resp.get("code") != "ok":
                raise AdminError(
                    f"{op} {snapshot_id} on {t['tablet_id']}: "
                    f"{resp.get('message', resp.get('code'))}")
            n += 1
        return n

    def cluster_snapshot(self, action: str, table: str | None = None,
                         snapshot_id: str | None = None) -> dict:
        """Master-coordinated cluster snapshot (yb-admin
        create_snapshot / restore_snapshot / delete_snapshot /
        list_snapshots): the MASTER fans the per-tablet ops and tracks
        the snapshot's state in the replicated sys catalog."""
        payload = {"action": action}
        if table is not None:
            payload["table"] = table
        if snapshot_id is not None:
            payload["snapshot_id"] = snapshot_id
        resp = self.master_rpc("master.snapshot_op", payload)
        if resp.get("code") != "ok":
            raise AdminError(
                f"snapshot {action}: "
                f"{resp.get('message', resp.get('code'))}")
        return resp

    def list_snapshots(self, table: str) -> dict[str, list[str]]:
        out = {}
        for t in self.table_locations(table):
            resp = self._leader_rpc(t["tablet_id"], "ts.list_snapshots",
                                    {"tablet_id": t["tablet_id"]})
            out[t["tablet_id"]] = resp.get("snapshots", [])
        return out

    def tserver_status(self, uuid: str) -> dict:
        return self.transport.send(uuid, "ts.status", {}, timeout=3.0)

    def checksum(self, tablet_id: str, replica: str,
                 read_ht: int | None = None) -> dict:
        payload = {"tablet_id": tablet_id}
        if read_ht is not None:
            payload["read_ht"] = read_ht
        return self.transport.send(replica, "ts.checksum", payload,
                                   timeout=15.0)
