"""ysck: cluster consistency checker.

Reference analog: src/yb/tools/ysck.cc + ysck_remote.cc — walk the
master's table/tablet/replica topology, health-check every tserver, and
run checksum scans on EVERY replica of every tablet at one pinned read
hybrid time, flagging replicas whose data diverges. ClusterVerifier
(src/yb/integration-tests/cluster_verifier.cc) runs this after every
integration test; tests here use it the same way.

Divergence that heals itself (a follower still applying) is not
corruption: checksums are retried with backoff until they agree or the
deadline passes — only a mismatch that PERSISTS is reported.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from yugabyte_db_tpu.consensus.transport import TransportError
from yugabyte_db_tpu.tools.admin_client import AdminClient


@dataclass
class TabletCheck:
    tablet_id: str
    table: str
    consistent: bool
    rows: int = 0
    read_ht: int = 0
    detail: str = ""
    replica_checksums: dict = field(default_factory=dict)


@dataclass
class YsckReport:
    ok: bool
    tservers_alive: int = 0
    tservers_dead: list = field(default_factory=list)
    tables_checked: int = 0
    tablet_checks: list = field(default_factory=list)

    def summary(self) -> str:
        lines = [f"tservers: {self.tservers_alive} alive"
                 + (f", DEAD: {self.tservers_dead}"
                    if self.tservers_dead else ""),
                 f"tables checked: {self.tables_checked}"]
        bad = [c for c in self.tablet_checks if not c.consistent]
        for c in self.tablet_checks:
            mark = "OK " if c.consistent else "BAD"
            lines.append(f"  [{mark}] {c.table}/{c.tablet_id} "
                         f"rows={c.rows}{' ' + c.detail if c.detail else ''}")
        lines.append("ysck: " + ("OK" if self.ok
                                 else f"{len(bad)} inconsistent tablet(s)"))
        return "\n".join(lines)


class Ysck:
    def __init__(self, admin: AdminClient):
        self.admin = admin

    def check_cluster(self, tables: list[str] | None = None,
                      timeout_s: float = 20.0) -> YsckReport:
        report = YsckReport(ok=True)
        for d in self.admin.list_tservers():
            if d.get("alive", True):
                report.tservers_alive += 1
            else:
                report.tservers_dead.append(d["uuid"])
                report.ok = False
        names = tables if tables is not None else \
            [t["name"] for t in self.admin.list_tables()]
        for name in names:
            report.tables_checked += 1
            for t in self.admin.table_locations(name):
                check = self._check_tablet(name, t, timeout_s)
                report.tablet_checks.append(check)
                if not check.consistent:
                    report.ok = False
        return report

    def _check_tablet(self, table: str, t: dict,
                      timeout_s: float) -> TabletCheck:
        tid = t["tablet_id"]
        replicas = [r["uuid"] for r in t["replicas"]]
        leader = t.get("leader") or (replicas[0] if replicas else None)
        if leader is None:
            return TabletCheck(tid, table, False, detail="no replicas")
        deadline = time.monotonic() + timeout_s
        last: dict = {}
        while True:
            try:
                # The leader (or first replica) picks the read point; the
                # rest of the group is checksummed AT that point.
                head = self.admin.checksum(tid, leader)
                if head.get("code") != "ok":
                    raise TransportError(head.get("code", "error"))
                read_ht = head["read_ht"]
                last = {leader: head["checksum"]}
                rows = head["rows"]
                agree = True
                for r in replicas:
                    if r == leader:
                        continue
                    resp = self.admin.checksum(tid, r, read_ht=read_ht)
                    if resp.get("code") != "ok":
                        raise TransportError(resp.get("code", "error"))
                    last[r] = resp["checksum"]
                    agree = agree and resp["checksum"] == head["checksum"]
                if agree:
                    return TabletCheck(tid, table, True, rows=rows,
                                       read_ht=read_ht,
                                       replica_checksums=last)
                detail = "checksum mismatch"
            except TransportError as e:
                detail = f"replica unreachable: {e}"
            if time.monotonic() >= deadline:
                return TabletCheck(tid, table, False, detail=detail,
                                   replica_checksums=last)
            time.sleep(0.5)


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="ysck", description="cluster consistency checker")
    ap.add_argument("--master", required=True,
                    help="host:port of any master")
    ap.add_argument("--tables", nargs="*", default=None)
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)
    admin = AdminClient.connect(args.master)
    report = Ysck(admin).check_cluster(args.tables, timeout_s=args.timeout)
    print(report.summary())
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
