"""fs_tool / log-dump: offline inspection of daemon data directories.

Reference analog: src/yb/tools/fs_tool.cc + fs_{list,dump}-tool.cc
(walk a server's data root, list tablets/SSTables, dump rows) and
src/yb/consensus/log-dump.cc (decode WAL segments record by record).

Operates purely on files — no running daemon required — so it is the
tool of last resort for a server that won't start.

Usage:
  python -m yugabyte_db_tpu.tools.fs_tool list <data_root>
  python -m yugabyte_db_tpu.tools.fs_tool dump_run <run-file.dat> [-n N]
  python -m yugabyte_db_tpu.tools.fs_tool dump_wal <wal-file.seg|wal-dir> [-n N]
"""

from __future__ import annotations

import json
import os
import struct
import zlib

from yugabyte_db_tpu.storage.row_version import MAX_HT
from yugabyte_db_tpu.utils import codec

_WAL_HEADER = struct.Struct("<II")


# -- listing -----------------------------------------------------------------

def list_tablet_dirs(data_root: str) -> list[dict]:
    """Inventory of every tablet directory under a daemon data root
    (tserver ``tablet-data/`` children or a master ``sys-catalog``)."""
    out = []
    candidates = []
    for dirpath, dirnames, filenames in os.walk(data_root):
        if "tablet-meta.json" in filenames or "consensus-meta.json" \
                in filenames:
            candidates.append(dirpath)
            dirnames[:] = [d for d in dirnames if d not in ("wal", "runs")]
    for tdir in sorted(candidates):
        info: dict = {"dir": tdir, "tablet_id": os.path.basename(tdir)}
        meta_path = os.path.join(tdir, "tablet-meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            info["table"] = meta.get("table_name", meta.get("table_id"))
            info["engine"] = meta.get("engine")
        wal_dir = os.path.join(tdir, "wal")
        segs = sorted(os.listdir(wal_dir)) if os.path.isdir(wal_dir) else []
        info["wal_segments"] = len(segs)
        info["wal_bytes"] = sum(
            os.path.getsize(os.path.join(wal_dir, s)) for s in segs)
        runs_dir = os.path.join(tdir, "runs")
        runs = sorted(os.listdir(runs_dir)) if os.path.isdir(runs_dir) else []
        info["runs"] = len(runs)
        info["run_bytes"] = sum(
            os.path.getsize(os.path.join(runs_dir, r)) for r in runs)
        out.append(info)
    return out


# -- run dump ----------------------------------------------------------------

def iter_run_entries(path: str):
    """Yield (key, [version-record, ...]) from one sorted-run file
    (storage.run_io format)."""
    with open(path, "rb") as f:
        magic, payload = codec.decode(f.read())
    if magic != "run1":
        raise ValueError(f"{path}: not a run file (magic {magic!r})")
    yield from payload


# -- wal dump ----------------------------------------------------------------

def iter_wal_records(path: str):
    """Yield (record, error) from one WAL segment; decoding stops at the
    first torn/corrupt record exactly as recovery does, but the tool also
    REPORTS it (log-dump's role)."""
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos + _WAL_HEADER.size <= len(data):
        ln, crc = _WAL_HEADER.unpack_from(data, pos)
        body = data[pos + _WAL_HEADER.size:pos + _WAL_HEADER.size + ln]
        if len(body) < ln:
            yield None, f"torn record at offset {pos} " \
                        f"(want {ln} bytes, have {len(body)})"
            return
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            yield None, f"CRC mismatch at offset {pos}"
            return
        yield codec.decode(body), None
        pos += _WAL_HEADER.size + ln


def wal_segment_paths(path: str) -> list[str]:
    if os.path.isdir(path):
        return [os.path.join(path, n) for n in sorted(os.listdir(path))
                if n.startswith("wal-") and n.endswith(".seg")]
    return [path]


# -- block-level dump (sst_dump analog) --------------------------------------

def _cmd_blocks(path: str, rows_per_block: int) -> int:
    """Rebuild the columnar block layout of one run file and print
    per-block metadata + plane statistics — the role of the reference's
    sst_dump over SSTable blocks (src/yb/rocksdb/tools/sst_dump_tool.cc),
    for the columnar format: block boundaries, key ranges, validity,
    per-column set/null density, plane checksums."""
    from yugabyte_db_tpu.models.schema import Schema  # noqa: F401 (doc)
    from yugabyte_db_tpu.storage.row_version import RowVersion

    entries = []
    for key, versions in iter_run_entries(path):
        entries.append((key, [
            RowVersion(key, ht=rec[0], tombstone=rec[1], liveness=rec[2],
                       columns={int(c): val for c, val in rec[3].items()},
                       expire_ht=rec[4],
                       write_id=rec[5] if len(rec) > 5 else 0)
            for rec in versions]))
    if not entries:
        print("empty run")
        return 0
    # A schema-free structural build: block packing + key/ht planes only
    # need the keys and version lists, so derive column ids from the data.
    col_ids = sorted({c for _k, vs in entries for v in vs
                      for c in v.columns})
    from yugabyte_db_tpu.storage.columnar import ColumnarRun

    ranges = ColumnarRun.pack_group_ranges(
        [len(v) for _, v in entries], rows_per_block)
    total_rows = sum(len(v) for _, v in entries)
    print(f"run: {len(entries)} keys, {total_rows} versions, "
          f"{len(ranges)} block(s) at R={rows_per_block}, "
          f"columns={col_ids}")
    for b, (g0, gn, rows) in enumerate(ranges):
        group = entries[g0:g0 + gn]
        min_key = group[0][0]
        max_key = group[-1][0]
        max_ht = max(v.ht for _k, vs in group for v in vs)
        tombs = sum(1 for _k, vs in group for v in vs if v.tombstone)
        per_col = {c: sum(1 for _k, vs in group for v in vs
                          if c in v.columns) for c in col_ids}
        crc = zlib.crc32(b"".join(k for k, _ in group)) & 0xFFFFFFFF
        print(f"  block {b}: rows={rows} groups={gn} "
              f"min={min_key.hex()[:24]} max={max_key.hex()[:24]} "
              f"max_ht={max_ht} tombstones={tombs} "
              f"set_counts={per_col} keycrc={crc:08x}")
    return 0


# -- CLI ---------------------------------------------------------------------

def _preview(v, limit=80) -> str:
    s = repr(v)
    return s if len(s) <= limit else s[:limit] + "..."


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="fs_tool")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("list")
    p.add_argument("data_root")
    p = sub.add_parser("dump_run")
    p.add_argument("path")
    p.add_argument("-n", type=int, default=20, help="max entries")
    p = sub.add_parser("dump_wal")
    p.add_argument("path")
    p.add_argument("-n", type=int, default=50, help="max records")
    p = sub.add_parser("blocks", help="block-level columnar layout of a "
                       "run (sst_dump analog)")
    p.add_argument("path")
    p.add_argument("--rows-per-block", type=int, default=2048)
    p = sub.add_parser("instance", help="data-dir identity record "
                       "(fs_manager instance metadata)")
    p.add_argument("data_dir")
    args = ap.parse_args(argv)

    if args.cmd == "list":
        infos = list_tablet_dirs(args.data_root)
        for i in infos:
            print(f"{i['tablet_id']}  table={i.get('table', '-')} "
                  f"engine={i.get('engine', '-')} "
                  f"wal={i['wal_segments']}seg/{i['wal_bytes']}B "
                  f"runs={i['runs']}/{i['run_bytes']}B")
        print(f"{len(infos)} tablet dir(s)")
        return 0

    if args.cmd == "dump_run":
        n = 0
        try:
            for key, versions in iter_run_entries(args.path):
                print(f"key={key.hex()} versions={len(versions)}")
                for v in versions:
                    ht, tomb, live, cols, exp = v[0], v[1], v[2], v[3], v[4]
                    kind = ("DEL" if tomb else "PUT" if live else "UPD")
                    print(f"  ht={ht} {kind} cols={_preview(cols)}"
                          + (f" expire_ht={exp}" if exp != MAX_HT else ""))
                n += 1
                if n >= args.n:
                    print("...")
                    break
        except Exception as e:  # noqa: BLE001 — corrupt file is the use case
            print(f"!! corrupt run file: {type(e).__name__}: {e}")
            return 1
        return 0

    if args.cmd == "blocks":
        return _cmd_blocks(args.path, args.rows_per_block)

    if args.cmd == "instance":
        path = os.path.join(args.data_dir, "instance")
        try:
            rec = codec.decode(open(path, "rb").read())
        except FileNotFoundError:
            print(f"{args.data_dir}: no instance metadata (unformatted)")
            return 1
        print(json.dumps({"server_uuid": rec[1], "instance_uuid": rec[2],
                          "format_time_us": rec[3]}))
        return 0

    # dump_wal
    shown = 0
    rc = 0
    for seg in wal_segment_paths(args.path):
        print(f"-- {seg}")
        for rec, err in iter_wal_records(seg):
            if err is not None:
                print(f"  !! {err}")
                rc = 1
                break
            term, index, ht, op_type, body = rec[0], rec[1], rec[2], \
                rec[3], rec[4]
            print(f"  {term}.{index} ht={ht} {op_type} "
                  f"{_preview(body)}")
            shown += 1
            if shown >= args.n:
                print("  ...")
                return rc
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
