"""Load tester: the sample-app workload suite.

Reference analog: java/yb-loadtester's com.yugabyte.sample.apps
(CassandraKeyValue etc.) and src/yb/benchmarks/yb_load_test_tool.cc —
the workloads behind the published performance numbers. Drives a real
cluster through the client with N writer/reader threads and reports
throughput + latency percentiles.

  python -m yugabyte_db_tpu.tools.load_test --master 127.0.0.1:7100 \
      --workload keyvalue --num-ops 50000 --threads 8 --read-ratio 0.5
"""

from __future__ import annotations

import argparse
import random
import sys
import threading
import time

from yugabyte_db_tpu.client.client import YBClient
from yugabyte_db_tpu.client.session import YBSession
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.scan_spec import ScanSpec


class Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.lat_us: list[int] = []
        self.errors = 0

    def add(self, us: int):
        with self.lock:
            self.lat_us.append(us)

    def error(self):
        with self.lock:
            self.errors += 1

    def report(self, elapsed: float, label: str) -> dict:
        with self.lock:
            lats = sorted(self.lat_us)
            n = len(lats)
        if not n:
            return {"workload": label, "ops": 0, "errors": self.errors}
        return {
            "workload": label,
            "ops": n,
            "errors": self.errors,
            "ops_per_sec": round(n / elapsed, 1),
            "avg_us": sum(lats) // n,
            "p50_us": lats[n // 2],
            "p99_us": lats[min(n - 1, n * 99 // 100)],
        }


def _run_threads(n_threads, per_thread_fn):
    threads = [threading.Thread(target=per_thread_fn, args=(i,))
               for i in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.perf_counter() - t0


def ensure_table(client: YBClient, table_name: str,
                 num_tablets: int) -> None:
    try:
        client.open_table(table_name)
    except KeyError:
        client.create_table(table_name, [
            ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
            ColumnSchema("v", DataType.STRING),
        ], num_tablets=num_tablets)


def run_keyvalue(master: str, num_ops: int, threads: int,
                 read_ratio: float, batch: int, value_size: int,
                 table_name: str = "load_kv",
                 num_tablets: int = 8) -> dict:
    """CassandraKeyValue shape: random-key writes and point reads."""
    boot = YBClient.connect(master)
    ensure_table(boot, table_name, num_tablets)
    write_stats, read_stats = Stats(), Stats()
    per = num_ops // threads
    value = "v" * value_size
    written_floor = max(1, per // 10)

    def worker(wid):
        client = YBClient.connect(master)
        session = YBSession(client)
        table = client.open_table(table_name)
        rng = random.Random(wid)
        pending = 0
        written = 0    # inserted (possibly still client-buffered)
        acked = 0      # flushed: reads must only target these
        for i in range(per):
            if rng.random() < read_ratio and acked > written_floor:
                k = f"w{wid}-{rng.randrange(acked):08d}"
                t0 = time.perf_counter()
                try:
                    session.get(table, {"k": k})
                    read_stats.add(int((time.perf_counter() - t0) * 1e6))
                except Exception:  # noqa: BLE001
                    read_stats.error()
                continue
            session.insert(table, {"k": f"w{wid}-{written:08d}",
                                   "v": value})
            written += 1
            pending += 1
            if pending >= batch:
                t0 = time.perf_counter()
                try:
                    session.flush()
                    write_stats.add(
                        int((time.perf_counter() - t0) * 1e6 // pending))
                    acked = written
                except Exception:  # noqa: BLE001
                    write_stats.error()
                pending = 0
        if pending:
            try:
                session.flush()
            except Exception:  # noqa: BLE001 — must count, not vanish
                write_stats.error()

    elapsed = _run_threads(threads, worker)
    return {"elapsed_s": round(elapsed, 1),
            "write": write_stats.report(elapsed, "keyvalue-write"),
            "read": read_stats.report(elapsed, "keyvalue-read")}


def run_scan(master: str, num_ops: int, threads: int, limit: int,
             table_name: str = "load_kv") -> dict:
    """YCSB-E shape: LIMIT pages from random start keys."""
    boot = YBClient.connect(master)
    table = boot.open_table(table_name)
    stats = Stats()
    per = num_ops // threads

    def worker(wid):
        client = YBClient.connect(master)
        session = YBSession(client)
        t = client.open_table(table_name)
        rng = random.Random(1000 + wid)
        for _ in range(per):
            lo = t.encode_key({"k": f"w{rng.randrange(threads)}-"
                                    f"{rng.randrange(1000):08d}"})
            t0 = time.perf_counter()
            try:
                session.scan(t, ScanSpec(lower=lo, limit=limit,
                                         projection=["k", "v"]))
                stats.add(int((time.perf_counter() - t0) * 1e6))
            except Exception:  # noqa: BLE001
                stats.error()

    elapsed = _run_threads(threads, worker)
    return {"elapsed_s": round(elapsed, 1),
            "scan": stats.report(elapsed, "range-scan")}


def main(argv=None) -> int:
    import json

    ap = argparse.ArgumentParser(prog="yb-load-test")
    ap.add_argument("--master", required=True)
    ap.add_argument("--workload", choices=("keyvalue", "scan"),
                    default="keyvalue")
    ap.add_argument("--num-ops", type=int, default=20_000)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--read-ratio", type=float, default=0.5)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--value-size", type=int, default=64)
    ap.add_argument("--limit", type=int, default=100)
    args = ap.parse_args(argv)
    if args.workload == "keyvalue":
        out = run_keyvalue(args.master, args.num_ops, args.threads,
                           args.read_ratio, args.batch, args.value_size)
    else:
        out = run_scan(args.master, args.num_ops, args.threads,
                       args.limit)
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
