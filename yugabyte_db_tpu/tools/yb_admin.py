"""yb-admin: cluster administration CLI.

Reference analog: src/yb/tools/yb-admin_cli.cc — the operator commands
(list_tables, list_tablets, list_all_tablet_servers, change_config,
leader_stepdown, flush/compact, delete_table) over AdminClient.

Usage: python -m yugabyte_db_tpu.tools.yb_admin --master host:port CMD ...
"""

from __future__ import annotations

import argparse

from yugabyte_db_tpu.tools.admin_client import AdminClient


def _fmt_table(rows: list[list], header: list[str]) -> str:
    cols = [header] + [[str(c) for c in r] for r in rows]
    widths = [max(len(r[i]) for r in cols) for i in range(len(header))]
    out = []
    for i, r in enumerate(cols):
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
        if i == 0:
            out.append("-" * (sum(widths) + 2 * (len(widths) - 1)))
    return "\n".join(out)


def cmd_list_tables(admin: AdminClient, args) -> int:
    rows = [[t["name"], t["table_id"], t["state"], t["num_tablets"]]
            for t in admin.list_tables()]
    print(_fmt_table(rows, ["name", "table_id", "state", "tablets"]))
    return 0


def cmd_list_tablets(admin: AdminClient, args) -> int:
    rows = []
    for t in admin.table_locations(args.table):
        rows.append([t["tablet_id"], t["partition_start"],
                     t["partition_end"],
                     ",".join(r["uuid"] for r in t["replicas"]),
                     t.get("leader") or "?"])
    print(_fmt_table(rows, ["tablet_id", "start", "end", "replicas",
                            "leader"]))
    return 0


def cmd_list_tablet_servers(admin: AdminClient, args) -> int:
    rows = [[d["uuid"], d.get("addr"), "ALIVE" if d.get("alive") else "DEAD",
             d.get("num_live_tablets", 0)]
            for d in admin.list_tservers()]
    print(_fmt_table(rows, ["uuid", "addr", "state", "tablets"]))
    return 0


def cmd_change_config(admin: AdminClient, args) -> int:
    admin.change_config(args.tablet_id, args.peers.split(","))
    print("config changed")
    return 0


def cmd_leader_stepdown(admin: AdminClient, args) -> int:
    admin.leader_stepdown(args.tablet_id, args.target)
    print("stepdown requested")
    return 0


def cmd_flush_table(admin: AdminClient, args) -> int:
    n = admin.flush_table(args.table)
    print(f"flushed {n} tablet(s)")
    return 0


def cmd_compact_table(admin: AdminClient, args) -> int:
    n = admin.compact_table(args.table, args.history_cutoff_ht)
    print(f"compacted {n} tablet(s)")
    return 0


def cmd_delete_table(admin: AdminClient, args) -> int:
    admin.delete_table(args.table)
    print(f"deleted {args.table}")
    return 0


def cmd_split_tablet(admin: AdminClient, args) -> int:
    resp = admin.split_tablet(args.table, args.tablet_id,
                              timeout_s=args.timeout)
    kids = resp.get("children") or []
    print(f"split {args.tablet_id} -> {', '.join(kids)}")
    return 0


def cmd_rebalance(admin: AdminClient, args) -> int:
    resp = admin.rebalance()
    move = resp.get("move")
    if move:
        print(f"moved leader of {move['tablet_id']}: "
              f"{move['from']} -> {move['to']}")
    else:
        print("balanced (no move needed)")
    counts = resp.get("leader_counts") or {}
    rows = [[u, n] for u, n in sorted(counts.items())]
    if rows:
        print(_fmt_table(rows, ["tserver", "leaders"]))
    return 0


def cmd_create_snapshot(admin: AdminClient, args) -> int:
    n = admin.snapshot_table(args.table, args.snapshot_id,
                             "create_snapshot")
    print(f"created snapshot {args.snapshot_id} on {n} tablet(s)")
    return 0


def cmd_restore_snapshot(admin: AdminClient, args) -> int:
    n = admin.snapshot_table(args.table, args.snapshot_id,
                             "restore_snapshot")
    print(f"restored snapshot {args.snapshot_id} on {n} tablet(s)")
    return 0


def cmd_delete_snapshot(admin: AdminClient, args) -> int:
    n = admin.snapshot_table(args.table, args.snapshot_id,
                             "delete_snapshot")
    print(f"deleted snapshot {args.snapshot_id} on {n} tablet(s)")
    return 0


def cmd_list_snapshots(admin: AdminClient, args) -> int:
    snaps = admin.list_snapshots(args.table)
    rows = [[tid, ", ".join(s) or "-"] for tid, s in sorted(snaps.items())]
    print(_fmt_table(rows, ["TABLET", "SNAPSHOTS"]))
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="yb-admin")
    ap.add_argument("--master", required=True, help="host:port of any master")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("list_tables").set_defaults(fn=cmd_list_tables)

    p = sub.add_parser("list_tablets")
    p.add_argument("table")
    p.set_defaults(fn=cmd_list_tablets)

    sub.add_parser("list_all_tablet_servers").set_defaults(
        fn=cmd_list_tablet_servers)

    p = sub.add_parser("change_config")
    p.add_argument("tablet_id")
    p.add_argument("peers", help="comma-separated peer uuids")
    p.set_defaults(fn=cmd_change_config)

    p = sub.add_parser("leader_stepdown")
    p.add_argument("tablet_id")
    p.add_argument("target")
    p.set_defaults(fn=cmd_leader_stepdown)

    p = sub.add_parser("flush_table")
    p.add_argument("table")
    p.set_defaults(fn=cmd_flush_table)

    p = sub.add_parser("compact_table")
    p.add_argument("table")
    p.add_argument("--history_cutoff_ht", type=int, default=0)
    p.set_defaults(fn=cmd_compact_table)

    p = sub.add_parser("delete_table")
    p.add_argument("table")
    p.set_defaults(fn=cmd_delete_table)

    p = sub.add_parser("split_tablet")
    p.add_argument("table")
    p.add_argument("tablet_id")
    p.add_argument("--timeout", type=float, default=30.0)
    p.set_defaults(fn=cmd_split_tablet)

    sub.add_parser("rebalance").set_defaults(fn=cmd_rebalance)

    for name, fn in (("create_snapshot", cmd_create_snapshot),
                     ("restore_snapshot", cmd_restore_snapshot),
                     ("delete_snapshot", cmd_delete_snapshot)):
        p = sub.add_parser(name)
        p.add_argument("table")
        p.add_argument("snapshot_id")
        p.set_defaults(fn=fn)

    p = sub.add_parser("list_snapshots")
    p.add_argument("table")
    p.set_defaults(fn=cmd_list_snapshots)
    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    admin = AdminClient.connect(args.master)
    return args.fn(admin, args)


if __name__ == "__main__":
    raise SystemExit(main())
