"""yb-docker-ctl: local containerized cluster orchestrator.

Reference analog: bin/yb-docker-ctl — create/start/stop/destroy a local
cluster where every daemon is a docker container on one bridge network.
The command construction is pure (testable without a docker engine);
``--dry-run`` prints the exact docker invocations instead of executing.

Usage:
  python -m yugabyte_db_tpu.tools.yb_docker_ctl create \
      [--masters N] [--tservers N] [--image yugabyte-tpu:latest] [--dry-run]
  python -m yugabyte_db_tpu.tools.yb_docker_ctl destroy [--dry-run]
  python -m yugabyte_db_tpu.tools.yb_docker_ctl status
"""

from __future__ import annotations

import subprocess

NETWORK = "yb-tpu-net"
MASTER_RPC, MASTER_WEB = 7100, 7000
TS_RPC, TS_WEB = 9100, 9000


def master_names(n: int) -> list[str]:
    return [f"yb-master-{i}" for i in range(n)]


def tserver_names(n: int) -> list[str]:
    return [f"yb-tserver-{i}" for i in range(n)]


def topology(masters: list[str]) -> str:
    return ",".join(f"{m}={m}:{MASTER_RPC}" for m in masters)


def create_commands(num_masters: int, num_tservers: int,
                    image: str) -> list[list[str]]:
    """The full docker command sequence bringing a cluster up."""
    masters = master_names(num_masters)
    cmds = [["docker", "network", "create", NETWORK]]
    for i, name in enumerate(masters):
        cmds.append([
            "docker", "run", "-d", "--name", name, "--hostname", name,
            "--network", NETWORK,
            "-p", f"{MASTER_WEB + i}:{MASTER_WEB}",
            "-v", f"{name}-data:/mnt/data",
            "-e", "JAX_PLATFORMS=cpu",
            image,
            "--role", "master", "--uuid", name,
            "--data-dir", "/mnt/data",
            "--masters", ",".join(masters),
            "--topology", topology(masters),
            "--web-port", str(MASTER_WEB),
        ])
    for i, name in enumerate(tserver_names(num_tservers)):
        cmds.append([
            "docker", "run", "-d", "--name", name, "--hostname", name,
            "--network", NETWORK,
            "-p", f"{TS_WEB + 100 + i}:{TS_WEB}",
            "-v", f"{name}-data:/mnt/data",
            image,
            "--role", "tserver", "--uuid", name,
            "--data-dir", "/mnt/data",
            "--masters", ",".join(masters),
            "--topology", topology(masters),
            "--web-port", str(TS_WEB),
        ])
    return cmds


def destroy_commands(num_masters: int = 8,
                     num_tservers: int = 16) -> list[list[str]]:
    """Remove any cluster containers/volumes up to the given bounds
    (idempotent: docker rm -f of an absent container is tolerated)."""
    names = master_names(num_masters) + tserver_names(num_tservers)
    cmds = [["docker", "rm", "-f"] + names]
    cmds.append(["docker", "volume", "rm", "-f"]
                + [f"{n}-data" for n in names])
    cmds.append(["docker", "network", "rm", NETWORK])
    return cmds


def _run(cmds: list[list[str]], dry_run: bool, tolerate=False) -> int:
    for cmd in cmds:
        if dry_run:
            print(" ".join(cmd))
            continue
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0 and not tolerate:
            print(proc.stderr.strip())
            return proc.returncode
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="yb-docker-ctl")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("create")
    p.add_argument("--masters", type=int, default=1)
    p.add_argument("--tservers", type=int, default=3)
    p.add_argument("--image", default="yugabyte-tpu:latest")
    p.add_argument("--dry-run", action="store_true")
    p = sub.add_parser("destroy")
    p.add_argument("--dry-run", action="store_true")
    sub.add_parser("status")
    args = ap.parse_args(argv)

    if args.cmd == "create":
        return _run(create_commands(args.masters, args.tservers,
                                    args.image), args.dry_run)
    if args.cmd == "destroy":
        return _run(destroy_commands(), args.dry_run, tolerate=True)
    # status
    return _run([["docker", "ps", "--filter", f"network={NETWORK}",
                  "--format", "{{.Names}}\t{{.Status}}"]], False,
                tolerate=True)


if __name__ == "__main__":
    raise SystemExit(main())
