"""Operator tooling: admin client/CLI (yb-admin), consistency checker
(ysck), offline fs/WAL inspection.

Reference analog: src/yb/tools/ (yb-admin_cli.cc, ysck.cc, fs_tool.cc)
+ src/yb/consensus/log-dump.cc.
"""

from yugabyte_db_tpu.tools.admin_client import AdminClient
from yugabyte_db_tpu.tools.ysck import Ysck, YsckReport

__all__ = ["AdminClient", "Ysck", "YsckReport"]
