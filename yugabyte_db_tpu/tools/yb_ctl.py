"""yb-ctl: local multi-process cluster orchestrator.

Reference analog: bin/yb-ctl — create/start/stop/status/destroy a local
cluster of REAL master and tserver processes (each with its own
interpreter, Messenger, data dir, and webserver), wired over loopback
TCP with deterministic ports.

  python -m yugabyte_db_tpu.tools.yb_ctl --data-dir /tmp/ybt create \
      --num-masters 1 --num-tservers 3
  python -m yugabyte_db_tpu.tools.yb_ctl --data-dir /tmp/ybt status
  python -m yugabyte_db_tpu.tools.yb_ctl --data-dir /tmp/ybt destroy
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import time

from yugabyte_db_tpu.utils.metrics import count_swallowed

STATE_FILE = "cluster.json"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


def _http_ok(port: int, path: str = "/healthz",
             timeout: float = 1.0) -> bool:
    import urllib.request

    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return r.status == 200
    except Exception:  # noqa: BLE001
        return False


class ClusterCtl:
    def __init__(self, data_dir: str):
        self.data_dir = os.path.abspath(data_dir)
        self.state_path = os.path.join(self.data_dir, STATE_FILE)

    # -- state ---------------------------------------------------------------
    def load(self) -> dict:
        with open(self.state_path) as f:
            return json.load(f)

    def save(self, state: dict) -> None:
        os.makedirs(self.data_dir, exist_ok=True)
        with open(self.state_path, "w") as f:
            json.dump(state, f, indent=1)

    # -- commands ------------------------------------------------------------
    def create(self, num_masters: int, num_tservers: int,
               engine: str = "cpu", fsync: bool = False) -> dict:
        if os.path.exists(self.state_path):
            raise SystemExit(f"cluster already exists at {self.data_dir} "
                             f"(use start/destroy)")
        daemons = []
        for i in range(num_masters):
            daemons.append({"role": "master", "uuid": f"m-{i}"})
        for i in range(num_tservers):
            daemons.append({"role": "tserver", "uuid": f"ts-{i}"})
        for d in daemons:
            d["rpc_port"] = _free_port()
            d["web_port"] = _free_port()
        state = {
            "engine": engine,
            "fsync": fsync,
            "daemons": daemons,
            "topology": ",".join(
                f"{d['uuid']}=127.0.0.1:{d['rpc_port']}" for d in daemons),
            "masters": ",".join(d["uuid"] for d in daemons
                                if d["role"] == "master"),
        }
        self.save(state)
        self.start()
        return state

    def _spawn(self, state: dict, d: dict) -> int:
        log_path = os.path.join(self.data_dir, f"{d['uuid']}.log")
        log = open(log_path, "ab")
        env = dict(os.environ)
        # Daemons run the cpu engine: FORCE the cpu backend (override,
        # not setdefault — the ambient env may pin the real-TPU tunnel,
        # and N daemons grabbing the single-chip lease would deadlock
        # the machine's actual TPU user).
        env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, "-m",
               "yugabyte_db_tpu.server.daemon_main",
               "--role", d["role"], "--uuid", d["uuid"],
               "--data-dir", os.path.join(self.data_dir, d["uuid"]),
               "--topology", state["topology"],
               "--masters", state["masters"],
               "--web-port", str(d["web_port"])]
        if not state.get("fsync", False):
            cmd.append("--no-fsync")
        proc = subprocess.Popen(cmd, stdout=log, stderr=log, env=env,
                                start_new_session=True)
        log.close()
        return proc.pid

    def start(self) -> None:
        state = self.load()
        for d in state["daemons"]:
            if d.get("pid") and _pid_alive(d["pid"]):
                continue
            d["pid"] = self._spawn(state, d)
        self.save(state)
        deadline = time.monotonic() + 30.0
        pending = list(state["daemons"])
        while pending and time.monotonic() < deadline:
            pending = [d for d in pending if not _http_ok(d["web_port"])]
            if pending:
                time.sleep(0.2)
        if pending:
            raise SystemExit(
                "daemons failed to become healthy: "
                + ", ".join(d["uuid"] for d in pending)
                + f" (logs in {self.data_dir})")

    def stop(self) -> None:
        state = self.load()
        for d in state["daemons"]:
            pid = d.get("pid")
            if pid and _pid_alive(pid):
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if not any(d.get("pid") and _pid_alive(d["pid"])
                       for d in state["daemons"]):
                break
            time.sleep(0.1)
        for d in state["daemons"]:
            pid = d.get("pid")
            if pid and _pid_alive(pid):
                os.kill(pid, signal.SIGKILL)
            d["pid"] = None
        self.save(state)

    def status(self) -> list[dict]:
        state = self.load()
        out = []
        for d in state["daemons"]:
            alive = bool(d.get("pid")) and _pid_alive(d["pid"])
            out.append({
                "uuid": d["uuid"], "role": d["role"],
                "pid": d.get("pid"), "alive": alive,
                "healthy": alive and _http_ok(d["web_port"]),
                "rpc": f"127.0.0.1:{d['rpc_port']}",
                "web": f"http://127.0.0.1:{d['web_port']}",
            })
        return out

    def destroy(self) -> None:
        if os.path.exists(self.state_path):
            self.stop()
        shutil.rmtree(self.data_dir, ignore_errors=True)

    def wait_tservers_registered(self, n: int | None = None,
                                 timeout_s: float = 30.0) -> None:
        """Block until n tservers are registered live with the master
        (the cluster is usable for create_table only after that)."""
        from yugabyte_db_tpu.tools.admin_client import AdminClient

        state = self.load()
        want = n if n is not None else sum(
            1 for d in state["daemons"] if d["role"] == "tserver")
        admin = AdminClient.connect(self.master_addresses())
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                if len(admin.list_tservers()) >= want:
                    return
            except Exception as e:  # noqa: BLE001 — master still electing
                count_swallowed("yb_ctl.wait_tservers", e)
            time.sleep(0.2)
        raise SystemExit(f"tservers did not register within {timeout_s}s")

    def master_addresses(self) -> str:
        state = self.load()
        return ",".join(f"127.0.0.1:{d['rpc_port']}"
                        for d in state["daemons"]
                        if d["role"] == "master")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="yb-ctl")
    ap.add_argument("--data-dir", required=True)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("create")
    p.add_argument("--num-masters", type=int, default=1)
    p.add_argument("--num-tservers", type=int, default=3)
    p.add_argument("--engine", default="cpu")
    p.add_argument("--fsync", action="store_true")
    sub.add_parser("start")
    sub.add_parser("stop")
    sub.add_parser("status")
    sub.add_parser("destroy")
    sub.add_parser("master_addresses")
    args = ap.parse_args(argv)
    ctl = ClusterCtl(args.data_dir)
    if args.cmd == "create":
        ctl.create(args.num_masters, args.num_tservers, args.engine,
                   args.fsync)
        print(f"cluster up; masters at {ctl.master_addresses()}")
    elif args.cmd == "start":
        ctl.start()
        print("cluster started")
    elif args.cmd == "stop":
        ctl.stop()
        print("cluster stopped")
    elif args.cmd == "status":
        for row in ctl.status():
            print(json.dumps(row))
    elif args.cmd == "destroy":
        ctl.destroy()
        print("cluster destroyed")
    elif args.cmd == "master_addresses":
        print(ctl.master_addresses())
    return 0


if __name__ == "__main__":
    sys.exit(main())
