"""Index schema derivation and write-path mutation computation.

An index on column C of base table T is itself a table:

    hash key:   C (the indexed column)
    range keys: T's primary key columns, in order
    values:     none (rows are liveness markers)

so an equality lookup on C is a hash-routed scan of the index table whose
rows decode straight back into base-table primary keys (reference:
IndexInfo's mapping of indexed + covered columns, src/yb/common/index.h).

Maintenance (Tablet::UpdateQLIndexes, tablet.cc:1015): on a base-table
write the leader compares old vs new indexed values; a changed value
yields a tombstone for the old index row and an insert of the new one.
NULL values have no index entry (CQL semantics).
"""

from __future__ import annotations

from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage.row_version import RowVersion


def index_table_name(base_table: str, column: str,
                     index_name: str | None = None) -> str:
    if index_name:
        if "." in base_table and "." not in index_name:
            ks = base_table.rsplit(".", 1)[0]
            return f"{ks}.{index_name}"
        return index_name
    return f"{base_table}__idx__{column}"


def index_schema(base_schema: Schema, column: str,
                 index_table: str) -> Schema:
    """Derive the index table's schema from the base schema."""
    idx_col = base_schema.column(column)
    if idx_col.is_key:
        raise ValueError(f"cannot index key column {column}")
    cols = [ColumnSchema(column, idx_col.dtype, ColumnKind.HASH)]
    for kc in base_schema.key_columns:
        cols.append(ColumnSchema(kc.name, kc.dtype, ColumnKind.RANGE))
    return Schema(cols, table_id=index_table)


def index_entry(index_schema_: Schema, indexed_value,
                base_key_values: dict) -> tuple[int, RowVersion]:
    """A liveness index row for (value, base PK) — backfill's unit."""
    return _entry(index_schema_, indexed_value, base_key_values,
                  tombstone=False)


def _entry(index_schema_: Schema, indexed_value, base_key_values: dict,
           tombstone: bool) -> tuple[int, RowVersion]:
    """One index-table row: returns (hash_code, RowVersion)."""
    idx_name = index_schema_.hash_columns[0].name
    kv = {idx_name: indexed_value}
    kv.update(base_key_values)
    hash_code = compute_hash_code(index_schema_, kv)
    key = index_schema_.encode_primary_key(kv, hash_code)
    if tombstone:
        return hash_code, RowVersion(key, ht=0, tombstone=True)
    return hash_code, RowVersion(key, ht=0, liveness=True, columns={})


def index_mutations(base_schema: Schema, indexes: list[dict],
                    base_key_values: dict, old_values: dict | None,
                    new_row: RowVersion):
    """Index-table writes for one base-table write.

    ``indexes``: [{"column", "index_table"}...]; ``old_values``: the
    row's current merged column values by NAME (None if the row didn't
    exist); ``new_row``: the incoming base write. Yields
    (index_table, index_schema, hash_code, RowVersion)."""
    col_by_id = {c.col_id: c.name for c in base_schema.value_columns}
    for idx in indexes:
        column = idx["column"]
        ischema = index_schema(base_schema, column, idx["index_table"])
        old_v = (old_values or {}).get(column)
        if new_row.tombstone:
            new_v = None          # whole-row delete: drop the entry
        else:
            cid = base_schema.column(column).col_id
            if cid in new_row.columns:
                new_v = new_row.columns[cid]
            else:
                new_v = old_v     # write doesn't touch the indexed column
        if old_v == new_v:
            continue
        if old_v is not None:
            hc, rv = _entry(ischema, old_v, base_key_values, tombstone=True)
            yield idx["index_table"], ischema, hc, rv
        if new_v is not None:
            hc, rv = _entry(ischema, new_v, base_key_values,
                            tombstone=False)
            yield idx["index_table"], ischema, hc, rv
    _ = col_by_id  # (kept for future covered-column support)
