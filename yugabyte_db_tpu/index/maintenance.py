"""Index schema derivation and write-path mutation computation.

An index on columns (C1..Cn) of base table T — optionally COVERING
columns (X1..Xm) — is itself a table:

    hash keys:  C1..Cn (the indexed columns, compound hash)
    range keys: T's primary key columns, in order
    values:     the covered columns (INCLUDE list)

so an equality lookup on all indexed columns is a hash-routed scan of
the index table whose rows decode straight back into base-table primary
keys — and, when the query only touches indexed + key + covered
columns, the index table answers it WITHOUT reading the base table
(reference: IndexInfo's indexed + covered column mapping,
src/yb/common/index.h).

Maintenance (Tablet::UpdateQLIndexes, tablet.cc:1015): on a base-table
write the leader compares old vs new indexed/covered values; a changed
indexed tuple yields a tombstone for the old index row and an insert of
the new one; a covered-only change rewrites the entry in place. A row
has an index entry only while EVERY indexed column is non-NULL.
"""

from __future__ import annotations

from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema, Schema
from yugabyte_db_tpu.storage.row_version import RowVersion


def normalize_index(idx: dict) -> dict:
    """Canonical descriptor: {"name", "columns": [...], "include": [...],
    "index_table"}; accepts the legacy single-"column" form (older
    catalog/WAL records)."""
    columns = list(idx.get("columns") or
                   ([idx["column"]] if idx.get("column") else []))
    out = dict(idx)
    out["columns"] = columns
    out["include"] = list(idx.get("include") or [])
    if columns and "column" not in out:
        out["column"] = columns[0]  # legacy readers
    return out


def index_table_name(base_table: str, columns, index_name=None) -> str:
    if isinstance(columns, str):
        columns = [columns]
    if index_name:
        if "." in base_table and "." not in index_name:
            ks = base_table.rsplit(".", 1)[0]
            return f"{ks}.{index_name}"
        return index_name
    return f"{base_table}__idx__{'_'.join(columns)}"


def index_schema(base_schema: Schema, columns, index_table: str,
                 include=()) -> Schema:
    """Derive the index table's schema from the base schema."""
    if isinstance(columns, str):
        columns = [columns]
    cols = []
    for name in columns:
        c = base_schema.column(name)
        if c.is_key:
            raise ValueError(f"cannot index key column {name}")
        cols.append(ColumnSchema(name, c.dtype, ColumnKind.HASH))
    for kc in base_schema.key_columns:
        cols.append(ColumnSchema(kc.name, kc.dtype, ColumnKind.RANGE))
    for name in include:
        c = base_schema.column(name)
        if c.is_key or name in columns:
            raise ValueError(f"cannot cover column {name}")
        cols.append(ColumnSchema(name, c.dtype))
    return Schema(cols, table_id=index_table)


def index_entry(index_schema_: Schema, indexed_values,
                base_key_values: dict, covered: dict | None = None):
    """An index row for (values, base PK) — backfill's unit.
    ``indexed_values``: one value (legacy) or a list matching the
    index's hash columns; ``covered``: {name: value} for INCLUDE cols."""
    if not isinstance(indexed_values, (list, tuple)):
        indexed_values = [indexed_values]
    return _entry(index_schema_, list(indexed_values), base_key_values,
                  tombstone=False, covered=covered or {})


def _entry(index_schema_: Schema, indexed_values: list,
           base_key_values: dict, tombstone: bool,
           covered: dict | None = None) -> tuple[int, RowVersion]:
    """One index-table row: returns (hash_code, RowVersion)."""
    kv = {c.name: v for c, v in zip(index_schema_.hash_columns,
                                    indexed_values)}
    kv.update(base_key_values)
    hash_code = compute_hash_code(index_schema_, kv)
    key = index_schema_.encode_primary_key(kv, hash_code)
    if tombstone:
        return hash_code, RowVersion(key, ht=0, tombstone=True)
    columns = {}
    for c in index_schema_.value_columns:
        if covered and c.name in covered:
            columns[c.col_id] = covered[c.name]
    return hash_code, RowVersion(key, ht=0, liveness=True,
                                 columns=columns)


def index_mutations(base_schema: Schema, indexes: list[dict],
                    base_key_values: dict, old_values: dict | None,
                    new_row: RowVersion):
    """Index-table writes for one base-table write.

    ``old_values``: the row's current merged column values by NAME (None
    if the row didn't exist); ``new_row``: the incoming base write.
    Yields (index_table, index_schema, hash_code, RowVersion)."""
    col_by_id = {c.col_id: c.name for c in base_schema.value_columns}
    new_by_name = {col_by_id[cid]: v for cid, v in new_row.columns.items()
                   if cid in col_by_id}

    def merged(name):
        if new_row.tombstone:
            return None
        if name in new_by_name:
            return new_by_name[name]
        return (old_values or {}).get(name)

    for idx in indexes:
        idx = normalize_index(idx)
        columns = idx["columns"]
        include = idx["include"]
        ischema = index_schema(base_schema, columns,
                               idx["index_table"], include)
        old_t = ([(old_values or {}).get(c) for c in columns]
                 if old_values is not None else None)
        new_t = [merged(c) for c in columns]
        old_valid = old_t is not None and all(v is not None for v in old_t)
        new_valid = (not new_row.tombstone
                     and all(v is not None for v in new_t))
        old_cov = {c: (old_values or {}).get(c) for c in include}
        new_cov = {c: merged(c) for c in include}
        if old_valid and (not new_valid or old_t != new_t):
            hc, rv = _entry(ischema, old_t, base_key_values,
                            tombstone=True)
            yield idx["index_table"], ischema, hc, rv
        if new_valid and (not old_valid or old_t != new_t
                          or old_cov != new_cov):
            hc, rv = _entry(ischema, new_t, base_key_values,
                            tombstone=False, covered=new_cov)
            yield idx["index_table"], ischema, hc, rv
