"""Secondary indexes: schema derivation + write-path maintenance.

Reference analog: src/yb/common/index.h (IndexInfo) and the index update
hook in the tablet write path (Tablet::UpdateQLIndexes,
src/yb/tablet/tablet.cc:1015) — the leader computes index mutations from
the old and new row states and issues them to the index table.
"""

from yugabyte_db_tpu.index.maintenance import (index_entry, index_mutations,
                                               index_schema,
                                               index_table_name,
                                               normalize_index)

__all__ = ["index_entry", "index_mutations", "index_schema",
           "index_table_name", "normalize_index"]
