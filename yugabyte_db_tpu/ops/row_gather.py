"""Packed device-side row materialization: the YCSB-E hot path.

A single jitted dispatch scans every block window in a key range with a
``lax.while_loop``, resolves MVCC visibility + predicates per key group
(ops.scan.resolve_window), and scatter-compacts the matched rows — group
start row index plus each projected column's latest-visible value planes —
into ONE fixed-capacity int32 output matrix. The host then bulk-decodes
the packed planes with vectorized numpy (utils.planes inverses); per-row
Python work is proportional to the *result* size, never the scanned size.

Interface design is driven by measured link behavior (the host↔device
link pays ~1 RTT per blocking call, ~ms per transferred array, and
pipelines async dispatches):
- every dynamic scalar (window range, row bounds, read point, predicate
  literals) rides in ONE int32 params vector (+ one float32 vector when
  f32 literals exist) — one upload per dispatch, not eight;
- the entire result (packed rows + count/scanned/w_end scalars) is ONE
  int32 [M+1, W] matrix — one download per dispatch;
- ``compiled_gather_batch`` vmaps the program over G independent scans
  (one tablet serving many concurrent pages — the YCSB-E server shape),
  so a whole batch costs one dispatch + one download.

Reference analog: the DocRowwiseIterator::HasNext/DoNextRow hot loop
(src/yb/docdb/doc_rowwise_iterator.cc:545) — here vectorized across a
whole key range in one device program, with LIMIT/paging expressed as the
output buffer capacity (truncation is a clean in-key-order prefix, so a
page resumes exactly where the buffer filled).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from yugabyte_db_tpu.ops.scan import resolve_window
from yugabyte_db_tpu.utils.jitting import compile_contract

# Fixed slots at the head of the int32 params vector; predicate literal
# planes follow from PARAM_FIXED onward (layout per GatherSig.preds).
# scan_from: rows below it are excluded from the rows_scanned statistic
# (but not from results) — continuation rounds re-scan part of an already
# counted window and must not double-count it.
PARAM_FIXED = 9  # w_first, w_last, row_lo, row_hi, r_hi, r_lo, e_hi, e_lo,
                 # scan_from


@dataclass(frozen=True)
class OutCol:
    col_id: int
    planes: int      # cmp-plane count (1 or 2)
    want_idx: bool   # fetch-by-index column: emit the setter's global row


@dataclass(frozen=True)
class GatherSig:
    """Static shape of the compiled gather program."""

    B: int            # blocks in run (padded)
    R: int            # rows per block
    K: int            # blocks per window
    M: int            # output capacity (rows)
    cols: tuple       # tuple[ColSig] — every column the resolve touches
    preds: tuple      # tuple[PredSig]
    apply_preds: bool
    out_cols: tuple   # tuple[OutCol]
    flat: bool = False  # single-version-per-key run (see ScanSig.flat)
    packed: bool = True  # True: device-compacted pages (top_k of the first
                         # M matches, while_loop over windows); False: one
                         # whole window emitted in place (start=-1 marks
                         # non-matches; the host compacts with numpy)


def out_layout(sig: GatherSig):
    """Column layout of the packed [M+1, W] output matrix.

    Row m < M: [start | per out col: cmp planes.., null, (idx)].
    Row M:     [count, scanned, w_end, 0...].
    Returns (W, {col_id: (cmp_off, null_off, idx_off|None)}).
    """
    off = 1
    cols = {}
    for oc in sig.out_cols:
        idx_off = off + oc.planes + 1 if oc.want_idx else None
        cols[oc.col_id] = (off, off + oc.planes, idx_off)
        off += oc.planes + 1 + (1 if oc.want_idx else 0)
    return max(off, 3), cols


def pack_params(w_first, w_last, row_lo, row_hi, read_planes, int_lits,
                f32_lits, scan_from=None):
    """Host-side mirror of the in-kernel params layout -> (i32[P], f32[F])."""
    iparams = np.array(
        [w_first, w_last, row_lo, row_hi, *read_planes,
         row_lo if scan_from is None else scan_from, *int_lits],
        dtype=np.int32)
    fparams = np.array(f32_lits if f32_lits else [0.0], dtype=np.float32)
    return iparams, fparams


def _unpack_literals(sig: GatherSig, iparams, fparams):
    off, foff = PARAM_FIXED, 0
    lits = []
    for ps in sig.preds:
        if ps.kind == "f32":
            lits.append(fparams[foff])
            foff += 1
        elif ps.kind in ("i32", "code"):
            lits.append(iparams[off])
            off += 1
        else:
            lits.append((iparams[off], iparams[off + 1]))
            off += 2
    return tuple(lits)


def _window_parts(sig, r, base, m):
    """Per-position output columns [N, W]: start (or -1 for non-match) +
    each out col's value planes / null / setter index."""
    W, _ = out_layout(sig)
    parts = [jnp.where(m, base + r["start_idx"], -1)[:, None]]
    for oc in sig.out_cols:
        cid = oc.col_id
        idx = r["col_idx"][cid]
        notnull = r["col_notnull"][cid]
        # Slice to the layout's plane count: dictionary-encoded string
        # columns decode a third (code) plane the output never carries.
        cmp = r["cmp_w"][cid][:, :oc.planes]
        parts.append(cmp if sig.flat else cmp[idx])
        parts.append((~notnull).astype(jnp.int32)[:, None])
        if oc.want_idx:
            parts.append(jnp.where(notnull, base + idx, -1)[:, None])
    vals = jnp.concatenate(parts, axis=1)
    if vals.shape[1] < W:
        vals = jnp.pad(vals, ((0, 0), (0, W - vals.shape[1])))
    return vals


def gather_rows(sig: GatherSig, run, iparams, fparams):
    """Traced program over one scan's params. Returns i32 [M+1, W]."""
    K, R, M = sig.K, sig.R, sig.M
    N = K * R
    W, col_offs = out_layout(sig)
    w_first, w_last = iparams[0], iparams[1]
    row_lo, row_hi = iparams[2], iparams[3]
    read_hi, read_lo, rexp_hi, rexp_lo = (iparams[4], iparams[5],
                                          iparams[6], iparams[7])
    scan_from = iparams[8]
    pred_literals = _unpack_literals(sig, iparams, fparams)

    def resolve(w):
        b0 = w * K
        base = b0 * R
        r = resolve_window(sig, run, b0, row_lo - base, row_hi - base,
                           read_hi, read_lo, rexp_hi, rexp_lo, pred_literals)
        gvalid = r["ridx"] < r["num_groups"]
        m = r["result"] & gvalid
        pre = r["pre_pred"] & gvalid & (r["start_idx"] >= scan_from - base)
        return r, base, m, pre

    if not sig.packed:
        # One whole window emitted in place; the host compacts (numpy
        # boolean indexing) — no device scatter/sort at all.
        r, base, m, pre = resolve(w_first)
        vals = _window_parts(sig, r, base, m)
        tail = jnp.zeros((W,), jnp.int32)
        tail = tail.at[0].set(jnp.sum(m.astype(jnp.int32)))
        tail = tail.at[1].set(jnp.sum(pre.astype(jnp.int32)))
        tail = tail.at[2].set(w_first + 1)
        return jnp.concatenate([vals, tail[None, :]], axis=0)

    buf = jnp.zeros((M + 1, W), jnp.int32)

    def cond(carry):
        w, count, scanned, buf = carry
        return (w <= w_last) & (count < M)

    def body(carry):
        w, count, scanned, buf = carry
        r, base, m, pre = resolve(w)
        # Compact to the first M matches in key order: top_k over negated
        # match positions (non-matches sort last), then a small [M] gather
        # + contiguous scatter — far cheaper than scattering all N rows.
        sel = jnp.where(m, r["ridx"], jnp.int32(N))
        k = min(M, N)
        neg_vals, top_idx = lax.top_k(-sel, k)
        valid = (-neg_vals) < N
        vals = _window_parts(sig, r, base, m)[top_idx]
        pos = jnp.where(valid, count + jnp.arange(k, dtype=jnp.int32), M + 1)
        buf = buf.at[pos].set(vals, mode="drop")
        count = count + jnp.sum(m.astype(jnp.int32))
        scanned = scanned + jnp.sum(pre.astype(jnp.int32))
        return (w + jnp.int32(1), count, scanned, buf)

    init = (w_first, jnp.int32(0), jnp.int32(0), buf)
    w_end, count, scanned, buf = lax.while_loop(cond, body, init)
    tail = jnp.zeros((W,), jnp.int32).at[0].set(count).at[1].set(
        scanned).at[2].set(w_end)
    return buf.at[M].set(tail)


@functools.lru_cache(maxsize=128)
@compile_contract("gather_batch", max_compiles=128)
def compiled_gather_batch(sig: GatherSig, G: int):
    """G scans per dispatch: (run, i32[G,P], f32[G,F]) -> i32[G, M+1, W]."""
    fn = functools.partial(gather_rows, sig)
    return jax.jit(jax.vmap(fn, in_axes=(None, 0, 0)))
