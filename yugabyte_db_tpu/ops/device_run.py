"""DeviceRun: a ColumnarRun's planes uploaded to device memory (HBM).

Reference analog: the SSTable blocks an LRU block cache holds in RAM
(src/yb/rocksdb/util/cache.cc).  A DeviceRun is the cached unit, not a
permanent resident: the TPU engine demand-uploads runs through the
residency manager (storage/residency.py) under ``--tpu_hbm_budget_bytes``
and re-uploads from the authoritative host ColumnarRun after eviction.
While resident, scans window over the planes with dynamic slices, so a
scan is pure compute with no host↔device data motion besides its scalars
and its (small) result.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import numpy as np
import jax
import jax.numpy as jnp

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.ops import encodings

if TYPE_CHECKING:  # type-only: ops never depends on storage at runtime
    from yugabyte_db_tpu.storage.columnar import ColumnarRun


def device_label(d) -> str:
    """Canonical budget-bucket name for a jax Device — the string the
    residency cache keys its per-device budget map and {device=...}
    metric labels by (storage/residency.py)."""
    return "%s:%d" % (d.platform, d.id)


def dtype_kind(dt: DataType) -> str:
    if not dt.is_fixed_width:
        return "str"  # varlen/opaque: host payload + 8-byte prefix planes
    if dt == DataType.DOUBLE:
        return "f64"
    if dt == DataType.FLOAT:
        return "f32"
    if dt.np_dtype.itemsize == 8:
        return "i64"
    return "i32"


def padded_blocks(B: int, window_blocks: int) -> int:
    """The padded block count a DeviceRun uses for a run of ``B`` blocks
    — host-side math shared with residency sizing and warmup, so cache
    keys and byte hints agree with the actual upload."""
    b = max(B, 1)
    return b + (-b) % window_blocks


def plane_nbytes(run: ColumnarRun, window_blocks: int) -> int:
    """Predicted HBM footprint of DeviceRun(run, window_blocks), computed
    from host plane shapes without uploading — the eviction hint that
    lets the residency cache make room *before* a demand upload."""
    pb = padded_blocks(run.B, window_blocks)
    # Compressed runs (--tpu_plane_encoding) upload their encoded tree;
    # the budget must account those bytes, not the logical plane bytes.
    tree = getattr(run, "encoded_arrays", lambda: None)()
    if tree is not None:
        return encodings.tree_padded_nbytes(tree, run.B, pb)

    def padded(arr) -> int:
        per_block = 1
        for d in arr.shape[1:]:
            per_block *= int(d)
        return pb * per_block * arr.dtype.itemsize

    total = sum(padded(a) for a in (
        run.valid, run.group_start, run.tomb, run.live,
        run.ht_hi, run.ht_lo, run.exp_hi, run.exp_lo))
    for col in run.cols.values():
        total += padded(col.set_) + padded(col.isnull)
        total += padded(col.cmp_planes)
        if col.arith is not None:
            total += padded(col.arith)
    return total


class DeviceRun:
    """Uploads a ColumnarRun, padding the block axis to a multiple of the
    window size so window tiling never clamps (clamped dynamic slices would
    re-read earlier blocks and double-count aggregates)."""

    def __init__(self, run: ColumnarRun, window_blocks: int, device=None):
        self.run = run
        self.K = window_blocks
        B = max(run.B, 1)
        pad = padded_blocks(run.B, window_blocks) - B
        self.B = B + pad
        self.device = device or jax.devices()[0]

        # Compressed upload: the run's cached encoded tree (if the
        # encoding flag is on) uploads leaf-by-leaf with the same block
        # padding semantics; kernels decode windows of it inline.
        tree = getattr(run, "encoded_arrays", lambda: None)()
        self.encoded = tree is not None
        if tree is not None:

            def up_leaf(leaf, ones=False):
                padded = encodings.pad_leaf(leaf, self.B, ones=ones)
                k = encodings.leaf_kind(padded)
                if k is None:
                    return jax.device_put(padded, self.device)
                return {k: {n: jax.device_put(a, self.device)
                            for n, a in padded[k].items()}}

            self.arrays = {"cols": {}}
            for name in ("valid", "group_start", "tomb", "live",
                         "ht_hi", "ht_lo", "exp_hi", "exp_lo"):
                self.arrays[name] = up_leaf(
                    tree[name], ones=(name == "group_start"))
            for cid, col in tree["cols"].items():
                self.arrays["cols"][cid] = {
                    n: up_leaf(p) for n, p in col.items()}
            return

        def pad_b(arr):
            if pad == 0:
                return arr
            shape = (pad,) + arr.shape[1:]
            return np.concatenate([arr, np.zeros(shape, dtype=arr.dtype)], axis=0)

        def up(arr):
            return jax.device_put(pad_b(arr), self.device)

        # Padding blocks: valid=False, group_start=True (each pad row its own
        # group), everything else zero.
        gs = pad_b(run.group_start)
        if pad:
            gs[B:] = True
        self.arrays = {
            "valid": up(run.valid),
            "group_start": jax.device_put(gs, self.device),
            "tomb": up(run.tomb),
            "live": up(run.live),
            "ht_hi": up(run.ht_hi),
            "ht_lo": up(run.ht_lo),
            "exp_hi": up(run.exp_hi),
            "exp_lo": up(run.exp_lo),
            "cols": {},
        }
        for cid, col in run.cols.items():
            entry = {
                "set": up(col.set_),
                "isnull": up(col.isnull),
                "cmp": up(col.cmp_planes),
            }
            if col.arith is not None:
                entry["arith"] = up(col.arith)
            self.arrays["cols"][cid] = entry

    @classmethod
    def from_arrays(cls, run: ColumnarRun, window_blocks: int, arrays,
                    device=None) -> "DeviceRun":
        """Wrap device planes produced ON DEVICE (ops.flush) instead of
        uploading host planes — the arrays must already carry this
        class's padding encoding, with the block axis padded to the
        window multiple. Lets a flush seed the residency cache without
        a host->device round trip."""
        self = cls.__new__(cls)
        self.run = run
        self.K = window_blocks
        self.B = int(arrays["valid"].shape[0])
        self.device = device or jax.devices()[0]
        self.arrays = arrays
        # The device flush emits dict leaves for string columns when the
        # encoding flag is on; everything else it scatters stays plain
        # until the run is evicted and demand re-uploads compressed.
        self.encoded = encodings.tree_encoded(arrays)
        return self

    @property
    def num_windows(self) -> int:
        return self.B // self.K

    @property
    def nbytes(self) -> int:
        """Device-resident bytes of this run's planes — the HBM
        footprint the engine accounts under the root->device MemTracker
        subtree (/memz)."""
        total = 0
        stack = [self.arrays]
        while stack:
            node = stack.pop()
            if isinstance(node, dict):
                stack.extend(node.values())
            else:
                total += int(node.size) * node.dtype.itemsize
        return total
