"""Device GROUP BY aggregates: bucket-hashed segmented reduction.

The TPC-H Q1 shape — few, low-cardinality groups over millions of rows —
runs as ONE device dispatch: a fori_loop over block windows resolves MVCC
visibility + predicates (ops.scan.resolve_window), hashes each row's
group-key planes into a fixed bucket table, and segment-sums exact
integer digit vectors per bucket. The host decodes buckets back to group
values through a representative row.

Exactness machinery:
- group keys hash over the columns' cmp planes (+ a null plane). A
  bucket also accumulates the min and max of every key plane; the host
  verifies min == max per live bucket — a hash collision (different
  groups, one bucket) fails that check and the scan falls back to the
  host path (retry-with-salt left for later; collisions are vanishingly
  rare with NB >= 16x groups). Varlen group columns are exact only when
  their values fit the 8-byte device prefix — the engine checks the
  run's recorded max length before choosing this path.
- integer sums (including product expressions like
  sum(price * (100 - disc) * (100 + tax)) over scaled-integer money
  columns) evaluate per row in base-2^16 digit vectors: the wide column
  splits into digits, each small factor (statically bounded < 2^14,
  non-negative) multiplies the digit vector with an elementwise carry
  chain, digits segment-sum per bucket, and a per-window carry
  normalization keeps everything inside int32 — bit-exact at any scale
  (the same discipline as ops.agg_fold's limb sums).

Reference analog: the grouped aggregate evaluation the reference runs
row-at-a-time inside the scan (PgsqlReadOperation::EvalAggregate,
src/yb/docdb/pgsql_operation.cc:473) — vectorized per window here.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from yugabyte_db_tpu.ops.scan import I32_MAX, I32_MIN, resolve_window
from yugabyte_db_tpu.utils.jitting import compile_contract

NUM_BUCKETS = 512
DIGITS = 8            # base-2^16 digits per integer accumulator (2^128 cap)

# factor-expression opcodes (static tuples, traced evaluation)
#   ("k", const) | ("c", col_id) | ("+"|"-"|"*", left, right)


@dataclass(frozen=True)
class GAgg:
    kind: str            # 'count' | 'sum_int' | 'sum_prod'
    col_id: int | None   # sum_int: the column; sum_prod: the wide base
    planes: int = 1      # base column plane count (1=i32, 2=i64)
    factors: tuple = ()  # sum_prod: tuple of factor expression tuples
    need_cols: tuple = ()  # col_ids whose notnull gates the row


@dataclass(frozen=True)
class GroupAggSig:
    B: int
    R: int
    K: int
    NB: int
    cols: tuple          # tuple[ColSig] — everything resolve touches
    preds: tuple
    apply_preds: bool
    flat: bool
    group_cols: tuple    # tuple[(col_id, planes)]
    aggs: tuple          # tuple[GAgg]


def _eval_factor(expr, cmp_w, idx, flat):
    """Trace a small-factor expression to a per-row int32 vector."""
    op = expr[0]
    if op == "k":
        return jnp.int32(expr[1])
    if op == "c":
        col = cmp_w[expr[1]]
        v = col[:, 0] if flat else col[idx[expr[1]], 0]
        return v
    left = _eval_factor(expr[1], cmp_w, idx, flat)
    right = _eval_factor(expr[2], cmp_w, idx, flat)
    if op == "+":
        return left + right
    if op == "-":
        return left - right
    return left * right


def _digits_mul(digits: list, f):
    """Multiply a base-2^16 digit vector by a small non-negative factor,
    renormalizing with an elementwise carry chain."""
    out = []
    carry = jnp.int32(0)
    for d in digits:
        t = d * f + carry
        out.append(t & jnp.int32(0xFFFF))
        carry = t >> jnp.int32(16)
    out.append(carry)  # f < 2^14 and digits < 2^16: one extra digit
    return out[:DIGITS]


def _base_digits(sig_planes, cmp, idx, flat):
    """Wide base column -> (digit list, value-negative flag per row)."""
    if sig_planes == 1:
        v = cmp[:, 0] if flat else cmp[idx, 0]
        neg = v < 0
        d0 = v & jnp.int32(0xFFFF)
        d1 = (v >> jnp.int32(16)) & jnp.int32(0x7FFF)
        return [d0, d1], neg
    hi = cmp[:, 0] if flat else cmp[idx, 0]
    lo = cmp[:, 1] if flat else cmp[idx, 1]
    # ordered planes: u64 = v ^ 2^63 with both words bias-flipped
    hi_u = (hi.view(jnp.uint32) ^ jnp.uint32(0x80000000)).view(jnp.int32)
    lo_u = (lo.view(jnp.uint32) ^ jnp.uint32(0x80000000)).view(jnp.int32)
    # v >= 0  <=>  top bit of u64 set  <=>  hi_u (as i32) < 0
    neg = hi_u >= 0
    v_hi = hi_u & jnp.int32(0x7FFFFFFF)  # strip the sign-bias bit
    d0 = lo_u & jnp.int32(0xFFFF)
    d1 = (lo_u >> jnp.int32(16)) & jnp.int32(0xFFFF)
    d2 = v_hi & jnp.int32(0xFFFF)
    d3 = (v_hi >> jnp.int32(16)) & jnp.int32(0x7FFF)
    return [d0, d1, d2, d3], neg


def _carry_norm(acc):
    """Carry-normalize a [NB, DIGITS] accumulator after one window."""
    for _ in range(2):
        lo = acc & jnp.int32(0xFFFF)
        hi = acc >> jnp.int32(16)
        acc = lo + jnp.concatenate(
            [jnp.zeros_like(hi[:, :1]), hi[:, :-1]], axis=1)
    return acc


def grouped_aggregate(sig: GroupAggSig, run, iparams, fparams):
    """Traced program: one dispatch over [w_first, w_last] windows.

    iparams layout: [w_first, w_last, row_lo, row_hi, r_hi, r_lo,
                     e_hi, e_lo, scan_from, *int predicate literals]
    (the row_gather params layout — reuses pack_params).

    Returns a dict of arrays keyed per output (fetched in one transfer):
      count[NB] i32, rep[NB] i32 (min matching global row, I32_MAX if
      none), keymin/keymax[NB, KP] i32 (collision check), scanned i32,
      negs i32 (any negative base seen — host falls back), and per agg
      a<i>[NB, DIGITS] i32 digit sums (count aggs: a<i>[NB] i32).
    """
    from yugabyte_db_tpu.ops.row_gather import _unpack_literals

    K, R, NB = sig.K, sig.R, sig.NB
    N = K * R
    w_first, w_last = iparams[0], iparams[1]
    row_lo, row_hi = iparams[2], iparams[3]
    read = (iparams[4], iparams[5], iparams[6], iparams[7])
    pred_literals = _unpack_literals(sig, iparams, fparams)

    KP = max(1, sum(p + 1 for _c, p in sig.group_cols))  # planes+null/col

    NBP = NB + 1  # one trash segment for non-matching rows

    def init_acc():
        acc = {
            "count": jnp.zeros((NBP,), jnp.int32),
            "rep": jnp.full((NBP,), I32_MAX, jnp.int32),
            "keymin": jnp.full((NBP, KP), I32_MAX, jnp.int32),
            "keymax": jnp.full((NBP, KP), I32_MIN, jnp.int32),
            "scanned": jnp.int32(0),
            "negs": jnp.int32(0),
        }
        for i, ag in enumerate(sig.aggs):
            if ag.kind == "count":
                acc[f"a{i}"] = jnp.zeros((NBP,), jnp.int32)
            else:
                acc[f"a{i}"] = jnp.zeros((NBP, DIGITS), jnp.int32)
                # non-null input count: SQL sum over zero inputs is NULL,
                # which a zero digit vector alone cannot distinguish.
                acc[f"n{i}"] = jnp.zeros((NBP,), jnp.int32)
        return acc

    def seg(vals, bucket, red="sum"):
        fn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
              "max": jax.ops.segment_max}[red]
        return fn(vals, bucket, num_segments=NBP)

    def body(w, acc):
        b0 = w * K
        base = b0 * R
        r = resolve_window(sig, run, b0, row_lo - base, row_hi - base,
                           *read, pred_literals)
        gvalid = r["ridx"] < r["num_groups"]
        m = r["result"] & gvalid
        cmp_w = r["cmp_w"]
        col_idx = r["col_idx"]
        col_notnull = r["col_notnull"]

        # group key planes (+ null flags) and FNV-ish bucket hash
        planes = []
        h = jnp.full((N,), 0x01000193, jnp.int32)
        for cid, np_ in sig.group_cols:
            idx = col_idx[cid]
            nn = col_notnull[cid]
            for pi in range(np_):
                p = (cmp_w[cid][:, pi] if sig.flat
                     else cmp_w[cid][idx, pi])
                p = jnp.where(nn, p, jnp.int32(0))
                planes.append(p)
                h = (h ^ p) * jnp.int32(-2128831035)
            nulls = (~nn).astype(jnp.int32)
            planes.append(nulls)
            h = (h ^ nulls) * jnp.int32(-2128831035)
        # Avalanche: mod-2^32 multiplies only push bits UP, so values
        # differing in high bits alone (e.g. short string prefixes) would
        # share the low-bit bucket; fold the high bits back down
        # (murmur3 fmix shape).
        h = h ^ ((h >> jnp.int32(16)) & jnp.int32(0xFFFF))
        h = h * jnp.int32(-2048144789)
        h = h ^ ((h >> jnp.int32(13)) & jnp.int32(0x7FFFF))
        bucket = jnp.where(m, (h & jnp.int32(0x7FFFFFFF)) % NB, NB)

        acc = dict(acc)
        acc["count"] = acc["count"] + seg(m.astype(jnp.int32), bucket)
        acc["rep"] = jnp.minimum(
            acc["rep"], seg(jnp.where(m, base + r["start_idx"], I32_MAX),
                            bucket, red="min"))
        if planes:
            key = jnp.stack(planes, axis=1)  # [N, KP]
            acc["keymin"] = jnp.minimum(
                acc["keymin"], seg(jnp.where(m[:, None], key, I32_MAX),
                                   bucket, red="min"))
            acc["keymax"] = jnp.maximum(
                acc["keymax"], seg(jnp.where(m[:, None], key, I32_MIN),
                                   bucket, red="max"))
        acc["scanned"] = acc["scanned"] + jnp.sum(
            (r["pre_pred"] & gvalid).astype(jnp.int32))

        for i, ag in enumerate(sig.aggs):
            if ag.kind == "count":
                mask = m
                if ag.col_id is not None:
                    mask = mask & col_notnull[ag.col_id]
                acc[f"a{i}"] = acc[f"a{i}"] + seg(mask.astype(jnp.int32),
                                                  bucket)
                continue
            mask = m
            for cid in ag.need_cols:
                mask = mask & col_notnull[cid]
            acc[f"n{i}"] = acc[f"n{i}"] + seg(mask.astype(jnp.int32),
                                              bucket)
            digits, neg = _base_digits(
                ag.planes, cmp_w[ag.col_id],
                None if sig.flat else col_idx[ag.col_id], sig.flat)
            acc["negs"] = acc["negs"] + jnp.sum(
                (mask & neg).astype(jnp.int32))
            for fx in ag.factors:
                f = _eval_factor(fx, cmp_w,
                                 None if sig.flat else col_idx, sig.flat)
                # Factors are statically bounded |f| < 2^14 but may still
                # be negative at runtime (dtype ranges are conservative);
                # a negative factor invalidates the digit math — counted
                # here, and the host falls back when any were seen.
                acc["negs"] = acc["negs"] + jnp.sum(
                    (mask & (f < 0)).astype(jnp.int32))
                digits = _digits_mul(digits, f)
            dg = jnp.stack(
                digits + [jnp.zeros_like(digits[0])] *
                (DIGITS - len(digits)), axis=1)
            dg = jnp.where(mask[:, None], dg, 0)
            acc[f"a{i}"] = _carry_norm(acc[f"a{i}"] + seg(dg, bucket))
        return acc

    return lax.fori_loop(w_first, w_last + 1, body, init_acc())


@functools.lru_cache(maxsize=64)
@compile_contract("grouped_aggregate", max_compiles=64)
def compiled_grouped(sig: GroupAggSig):
    return jax.jit(functools.partial(grouped_aggregate, sig))
