"""Loop-free full-run aggregate over a MULTI-VERSION run: segmented
scans instead of a serialized window walk.

ops.flat_fold made flat runs bandwidth-bound; this module does the same
for segmented MVCC state. The key layout invariant — a key's versions
are contiguous, newest-first, and never span a block (storage.columnar)
— turns every per-group MVCC question into a segmented scan along the
row axis of the [B, R] planes, which XLA lowers to log-depth fused
passes over the whole run:

- newest visible tombstone per group: prefix + suffix segmented
  first-found scans over (visible & tomb) carrying the ht planes;
- per-column latest alive setter: ONE suffix segmented first-found scan
  per column carrying the value planes — evaluated at each group's
  first row (the group representative), the suffix IS the whole group;
- group aggregates: representative rows then ride the exact flat limb
  machinery (flat_fold) with mask = group_start & exists & predicates.

Equal-hybrid-time DELETE+write pairs shadow correctly regardless of
intra-tie layout order because the tombstone reduction combines both
scan directions (prefix ∪ suffix covers the whole group).

Reference analog: the same merge-on-read the windowed fold implements
(DocRowwiseIterator semantics) at memory-roofline shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from yugabyte_db_tpu.ops import encodings
from yugabyte_db_tpu.ops import flat_fold
from yugabyte_db_tpu.ops import scan as dscan
from yugabyte_db_tpu.ops.scan import I32_MIN, le2
from yugabyte_db_tpu.utils.jitting import compile_contract


def supports(sig: dscan.ScanSig) -> bool:
    if sig.R > flat_fold.MAX_R or sig.B > flat_fold.MAX_B:
        return False
    if any(ps.kind not in ("i32", "i64", "f64", "code")
           for ps in sig.preds):
        return False
    for ag in sig.aggs:
        if ag.fn not in ("count", "sum", "min", "max"):
            return False
    return True


def _seg_first(found, payload, group_start, last_found: bool):
    """Segmented first-found scan along axis=1.

    found: [B, R] bool; payload: pytree of [B, R] arrays. Returns
    (found', payload') where each position holds the first found
    element of its segment-prefix (last_found=False) or the LAST found
    of the prefix (last_found=True — used via flipping for suffix
    scans). Segments restart where group_start is True."""
    def op(a, b):
        a_found, a_g, a_p = a
        b_found, b_g, b_p = b
        # b is the element/aggregate closer to the scan end. If b
        # restarts the segment, a's contribution is discarded.
        if last_found:
            take_b = b_g | b_found
        else:
            take_b = b_g | ~a_found

        def sel(x, y):
            m = take_b
            while m.ndim < x.ndim:  # plane leaves carry a trailing axis
                m = m[..., None]
            return jnp.where(m, y, x)

        out_found = jnp.where(b_g, b_found, a_found | b_found)
        return out_found, a_g | b_g, jax.tree.map(sel, a_p, b_p)

    f, _g, p = lax.associative_scan(
        op, (found, group_start, payload), axis=1)
    return f, p


def _suffix_first(found, payload, group_start):
    """At each row: the first-in-forward-order found element among the
    rows of ITS group at-or-after it. At a group's first row this is the
    group's overall first found — the 'latest version' selector."""
    # Reversed coordinates: suffix -> prefix, and the forward-first
    # becomes the LAST found of the reversed prefix. Segment restarts in
    # reversed order happen at original group ENDS (the row before the
    # next group_start).
    flip = lambda x: jnp.flip(x, axis=1)
    group_end = jnp.concatenate(
        [group_start[:, 1:], jnp.ones_like(group_start[:, :1])], axis=1)
    f, p = _seg_first(flip(found), jax.tree.map(flip, payload),
                      flip(group_end), last_found=True)
    return flip(f), jax.tree.map(flip, p)


@functools.lru_cache(maxsize=128)
@compile_contract("seg_aggregate", max_compiles=128)
def compiled_seg_aggregate(sig: dscan.ScanSig):
    """jit(run, row_lo, row_hi, read_hi, read_lo, rexp_hi, rexp_lo,
    pred_lits) -> (ivec, fvec) in agg_fold's packed format; exact
    equivalence with the windowed fold on any multi-version run."""
    assert supports(sig)

    def fn(run, row_lo, row_hi, read_hi, read_lo, rexp_hi, rexp_lo,
           pred_lits):
        run = encodings.decode_run(run)
        valid = run["valid"]
        gs = run["group_start"]
        ht_hi, ht_lo = run["ht_hi"], run["ht_lo"]
        visible = valid & le2(ht_hi, ht_lo, read_hi, read_lo)
        expired = le2(run["exp_hi"], run["exp_lo"], rexp_hi, rexp_lo)
        tomb = run["tomb"]

        # 1. Newest visible tombstone per group (ht-desc layout: the
        # first visible tombstone in forward order has the max ht).
        # Prefix pass covers older rows, suffix pass covers newer/tied
        # rows; lex-max of both = the group's tombstone everywhere.
        vt = visible & tomb
        tf, tf_p = _seg_first(vt, (ht_hi, ht_lo), gs, last_found=False)
        tb, tb_p = _suffix_first(vt, (ht_hi, ht_lo), gs)
        tf_hi = jnp.where(tf, tf_p[0], I32_MIN)
        tf_lo = jnp.where(tf, tf_p[1], I32_MIN)
        tb_hi = jnp.where(tb, tb_p[0], I32_MIN)
        tb_lo = jnp.where(tb, tb_p[1], I32_MIN)
        use_b = (tb_hi > tf_hi) | ((tb_hi == tf_hi) & (tb_lo > tf_lo))
        t_hi = jnp.where(use_b, tb_hi, tf_hi)
        t_lo = jnp.where(use_b, tb_lo, tf_lo)
        has_tomb = tf | tb
        shadowed = has_tomb & le2(ht_hi, ht_lo, t_hi, t_lo)
        alive = visible & ~tomb & ~shadowed

        # 2. Group-level liveness + per-column latest values at the
        # group representative (= group_start rows; their suffix is the
        # whole group).
        live_any, _ = _suffix_first(
            alive & run["live"] & ~expired,
            (jnp.zeros_like(ht_hi),), gs)
        col_notnull = {}
        col_val = {}
        for cs in sig.cols:
            c = run["cols"][cs.col_id]
            cand = alive & c["set"]
            payload = {"null": c["isnull"], "exp": expired,
                       "cmp": c["cmp"]}
            if "arith" in c:
                payload["arith"] = c["arith"]
            has, latest = _suffix_first(cand, payload, gs)
            col_notnull[cs.col_id] = has & ~latest["null"] & ~latest["exp"]
            col_val[cs.col_id] = latest

        return flat_fold.finish_groups(sig, gs, live_any, col_notnull,
                                       col_val, row_lo, row_hi, pred_lits)

    return jax.jit(fn)
