"""Loop-free full-run aggregate over a FLAT run: one fused XLA program.

The windowed fold (agg_fold.compiled_full_aggregate) walks a run with a
fori_loop of small dynamic-slice windows — correct for segmented MVCC
state threading, but the serialized tiny iterations leave the MXU/VPU
idle (measured ~1 GB/s of HBM traffic at 17M rows). A flat run (one
version per key — the common post-compaction shape) needs no cross-row
state at all, so the whole resolve + predicate + aggregate evaluates as
ONE elementwise/reduction program over the full [B, R] planes and XLA
tiles it at memory speed (measured ~130 GB/s / >5G rows/s on the same
shape — ~180x the windowed fold).

Exact integer sums without int64: every 32-bit plane splits into two
16-bit limbs; per-BLOCK limb sums stay below 2^31 for R <= 2^15-1, and
a second decompose+sum over the block axis stays exact for B <= 2^14 —
the program returns a handful of scalars, packed into agg_fold's
(ivec, fvec) format so the engine's unpack/finalize path is shared.

Reference analog: the same per-tablet aggregate pushdown
(PgsqlReadOperation::EvalAggregate, src/yb/docdb/pgsql_operation.cc:473)
— this is its bandwidth-roofline form.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np
from jax import lax

from yugabyte_db_tpu.ops import agg_fold
from yugabyte_db_tpu.ops import encodings
from yugabyte_db_tpu.ops import scan as dscan
from yugabyte_db_tpu.ops.scan import le2
from yugabyte_db_tpu.utils.jitting import compile_contract

# np scalars, not jnp: module import must not touch the backend.
I32_MIN = np.int32(-(1 << 31))
I32_MAX = np.int32((1 << 31) - 1)
_BIAS = np.int32(-(1 << 31))  # bit pattern 0x80000000

MAX_R = (1 << 15) - 1   # block limb sums stay < 2^31
MAX_B = 1 << 14         # second-stage limb sums stay < 2^31


def supports(sig: dscan.ScanSig) -> bool:
    """Eligibility: flat run within the exact-limb shape bounds, exact
    predicate kinds only (the callers' device-exact set)."""
    if not sig.flat or sig.R > MAX_R or sig.B > MAX_B:
        return False
    if any(ps.kind not in ("i32", "i64", "f64", "code")
           for ps in sig.preds):
        return False
    for ag in sig.aggs:
        if ag.fn not in ("count", "sum", "min", "max"):
            return False
    return True


def _limb_scalars(masked_u16, pos, digits):
    """Exactly sum a [B, R] int32 array of values in [0, 0xFFFF] and add
    the total into the base-2^16 digit accumulation at digit ``pos``.
    Two-stage: per-block int32 sums, then decompose and sum over blocks.
    """
    s1 = jnp.sum(masked_u16, axis=1, dtype=jnp.int32)          # [B] < 2^31
    lo = jnp.sum(s1 & jnp.int32(0xFFFF), dtype=jnp.int32)      # < B*2^16
    hi = jnp.sum(lax.shift_right_logical(s1, 16), dtype=jnp.int32)
    digits[pos] = digits[pos] + lo
    digits[pos + 1] = digits[pos + 1] + hi
    return digits


def _masked_plane_limbs(plane, m_i32, digits, base_pos):
    """Add a biased-u32 plane's masked exact sum into the digits."""
    u = plane ^ _BIAS  # biased: unsigned order == signed plane order
    lo16 = (u & jnp.int32(0xFFFF)) * m_i32
    hi16 = lax.shift_right_logical(u, 16) * m_i32
    digits = _limb_scalars(lo16, base_pos, digits)
    digits = _limb_scalars(hi16, base_pos + 1, digits)
    return digits


def _eval_pred_flat(ps: dscan.PredSig, cmp, arith, lit):
    """Elementwise exact predicate over full planes (i32/i64/f64)."""
    if ps.kind == "i32":
        v = cmp[..., 0]
        return {"=": v == lit, "!=": v != lit, "<": v < lit,
                "<=": v <= lit, ">": v > lit, ">=": v >= lit}[ps.op]
    if ps.kind == "code":
        # Promoted string predicate: exact compare on the decoded
        # dictionary-code plane (see ops.scan._eval_pred).
        v = cmp[..., 2]
        return {"=": v == lit, "!=": v != lit, "<": v < lit,
                "<=": v <= lit, ">": v > lit, ">=": v >= lit}[ps.op]
    hi, lo = cmp[..., 0], cmp[..., 1]
    lhi, llo = lit[0], lit[1]
    eq = (hi == lhi) & (lo == llo)
    lt = (hi < lhi) | ((hi == lhi) & (lo < llo))
    return {"=": eq, "!=": ~eq, "<": lt, "<=": lt | eq,
            ">": ~(lt | eq), ">=": ~lt}[ps.op]


def finish_groups(sig: dscan.ScanSig, gs, live_any, col_notnull, col_val,
                  row_lo, row_hi, pred_lits):
    """Shared group-representative accumulation tail of the multi-version
    folds (seg_fold / lookback_fold): exists fold, range/predicate result
    mask at each group's first row, and exact count/sum/min/max packing.
    ``col_val`` holds each column's latest-visible payload {null, exp,
    cmp[, arith]} evaluated at the representative row."""
    from jax import lax

    exists = live_any
    for cs in sig.cols:
        exists = exists | col_notnull[cs.col_id]

    B, R = gs.shape
    gidx = (lax.broadcasted_iota(jnp.int32, (B, R), 0) * R
            + lax.broadcasted_iota(jnp.int32, (B, R), 1))
    result = gs & exists & (gidx >= row_lo) & (gidx < row_hi)
    for i, ps in enumerate(sig.preds):
        latest = col_val[ps.col_id]
        result = result & col_notnull[ps.col_id] & \
            _eval_pred_flat(ps, latest["cmp"], latest.get("arith"),
                            pred_lits[i])

    scanned = jnp.sum(result, dtype=jnp.int32)
    acc = []
    for ag in sig.aggs:
        if ag.fn == "count":
            m = (result if ag.col_id is None
                 else result & col_notnull[ag.col_id])
            acc.append({"count": jnp.sum(m, dtype=jnp.int32)})
            continue
        latest = col_val[ag.col_id]
        m = result & col_notnull[ag.col_id]
        n = jnp.sum(m, dtype=jnp.int32)
        if ag.fn == "sum":
            if ag.kind in ("f32", "f64"):
                s1 = jnp.sum(jnp.where(m, latest["arith"], 0.0), axis=1)
                acc.append({"fsum": jnp.sum(s1),
                            "fcomp": jnp.float32(0), "n": n})
            else:
                m_i32 = m.astype(jnp.int32)
                digits = [jnp.int32(0)] * agg_fold.DIGITS
                if ag.kind == "i32":
                    digits = _masked_plane_limbs(
                        latest["cmp"][..., 0], m_i32, digits, 0)
                else:
                    digits = _masked_plane_limbs(
                        latest["cmp"][..., 1], m_i32, digits, 0)
                    digits = _masked_plane_limbs(
                        latest["cmp"][..., 0], m_i32, digits, 2)
                acc.append({"digits": jnp.stack(digits), "n": n})
        else:
            is_max = ag.fn == "max"
            red = jnp.max if is_max else jnp.min
            if ag.kind == "f32":
                fill = jnp.float32(-jnp.inf if is_max else jnp.inf)
                acc.append({"fext": red(
                    jnp.where(m, latest["arith"], fill)), "n": n})
            elif ag.kind == "i32":
                fill = I32_MIN if is_max else I32_MAX
                acc.append({"ext": red(jnp.where(
                    m, latest["cmp"][..., 0], fill)), "n": n})
            else:
                fill = I32_MIN if is_max else I32_MAX
                hi = latest["cmp"][..., 0]
                lo = latest["cmp"][..., 1]
                ext_hi = red(jnp.where(m, hi, fill))
                ext_lo = red(jnp.where(m & (hi == ext_hi), lo, fill))
                acc.append({"ext_hi": ext_hi, "ext_lo": ext_lo, "n": n})
    return agg_fold.pack(sig.aggs, acc, scanned)


@functools.lru_cache(maxsize=128)
@compile_contract("flat_aggregate", max_compiles=128)
def compiled_flat_aggregate(sig: dscan.ScanSig):
    """jit(run, row_lo, row_hi, read_hi, read_lo, rexp_hi, rexp_lo,
    pred_lits) -> (ivec, fvec) in agg_fold's packed format."""
    assert supports(sig)
    import jax

    def fn(run, row_lo, row_hi, read_hi, read_lo, rexp_hi, rexp_lo,
           pred_lits):
        # Encoded leaves decode here as transients fused into the one
        # elementwise program — HBM holds only the compressed planes.
        run = encodings.decode_run(run)
        valid = run["valid"]
        visible = valid & le2(run["ht_hi"], run["ht_lo"], read_hi, read_lo)
        expired = le2(run["exp_hi"], run["exp_lo"], rexp_hi, rexp_lo)
        alive = visible & ~run["tomb"]
        not_expired = ~expired
        exists = alive & run["live"] & not_expired
        notnull = {}
        for cs in sig.cols:
            c = run["cols"][cs.col_id]
            nn = alive & c["set"] & ~c["isnull"] & not_expired
            notnull[cs.col_id] = nn
            exists = exists | nn
        B, R = valid.shape
        gidx = (lax.broadcasted_iota(jnp.int32, (B, R), 0) * R
                + lax.broadcasted_iota(jnp.int32, (B, R), 1))
        pre_pred = exists & (gidx >= row_lo) & (gidx < row_hi)
        result = pre_pred
        for i, ps in enumerate(sig.preds):
            c = run["cols"][ps.col_id]
            result = result & notnull[ps.col_id] & _eval_pred_flat(
                ps, c["cmp"], c.get("arith"), pred_lits[i])

        # Match the windowed fold's statistic: result rows scanned
        # (agg_fold.fold_window counts parts["result"]).
        scanned = jnp.sum(result, dtype=jnp.int32)
        acc = []
        for ag in sig.aggs:
            if ag.fn == "count":
                m = (result if ag.col_id is None
                     else result & notnull[ag.col_id])
                acc.append({"count": jnp.sum(m, dtype=jnp.int32)})
                continue
            c = run["cols"][ag.col_id]
            m = result & notnull[ag.col_id]
            n = jnp.sum(m, dtype=jnp.int32)
            if ag.fn == "sum":
                if ag.kind in ("f32", "f64"):
                    # Two-stage f32 sum of the arithmetic plane (block
                    # partials then block-axis sum); fcomp carries 0 —
                    # accuracy matches the windowed Kahan path to the
                    # tested tolerances.
                    s1 = jnp.sum(jnp.where(m, c["arith"], 0.0), axis=1)
                    acc.append({"fsum": jnp.sum(s1),
                                "fcomp": jnp.float32(0), "n": n})
                else:
                    m_i32 = m.astype(jnp.int32)
                    digits = [jnp.int32(0)] * agg_fold.DIGITS
                    if ag.kind == "i32":
                        digits = _masked_plane_limbs(
                            c["cmp"][..., 0], m_i32, digits, 0)
                    else:  # i64: lo plane at digit 0, hi plane at 2
                        digits = _masked_plane_limbs(
                            c["cmp"][..., 1], m_i32, digits, 0)
                        digits = _masked_plane_limbs(
                            c["cmp"][..., 0], m_i32, digits, 2)
                    acc.append({"digits": jnp.stack(digits), "n": n})
            else:
                is_max = ag.fn == "max"
                if ag.kind == "f32":
                    fill = jnp.float32(-jnp.inf if is_max else jnp.inf)
                    red = jnp.max if is_max else jnp.min
                    acc.append({"fext": red(jnp.where(m, c["arith"], fill)),
                                "n": n})
                elif ag.kind == "i32":
                    fill = I32_MIN if is_max else I32_MAX
                    red = jnp.max if is_max else jnp.min
                    acc.append({"ext": red(
                        jnp.where(m, c["cmp"][..., 0], fill)), "n": n})
                else:
                    fill = I32_MIN if is_max else I32_MAX
                    red = jnp.max if is_max else jnp.min
                    hi = c["cmp"][..., 0]
                    lo = c["cmp"][..., 1]
                    ext_hi = red(jnp.where(m, hi, fill))
                    ext_lo = red(jnp.where(m & (hi == ext_hi), lo, fill))
                    acc.append({"ext_hi": ext_hi, "ext_lo": ext_lo,
                                "n": n})
        return agg_fold.pack(sig.aggs, acc, scanned)

    return jax.jit(fn)
