"""Compressed plane encodings for device-resident columnar runs.

Reference analog: the block-based SSTable keeps blocks compressed in the
block cache and only materialises restart-interval rows on read
(src/yb/rocksdb/table/block_builder.cc prefix compression;
src/yb/rocksdb/table/block_based_table_reader.cc). Here the unit is the
column *plane* instead of the row block: each [B, R] (or [B, R, P])
host plane may upload in one of five compressed leaf forms, and the
scan/fold kernels decode windows of them inline — HBM holds only the
compressed bytes, decoded values exist as register/vmem transients
inside the fused XLA program.

Leaf forms (a leaf is either a bare ndarray — "plain" — or a
single-key dict naming the encoding):

  {"bits":    {"bw": i32 [B, R//32]}}          bool plane, 1 bit/row
  {"const":   {"cval": [1, 1, ...]}}           whole-plane constant
  {"delta16": {"dbase": i32 [B, 1, ...],
               "doff": u16 [B, R, ...]}}       per-block base + u16 offset
  {"rle":     {"rid": i16 [B, R],
               "rvals": [B, Rc, ...]}}         per-block run id -> value
  {"dict":    {"codes": u16 [B, R],
               "dhi": i32 [D], "dlo": i32 [D]}} sorted per-run dictionary

Encoding invariants the kernels rely on:

- "valid" and "group_start" are only ever bits or plain — never const —
  so DeviceRun block padding can force valid=False / group_start=True
  word patterns on pad blocks exactly as the plain format does.
- A dict is the SORTED unique full (not prefix) values of the column's
  set, non-null rows; its last slot (index D-1) is reserved for
  absent rows (unset or NULL) and decodes to prefix planes (0, 0) —
  byte-identical to the zero-initialised planes those rows hold in the
  plain format. Sortedness makes the code order the value order, so
  range predicates translate to code-range compares ("code" preds).
- A dict cmp leaf decodes to THREE planes [.., 3]: the two prefix
  planes (byte-identical to the plain path) plus the int32 code plane
  that promoted "code" predicates compare against.
- rle uses one run id per block row shared by every plane of the leaf
  (a run breaks where ANY plane changes), so multi-plane values decode
  with a single gather index.

Selection (encode_int_plane / encode_bool_plane / encode_float_plane)
is a cheap stats pass: const when one distinct value, else the smaller
of delta16 (every block's span <= 65535) and rle (max runs/block <=
R//8), else plain. Pathological planes transparently stay plain — the
fallback matrix lives in docs/columnar-encoding.md.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

ENC_KINDS = ("bits", "const", "delta16", "rle", "dict")
_ENC_SET = frozenset(ENC_KINDS)

# Dictionary capacity: codes are uint16 and one slot is reserved for the
# absent (unset/NULL) rows, so at most 2^16 - 1 distinct values.
DICT_MAX_VALUES = (1 << 16) - 1
# rle is eligible when the worst block has at most R // RLE_MAX_RUN_DIV
# runs (denser planes gain too little over delta16/plain).
RLE_MAX_RUN_DIV = 8


def pow2_bucket(n: int) -> int:
    """Round a count up to the next power of two (>= 1) so encoded
    widths land in a small set of static shapes (bounded retraces)."""
    return 1 << max(0, int(n - 1).bit_length())


def leaf_kind(x):
    """Encoding kind of a plane leaf, or None for a plain ndarray.

    Encoded leaves are single-key dicts keyed by the kind; every other
    dict in a run tree (column entries, the cols map) has multiple keys
    or non-kind keys, so this never misfires on tree structure.
    """
    if isinstance(x, dict) and len(x) == 1:
        k = next(iter(x))
        if k in _ENC_SET:
            return k
    return None


def leaf_dims(leaf):
    """(B, R) of a leaf, or None when the leaf carries no block dim
    (const)."""
    k = leaf_kind(leaf)
    if k is None:
        return leaf.shape[0], leaf.shape[1]
    e = leaf[k]
    if k == "bits":
        return e["bw"].shape[0], e["bw"].shape[1] * 32
    if k == "delta16":
        return e["doff"].shape[0], e["doff"].shape[1]
    if k == "rle":
        return e["rid"].shape[0], e["rid"].shape[1]
    if k == "dict":
        return e["codes"].shape[0], e["codes"].shape[1]
    return None


def tree_encoded(run) -> bool:
    """True when any leaf of a run-plane tree is encoded."""
    for name, leaf in run.items():
        if name == "cols":
            for col in leaf.values():
                for p in col.values():
                    if leaf_kind(p) is not None:
                        return True
        elif leaf_kind(leaf) is not None:
            return True
    return False


def tree_dims(run):
    """(B, R) of a run-plane tree; "valid" always carries block dims."""
    d = leaf_dims(run["valid"])
    if d is None:  # pragma: no cover - valid is never const
        raise ValueError("run tree has no block-dimensioned valid plane")
    return d


# ---------------------------------------------------------------------------
# host-side encoders (numpy; run once per ColumnarRun at upload time)
# ---------------------------------------------------------------------------


def _as_cmp_words(p):
    """Bitwise view for value comparisons: floats compare as their bit
    patterns (NaN == NaN, -0.0 != 0.0) so decode is byte-identical."""
    if p.dtype.kind == "f":
        return p.view(np.int32 if p.dtype.itemsize == 4 else np.int64)
    return p


def encode_bits(plane):
    """[B, R] bool -> bits leaf (R must be a multiple of 32)."""
    B, R = plane.shape
    if R % 32 or plane.size == 0:
        return None
    w = plane.reshape(B, R // 32, 32).astype(np.uint32)
    bw = (w << np.arange(32, dtype=np.uint32)).sum(
        axis=-1, dtype=np.uint32)
    return {"bits": {"bw": bw.view(np.int32)}}


def encode_const(plane):
    """Whole-plane constant -> const leaf (cval keeps the dtype)."""
    if plane.size == 0:
        return None
    w = _as_cmp_words(plane)
    if not (w == w.reshape(-1, *w.shape[2:])[:1]).all():
        return None
    return {"const": {"cval": np.ascontiguousarray(plane[:1, :1])}}


def encode_delta16(plane):
    """Per-block int32 base + uint16 offsets; eligible when every
    block's span fits 16 bits (span computed in int64 — int32 max-min
    overflows)."""
    if plane.size == 0 or plane.dtype.kind not in "iu":
        return None
    p64 = plane.astype(np.int64)
    base = p64.min(axis=1, keepdims=True)
    span = (p64.max(axis=1, keepdims=True) - base).max(initial=0)
    if span > 0xFFFF:
        return None
    return {"delta16": {"dbase": base.astype(np.int32),
                        "doff": (p64 - base).astype(np.uint16)}}


def encode_rle(plane):
    """Per-block run-length leaf: rid[b, r] indexes rvals[b]; a run
    breaks where ANY plane of the leaf changes."""
    if plane.size == 0:
        return None
    B, R = plane.shape[0], plane.shape[1]
    w = _as_cmp_words(plane).reshape(B, R, -1)
    brk = np.ones((B, R), np.bool_)
    brk[:, 1:] = (w[:, 1:] != w[:, :-1]).any(axis=-1)
    rid = brk.cumsum(axis=1, dtype=np.int64) - 1
    nruns = int(rid[:, -1].max()) + 1
    if nruns > max(1, R // RLE_MAX_RUN_DIV):
        return None
    Rc = pow2_bucket(nruns)
    v3 = plane.reshape(B, R, -1)
    rvals = np.zeros((B, Rc, v3.shape[2]), plane.dtype)
    bi, ri = np.nonzero(brk)
    rvals[bi, rid[bi, ri]] = v3[bi, ri]
    if plane.ndim == 2:
        rvals = rvals[:, :, 0]
    return {"rle": {"rid": rid.astype(np.int16),
                    "rvals": np.ascontiguousarray(rvals)}}


def dict_leaf(codes, dhi, dlo):
    """Assemble a dict leaf. ``codes`` [B, R] row codes (absent rows
    already set to len(dhi) - 1); ``dhi``/``dlo`` the prefix planes of
    the sorted dictionary, absent slot zeroed, padded to a pow2 width."""
    return {"dict": {"codes": codes.astype(np.uint16),
                     "dhi": dhi.astype(np.int32),
                     "dlo": dlo.astype(np.int32)}}


def leaf_nbytes(leaf) -> int:
    """Encoded byte size of a leaf as uploaded (unpadded)."""
    k = leaf_kind(leaf)
    if k is None:
        return leaf.nbytes
    return sum(a.nbytes for a in leaf[k].values())


def _pick_smaller(plane, candidates):
    cands = [c for c in candidates if c is not None]
    if not cands:
        return plane
    best = min(cands, key=leaf_nbytes)
    return best if leaf_nbytes(best) < plane.nbytes else plane


def encode_bool_plane(plane):
    """bool planes bit-pack (never const: valid/group_start padding
    semantics depend on per-block words)."""
    e = encode_bits(np.ascontiguousarray(plane))
    return plane if e is None else e


def encode_int_plane(plane):
    """int32 [B, R(, P)] -> const | smaller of delta16/rle | plain."""
    c = encode_const(plane)
    if c is not None:
        return c
    return _pick_smaller(plane, [encode_delta16(plane),
                                 encode_rle(plane)])


def encode_float_plane(plane):
    """f32 arith planes: const | rle | plain (no delta on floats)."""
    c = encode_const(plane)
    if c is not None:
        return c
    return _pick_smaller(plane, [encode_rle(plane)])


# ---------------------------------------------------------------------------
# accounting (budget gates, metrics)
# ---------------------------------------------------------------------------


def leaf_padded_nbytes(leaf, B: int, pad_b: int) -> int:
    """Device byte size of a leaf once its block axis pads to pad_b.

    Block-dimensioned arrays scale by pad_b / B; const cval and dict
    dhi/dlo have no block axis and upload once.
    """
    k = leaf_kind(leaf)
    if k is None:
        per_block = int(np.prod(leaf.shape[1:], dtype=np.int64))
        return per_block * leaf.dtype.itemsize * pad_b
    total = 0
    no_block = {"const": ("cval",), "dict": ("dhi", "dlo")}.get(k, ())
    for name, a in leaf[k].items():
        if name in no_block:
            total += a.nbytes
        else:
            per_block = int(np.prod(a.shape[1:], dtype=np.int64))
            total += per_block * a.dtype.itemsize * pad_b
    return total


def tree_padded_nbytes(tree, B: int, pad_b: int) -> int:
    total = 0
    for name, leaf in tree.items():
        if name == "cols":
            for col in leaf.values():
                for p in col.values():
                    total += leaf_padded_nbytes(p, B, pad_b)
        else:
            total += leaf_padded_nbytes(leaf, B, pad_b)
    return total


def _leaf_logical_nbytes(leaf, B: int, R: int) -> int:
    """Plain-format bytes the leaf replaces (dict: the two int32 prefix
    planes; bits: one bool byte per row)."""
    k = leaf_kind(leaf)
    if k is None:
        return leaf.nbytes
    if k == "bits":
        return B * R
    if k == "dict":
        return B * R * 8
    if k == "const":
        cv = leaf[k]["cval"]
        return B * R * int(np.prod(cv.shape[2:], dtype=np.int64)) * \
            cv.dtype.itemsize
    if k == "delta16":
        d = leaf[k]["doff"]
        return B * R * int(np.prod(d.shape[2:], dtype=np.int64)) * 4
    rv = leaf[k]["rvals"]
    return B * R * int(np.prod(rv.shape[2:], dtype=np.int64)) * \
        rv.dtype.itemsize


def tree_stats(tree) -> dict:
    """Per-encoding byte accounting for metrics/memz: {"by_encoding":
    {kind: encoded_bytes}, "encoded_bytes", "logical_bytes"}."""
    B, R = tree_dims(tree)
    by = {}
    logical = 0

    def one(leaf):
        nonlocal logical
        k = leaf_kind(leaf) or "plain"
        by[k] = by.get(k, 0) + leaf_nbytes(leaf)
        logical += _leaf_logical_nbytes(leaf, B, R)

    for name, leaf in tree.items():
        if name == "cols":
            for col in leaf.values():
                for p in col.values():
                    one(p)
        else:
            one(leaf)
    return {"by_encoding": by, "encoded_bytes": sum(by.values()),
            "logical_bytes": logical}


# ---------------------------------------------------------------------------
# device-side block padding (DeviceRun upload)
# ---------------------------------------------------------------------------


def pad_leaf(leaf, pad_b: int, ones: bool = False):
    """Pad a leaf's block axis to pad_b blocks with the plain format's
    padding values: False/0 everywhere, except ``ones`` (group_start)
    pads all-True words so pad rows are each their own group."""
    k = leaf_kind(leaf)
    if k is None:
        B = leaf.shape[0]
        if pad_b <= B:
            return leaf
        fill = np.ones if ones else np.zeros
        pad = fill((pad_b - B,) + leaf.shape[1:], leaf.dtype)
        return np.concatenate([leaf, pad], axis=0)
    e = dict(leaf[k])
    if k == "bits":
        B = e["bw"].shape[0]
        if pad_b > B:
            fill = np.full((pad_b - B,) + e["bw"].shape[1:], -1,
                           np.int32) if ones else \
                np.zeros((pad_b - B,) + e["bw"].shape[1:], np.int32)
            e["bw"] = np.concatenate([e["bw"], fill], axis=0)
    elif k == "delta16":
        B = e["doff"].shape[0]
        if pad_b > B:
            for n in ("dbase", "doff"):
                pad = np.zeros((pad_b - B,) + e[n].shape[1:], e[n].dtype)
                e[n] = np.concatenate([e[n], pad], axis=0)
    elif k == "rle":
        B = e["rid"].shape[0]
        if pad_b > B:
            for n in ("rid", "rvals"):
                pad = np.zeros((pad_b - B,) + e[n].shape[1:], e[n].dtype)
                e[n] = np.concatenate([e[n], pad], axis=0)
    elif k == "dict":
        B = e["codes"].shape[0]
        if pad_b > B:
            # pad rows decode the absent slot: prefix planes (0, 0),
            # matching the plain format's zeroed pad rows.
            absent = e["dhi"].shape[0] - 1
            pad = np.full((pad_b - B,) + e["codes"].shape[1:], absent,
                          np.uint16)
            e["codes"] = np.concatenate([e["codes"], pad], axis=0)
    return {k: e}


# ---------------------------------------------------------------------------
# device-side decode (traced inside the scan/fold programs)
# ---------------------------------------------------------------------------


def _slice_b(arr, b0, K):
    return lax.dynamic_slice_in_dim(arr, b0, K, axis=0)


def wplane(leaf, b0, K: int, R: int):
    """Decode a K-block window of a leaf to the flat [K*R, ...] layout
    ops.scan's plain-plane windowing produces. Dispatch is on pytree
    STRUCTURE, so each branch is resolved at trace time."""
    k = leaf_kind(leaf)
    if k is None:
        return _slice_b(leaf, b0, K).reshape((K * R,) + leaf.shape[2:])
    e = leaf[k]
    if k == "bits":
        w = _slice_b(e["bw"], b0, K)
        bits = (w[:, :, None] >> jnp.arange(32, dtype=jnp.int32)) \
            & jnp.int32(1)
        return bits.astype(jnp.bool_).reshape(K * R)
    if k == "const":
        cv = e["cval"]
        tail = cv.shape[2:]
        return jnp.broadcast_to(jnp.reshape(cv, (1,) + tail),
                                (K * R,) + tail)
    if k == "delta16":
        base = _slice_b(e["dbase"], b0, K)
        off = _slice_b(e["doff"], b0, K).astype(jnp.int32)
        return (base + off).reshape((K * R,) + e["doff"].shape[2:])
    if k == "rle":
        Rc = e["rvals"].shape[1]
        rid = _slice_b(e["rid"], b0, K).reshape(K * R).astype(jnp.int32)
        rv = _slice_b(e["rvals"], b0, K)
        flat = rv.reshape((K * Rc,) + rv.shape[2:])
        idx = rid + Rc * (jnp.arange(K * R, dtype=jnp.int32)
                          // jnp.int32(R))
        return jnp.take(flat, idx, axis=0)
    # dict: prefix planes + the code plane for promoted predicates
    codes = _slice_b(e["codes"], b0, K).reshape(K * R).astype(jnp.int32)
    return jnp.stack([jnp.take(e["dhi"], codes),
                      jnp.take(e["dlo"], codes), codes], axis=-1)


def decode_leaf(leaf, B: int, R: int):
    """Full-plane decode back to the [B, R, ...] layout."""
    if leaf_kind(leaf) is None:
        return leaf
    flat = wplane(leaf, 0, B, R)
    return flat.reshape((B, R) + flat.shape[1:])


def decode_run(run):
    """Decode every leaf of a run-plane tree (flat fold entry points
    that read whole planes; the windowed kernels use wplane instead)."""
    B, R = tree_dims(run)
    out = {}
    for name, leaf in run.items():
        if name == "cols":
            out[name] = {
                cid: {n: decode_leaf(p, B, R) for n, p in col.items()}
                for cid, col in leaf.items()}
        else:
            out[name] = decode_leaf(leaf, B, R)
    return out
