"""Loop-free full-run aggregate over a MULTI-VERSION run: bounded
lookback instead of segmented scans.

ops.seg_fold answers every per-group MVCC question with
lax.associative_scan — log-depth, but each of the ~11 combine levels
re-materializes the full payload (ht planes + every column's planes),
so the resolve runs an order of magnitude below the flat path's memory
roofline (~16 GB/s vs ~490 GB/s measured at 17M rows).

This module exploits one more layout invariant: the columnar build
records the run's LARGEST key-group version count (max_group_versions).
When that bound W is small — the common case; version counts reflect
update traffic since the last compaction — every per-group question is
answerable by looking at most W-1 rows to either side:

- rows of a group are contiguous, newest-first, never spanning a block,
  so a shift along the row axis with zero fill never leaks across keys;
- "newest visible tombstone shadows ht <= its ht" becomes: any EARLIER
  visible tombstone in-group shadows this row (ht-desc order makes its
  ht >= ours), plus any LATER one at exactly our ht (same-batch
  DELETE+write ties);
- "latest alive setter per column" becomes a first-match select over
  the W forward offsets, evaluated at each group's first row (the
  representative), exactly seg_fold's suffix-first.

Everything is elementwise + W-1 static shifts, which XLA fuses like the
flat path. seg_fold remains the fallback for runs whose W exceeds the
unroll bound (heavy-update groups), and the oracle in tests.

Reference analog: the same merge-on-read (DocRowwiseIterator,
src/yb/docdb/doc_rowwise_iterator.cc:545) at memory-roofline shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


from yugabyte_db_tpu.ops import encodings
from yugabyte_db_tpu.ops import flat_fold
from yugabyte_db_tpu.ops import scan as dscan
from yugabyte_db_tpu.ops.scan import I32_MIN, le2
from yugabyte_db_tpu.utils.jitting import compile_contract

# Largest per-group version count the unrolled lookback compiles for.
# Beyond it the engine falls back to seg_fold's associative scans.
MAX_LOOKBACK = 32


def supports(sig: dscan.ScanSig) -> bool:
    if sig.flat or sig.lookback < 1 or sig.lookback > MAX_LOOKBACK:
        return False
    if sig.R > flat_fold.MAX_R or sig.B > flat_fold.MAX_B:
        return False
    if any(ps.kind not in ("i32", "i64", "f64", "code")
           for ps in sig.preds):
        return False
    for ag in sig.aggs:
        if ag.fn not in ("count", "sum", "min", "max"):
            return False
    return True


def _shift_r(x, k):
    """x[r-k] with zero/False fill (along the row axis)."""
    if k == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[1] = (k, 0)
    return jnp.pad(x, pad)[:, : x.shape[1]]


def _shift_l(x, k):
    """x[r+k] with zero/False fill (along the row axis)."""
    if k == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[1] = (0, k)
    return jnp.pad(x, pad)[:, k:]


@functools.lru_cache(maxsize=128)
@compile_contract("lookback_aggregate", max_compiles=128)
def compiled_lookback_aggregate(sig: dscan.ScanSig):
    """jit(run, row_lo, row_hi, read_hi, read_lo, rexp_hi, rexp_lo,
    pred_lits) -> (ivec, fvec) in agg_fold's packed format; exact
    equivalence with seg_fold on any run whose group sizes are within
    sig.lookback."""
    assert supports(sig)
    W = sig.lookback

    def fn(run, row_lo, row_hi, read_hi, read_lo, rexp_hi, rexp_lo,
           pred_lits):
        run = encodings.decode_run(run)
        valid = run["valid"]
        gs = run["group_start"]
        ht_hi, ht_lo = run["ht_hi"], run["ht_lo"]
        visible = valid & le2(ht_hi, ht_lo, read_hi, read_lo)
        expired = le2(run["exp_hi"], run["exp_lo"], rexp_hi, rexp_lo)
        tomb = run["tomb"]

        # same_prev[k]: row r-k is in r's group (k = 1..W-1); built
        # incrementally from "no group start in (r-k, r]".
        not_gs = ~gs
        same_prev = [None] * W
        for k in range(1, W):
            same_prev[k] = (not_gs if k == 1
                            else same_prev[k - 1] & _shift_r(not_gs, k - 1))
        # same_next[k]: row r+k is in r's group.
        same_next = [None] * W
        for k in range(1, W):
            same_next[k] = _shift_l(same_prev[k], k)

        # 1. Tombstone shadowing. Earlier in-group visible tombstones
        # always shadow (their ht is >= ours in ht-desc layout); later
        # ones shadow only at exactly our ht (same-batch ties).
        vt = visible & tomb
        shadowed = jnp.zeros_like(vt)
        for k in range(1, W):
            shadowed = shadowed | (same_prev[k] & _shift_r(vt, k))
            later_vt = same_next[k] & _shift_l(vt, k)
            eq_ht = (ht_hi == _shift_l(ht_hi, k)) & \
                (ht_lo == _shift_l(ht_lo, k))
            shadowed = shadowed | (later_vt & eq_ht)
        alive = visible & ~tomb & ~shadowed

        # 2. Group-level liveness at the representative (first row).
        def group_or(x):
            out = x
            for k in range(1, W):
                out = out | (same_next[k] & _shift_l(x, k))
            return out

        live_any = group_or(alive & run["live"] & ~expired)

        # 3. Per-column latest alive setter: first forward match over
        # the W offsets, payload selected newest-match-wins (iterate
        # offsets far-to-near so the nearest match lands last).
        col_notnull = {}
        col_val = {}

        def sel_where(m, a, b):
            mm = m
            while mm.ndim < a.ndim:
                mm = mm[..., None]
            return jnp.where(mm, a, b)

        for cs in sig.cols:
            c = run["cols"][cs.col_id]
            cand = alive & c["set"]
            payload = {"null": c["isnull"], "exp": expired,
                       "cmp": c["cmp"]}
            if "arith" in c:
                payload["arith"] = c["arith"]
            # Nearest-forward-match wins: fold offsets far -> near, then
            # let the row itself (offset 0) override. Garbage where no
            # offset matches -- gated by ``has``.
            has = cand
            sel = dict(payload)
            for k in range(W - 1, 0, -1):
                cand_k = same_next[k] & _shift_l(cand, k)
                has = has | cand_k
                sel = {name: sel_where(cand_k,
                                       _shift_l(payload[name], k),
                                       sel[name])
                       for name in payload}
            if W > 1:
                sel = {name: sel_where(cand, payload[name], sel[name])
                       for name in payload}
            col_notnull[cs.col_id] = has & ~sel["null"] & ~sel["exp"]
            col_val[cs.col_id] = sel

        return flat_fold.finish_groups(sig, gs, live_any, col_notnull,
                                       col_val, row_lo, row_hi, pred_lits)

    return jax.jit(fn)
