"""TPU device kernels: the data plane.

This package replaces the reference's three read-path hot loops
(SURVEY.md §3.2): DocRowwiseIterator row materialization
(src/yb/docdb/doc_rowwise_iterator.cc:545), the rocksdb
MergingIterator/BlockIter byte iteration, and QLExprExecutor per-row
predicate eval (src/yb/common/ql_expr.h:210) — with vectorized XLA/Pallas
computation over columnar plane arrays:

- scan: MVCC visibility + tombstone shadowing + per-column latest-visible
  merge + range/predicate masks + aggregate partials, one fused device
  program per block window;
- merge: compaction as a device sort (lax.sort multi-key) over concatenated
  runs (replacing compaction_job.cc's k-way heap merge).
"""
