"""Aggregate partial folding: exact accumulation across scan windows.

Shared by the single-chip full-run aggregate (one dispatch per scan — the
device fori_loops every window and returns two packed vectors, because the
host link pays ~per-transfer latency, not bandwidth) and the mesh-sharded
path (parallel.sharded, which folds per device then combines over ICI).

Integer sums are bit-exact at any scale: per-block 16-bit-limb partials
(ops.scan._eval_agg) fold into a base-2^16 digit vector with one
carry-propagation step per window, so no int32 ever overflows
(limb partial <= 65535*R*K <= ~1.1e9 for K<=8, digits stay < ~2^17).
Min/max fold lexicographically on two int32 planes; float sums fold in f32.

Reference analog of what this replaces: the per-row Python/C++ aggregate
accumulation inside the scan loop (QLReadOperation::EvalAggregate,
src/yb/docdb/cql_operation.cc:1212; PgsqlReadOperation::EvalAggregate,
src/yb/docdb/pgsql_operation.cc:473).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from yugabyte_db_tpu.ops import scan as dscan
from yugabyte_db_tpu.ops.scan import I32_MAX, I32_MIN
from yugabyte_db_tpu.utils import planes as PL
from yugabyte_db_tpu.utils.jitting import compile_contract

DIGITS = 8  # base-2^16 digit vector length for exact integer sums

# Window size for on-device full-run loops: keeps the per-window limb sum
# (<= 65535 * R * K) inside int32.
FULL_WINDOW_BLOCKS = 8

# Headroom for the accumulated carry digits (< ~2^17 after carry_step) on
# top of one window's limb sum.
_LIMB_BUDGET = (1 << 31) - (1 << 18)


def check_limb_bound(R: int, K: int) -> None:
    """Integer-sum safety: one window's 16-bit-limb partial plus carry
    headroom must fit int32."""
    if 65535 * R * K > _LIMB_BUDGET:
        raise ValueError(
            f"rows_per_block={R} x window_blocks={K} overflows the int32 "
            f"limb accumulator (65535*R*K > {_LIMB_BUDGET}); shrink one")


def safe_window_blocks(R: int, max_k: int) -> int:
    """Largest power-of-two window <= max_k that satisfies check_limb_bound."""
    k = max_k
    while k > 1 and 65535 * R * k > _LIMB_BUDGET:
        k //= 2
    check_limb_bound(R, k)
    return k


def carry_step(digits):
    """One base-2^16 carry propagation over a non-negative int32 digit vector."""
    lo = digits & jnp.int32(0xFFFF)
    hi = digits >> jnp.int32(16)
    return lo + jnp.concatenate([jnp.zeros((1,), jnp.int32), hi[:-1]])


def agg_init(sig_aggs):
    acc = []
    for ag in sig_aggs:
        if ag.fn == "count":
            acc.append({"count": jnp.int32(0)})
        elif ag.fn == "sum":
            if ag.kind in ("f32", "f64"):
                # Kahan-compensated f32 pair: cross-window accumulation must
                # not drift (TPU has no fast f64; the compensation term
                # recovers the per-add rounding, summed back in f64 on host).
                acc.append({"fsum": jnp.float32(0), "fcomp": jnp.float32(0),
                            "n": jnp.int32(0)})
            else:
                acc.append({"digits": jnp.zeros((DIGITS,), jnp.int32),
                            "n": jnp.int32(0)})
        else:  # min/max
            is_max = ag.fn == "max"
            fill = I32_MIN if is_max else I32_MAX
            if ag.kind == "f32":
                acc.append({"fext": jnp.float32(-np.inf if is_max else np.inf),
                            "n": jnp.int32(0)})
            elif ag.kind == "i32":
                acc.append({"ext": jnp.int32(fill), "n": jnp.int32(0)})
            else:
                acc.append({"ext_hi": jnp.int32(fill),
                            "ext_lo": jnp.int32(fill), "n": jnp.int32(0)})
    return acc


def agg_fold(sig_aggs, acc, parts):
    """Fold one window's scan_window partials into the accumulators."""
    out = []
    for i, ag in enumerate(sig_aggs):
        a = acc[i]
        p = {k.split("_", 1)[1]: v for k, v in parts.items()
             if k.startswith(f"agg{i}_")}
        if ag.fn == "count":
            out.append({"count": a["count"] + p["count"]})
        elif ag.fn == "sum":
            if ag.kind in ("f32", "f64"):
                # Kahan add of this window's block-partial sum.
                y = jnp.sum(p["fsum"]) - a["fcomp"]
                t = a["fsum"] + y
                out.append({"fsum": t, "fcomp": (t - a["fsum"]) - y,
                            "n": a["n"] + p["n"]})
            else:
                win = jnp.sum(p["limbs"], axis=0)  # [4] per-window limb sums
                widened = jnp.concatenate(
                    [win, jnp.zeros((DIGITS - win.shape[0],), jnp.int32)])
                out.append({"digits": carry_step(a["digits"] + widened),
                            "n": a["n"] + p["n"]})
        else:
            is_max = ag.fn == "max"
            red = jnp.maximum if is_max else jnp.minimum
            if ag.kind == "f32":
                out.append({"fext": red(a["fext"], p["fext"]),
                            "n": a["n"] + p["n"]})
            elif ag.kind == "i32":
                out.append({"ext": red(a["ext"], p["ext"]),
                            "n": a["n"] + p["n"]})
            else:
                phi, plo = p["ext_hi"], p["ext_lo"]
                if is_max:
                    take = (phi > a["ext_hi"]) | (
                        (phi == a["ext_hi"]) & (plo > a["ext_lo"]))
                else:
                    take = (phi < a["ext_hi"]) | (
                        (phi == a["ext_hi"]) & (plo < a["ext_lo"]))
                out.append({
                    "ext_hi": jnp.where(take, phi, a["ext_hi"]),
                    "ext_lo": jnp.where(take, plo, a["ext_lo"]),
                    "n": a["n"] + p["n"]})
    return out


# -- packing: accumulators <-> two flat vectors (minimize D2H transfers) -----

def pack(sig_aggs, acc, scanned):
    """(int32 vector, float32 vector) carrying every accumulator + scanned."""
    ints, floats = [scanned], []
    for ag, a in zip(sig_aggs, acc):
        if ag.fn == "count":
            ints.append(a["count"])
        elif ag.fn == "sum":
            if ag.kind in ("f32", "f64"):
                floats.extend([a["fsum"], a["fcomp"]])
                ints.append(a["n"])
            else:
                ints.extend([a["digits"][j] for j in range(DIGITS)])
                ints.append(a["n"])
        elif ag.kind == "f32":
            floats.append(a["fext"])
            ints.append(a["n"])
        elif ag.kind == "i32":
            ints.extend([a["ext"], a["n"]])
        else:
            ints.extend([a["ext_hi"], a["ext_lo"], a["n"]])
    ivec = jnp.stack(ints)
    fvec = (jnp.stack(floats) if floats
            else jnp.zeros((0,), jnp.float32))
    return ivec, fvec


def unpack(sig_aggs, ivec, fvec):
    """Inverse of pack on host numpy arrays -> (acc dicts of python
    numbers, scanned)."""
    ints = [int(x) for x in np.asarray(ivec)]
    floats = [float(x) for x in np.asarray(fvec)]
    ii, fi = 1, 0
    scanned = ints[0]
    acc = []
    for ag in sig_aggs:
        if ag.fn == "count":
            acc.append({"count": ints[ii]}); ii += 1
        elif ag.fn == "sum":
            if ag.kind in ("f32", "f64"):
                acc.append({"fsum": floats[fi], "fcomp": floats[fi + 1],
                            "n": ints[ii]})
                fi += 2; ii += 1
            else:
                acc.append({"digits": ints[ii:ii + DIGITS],
                            "n": ints[ii + DIGITS]})
                ii += DIGITS + 1
        elif ag.kind == "f32":
            acc.append({"fext": floats[fi], "n": ints[ii]}); fi += 1; ii += 1
        elif ag.kind == "i32":
            acc.append({"ext": ints[ii], "n": ints[ii + 1]}); ii += 2
        else:
            acc.append({"ext_hi": ints[ii], "ext_lo": ints[ii + 1],
                        "n": ints[ii + 2]})
            ii += 3
    return acc, scanned


def merge_accs(ag: dscan.AggSig, a: dict, b: dict) -> dict:
    """Combine two unpacked accumulators over DISJOINT row sets (the
    overlay-scan composition: primary-run partial + dirty-key overlay
    partial). Exact for count/sum (digit adds) and order-correct for
    min/max (lexicographic on ordered planes)."""
    if ag.fn == "count":
        return {"count": a["count"] + b["count"]}
    n = a["n"] + b["n"]
    if ag.fn == "sum":
        if ag.kind in ("f32", "f64"):
            return {"fsum": a["fsum"] + b["fsum"],
                    "fcomp": a["fcomp"] + b["fcomp"], "n": n}
        return {"digits": [int(x) + int(y)
                           for x, y in zip(a["digits"], b["digits"])],
                "n": n}
    if a["n"] == 0:
        return dict(b, n=n)
    if b["n"] == 0:
        return dict(a, n=n)
    pick = max if ag.fn == "max" else min
    if ag.kind == "f32":
        return {"fext": pick(a["fext"], b["fext"]), "n": n}
    if ag.kind == "i32":
        return {"ext": pick(a["ext"], b["ext"]), "n": n}
    best = pick((a["ext_hi"], a["ext_lo"]), (b["ext_hi"], b["ext_lo"]))
    return {"ext_hi": best[0], "ext_lo": best[1], "n": n}


def finalize(ag: dscan.AggSig, a: dict, fn_name: str):
    """Accumulator -> python value (fn_name is the user fn: avg uses a sum
    accumulator)."""
    if fn_name == "count":
        return int(a["count"])
    n = int(a["n"])
    if fn_name in ("sum", "avg"):
        if n == 0:
            return None
        if ag.kind in ("f32", "f64"):
            s = float(a["fsum"]) - float(a["fcomp"])
        else:
            digits = a["digits"]
            total = sum(int(digits[j]) << (16 * j) for j in range(DIGITS))
            bias = (1 << 63) if ag.kind == "i64" else (1 << 31)
            s = total - n * bias
        return s / n if fn_name == "avg" else s
    if n == 0:
        return None
    if ag.kind == "f32":
        return float(a["fext"])
    if ag.kind == "i32":
        return int(a["ext"])
    hi = np.array([int(a["ext_hi"])], dtype=np.int32)
    lo = np.array([int(a["ext_lo"])], dtype=np.int32)
    if ag.kind == "i64":
        return int(PL.ordered_planes_to_i64(hi, lo)[0])
    return float(PL.ordered_planes_to_f64(hi, lo)[0])


# -- shared window-fold body (single-chip + sharded paths) -------------------

def fold_window(sig: dscan.ScanSig, run, w, carry, row_lo, row_hi,
                read_planes, pred_lits, block_off=0):
    """fori_loop body: scan window w of `run` (local block offset
    block_off for mesh shards) and fold its partials into the carry."""
    acc, scanned = carry
    b0 = w * sig.K
    base = (block_off + b0) * sig.R
    parts = dscan.scan_window(
        sig, run, b0,
        jnp.clip(row_lo - base, -(1 << 30), 1 << 30),
        jnp.clip(row_hi - base, -(1 << 30), 1 << 30),
        *read_planes, pred_lits)
    scanned = scanned + jnp.sum(parts["result"].astype(jnp.int32))
    return agg_fold(sig.aggs, acc, parts), scanned


def window_bounds(row_lo: int, row_hi: int, R: int, K: int, W: int):
    """[w_first, w_last) window indices overlapping row range (host ints)."""
    if row_hi <= row_lo:
        return 0, 0
    w_first = max(0, min(W, (row_lo // R) // K))
    w_last = max(0, min(W, ((row_hi - 1) // R) // K + 1))
    return w_first, w_last


# -- AggSpec lowering (shared by tpu_engine and parallel.sharded) ------------

def lower_aggs(spec_aggs, name_to_id, kinds):
    """ScanSpec aggregates -> (device AggSigs, [(user_fn, index)] lowering).
    avg lowers to a sum accumulator; finalize() divides by n."""
    dev_aggs, lowering = [], []
    for a in spec_aggs:
        cid = name_to_id.get(a.column) if a.column else None
        kind = kinds[cid] if cid is not None else None
        fn = "sum" if a.fn == "avg" else a.fn
        lowering.append((a.fn, len(dev_aggs)))
        dev_aggs.append(dscan.AggSig(fn, cid, kind))
    return tuple(dev_aggs), lowering


def pred_literal_host(kind: str, value):
    """Predicate literal -> host (numpy) device representation. Kept on
    host so batched planners can stack many specs' literals into one
    transfer instead of queueing a tiny H2D copy per predicate."""
    if kind == "i32":
        return np.int32(int(value))
    if kind == "code":
        # Promoted string predicate: the engine already translated the
        # value to an int32 dictionary-code bound.
        return np.int32(int(value))
    if kind == "f32":
        return np.float32(value)
    if kind == "i64":
        hi, lo = PL.i64_to_ordered_planes(np.array([int(value)], dtype=np.int64))
        return np.array([hi[0], lo[0]], dtype=np.int32)
    if kind == "f64":
        hi, lo = PL.f64_to_ordered_planes(np.array([value], dtype=np.float64))
        return np.array([hi[0], lo[0]], dtype=np.int32)
    raw = (value.encode("utf-8", "surrogateescape")
           if isinstance(value, str) else bytes(value))
    hi, lo = PL.varlen_prefix_planes([raw])
    return np.array([hi[0], lo[0]], dtype=np.int32)


def pred_literal(kind: str, value):
    """Predicate literal -> device representation for its column kind."""
    return jnp.asarray(pred_literal_host(kind, value))


# -- the single-dispatch full-run aggregate program --------------------------

@functools.lru_cache(maxsize=128)
@compile_contract("full_aggregate", max_compiles=128)
def compiled_full_aggregate(sig: dscan.ScanSig):
    """One jitted program: fori_loop the [w_first, w_last) windows of the
    run, fold partials, return (ivec, fvec). One dispatch + two transfers
    per scan; window bounds are traced so bounded scans skip blocks."""
    check_limb_bound(sig.R, sig.K)

    def fn(run, row_lo, row_hi, w_first, w_last, read_hi, read_lo,
           rexp_hi, rexp_lo, pred_lits):
        init = (agg_init(sig.aggs), jnp.int32(0))
        body = functools.partial(
            fold_window, sig, run, row_lo=row_lo, row_hi=row_hi,
            read_planes=(read_hi, read_lo, rexp_hi, rexp_lo),
            pred_lits=pred_lits)
        acc, scanned = jax.lax.fori_loop(
            w_first, w_last, lambda w, c: body(w, c), init)
        return pack(sig.aggs, acc, scanned)

    return jax.jit(fn)
