"""The device scan kernel: MVCC merge-on-read + filter + aggregate pushdown.

One jitted program (per static signature) scans a window of K blocks from a
ColumnarRun: it resolves MVCC visibility (commit-ht vs read point, row
tombstone shadowing, TTL expiry), merges each key group to its
latest-visible per-column state, applies key-range row bounds and pushed
predicates, and either reports matching groups (row scans) or reduces
aggregate partials per block (aggregate pushdown).

Semantics are exactly storage.merge.merge_versions, vectorized with
segmented reductions keyed on contiguous key-group ids. The randomized
engine-diff tests pin this kernel to the CPU oracle.

Design notes (TPU-first):
- all 64-bit comparisons are two-int32-plane lexicographic compares
  (utils.planes); no int64 on device;
- groups never span blocks (columnar build invariant), so any window of
  whole blocks is segment-complete;
- range bounds arrive as *row index* bounds, pre-resolved on host by exact
  bisection over full key bytes — the device never resolves key-prefix ties;
- integer SUM is exact: values decompose into 16-bit limbs summed per block
  in int32, recombined on host in arbitrary precision (the float path sums
  f32 per block, f64 across blocks);
- varlen (string) predicates evaluate on 8-byte order-preserving prefixes
  as a SUPERSET mask (plane-equal = maybe-match); the engine host-verifies
  candidates, and routes aggregates through the row path in that case.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from yugabyte_db_tpu.ops import encodings
from yugabyte_db_tpu.utils.jitting import compile_contract

I32_MIN = np.int32(np.iinfo(np.int32).min)
I32_MAX = np.int32(np.iinfo(np.int32).max)

# -- 2-plane lexicographic compares (signed int32 planes) -------------------

def le2(a_hi, a_lo, b_hi, b_lo):
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


def lt2(a_hi, a_lo, b_hi, b_lo):
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo < b_lo))


def eq2(a_hi, a_lo, b_hi, b_lo):
    return (a_hi == b_hi) & (a_lo == b_lo)


# -- static signature -------------------------------------------------------

@dataclass(frozen=True)
class ColSig:
    col_id: int
    kind: str        # 'i32' | 'i64' | 'f32' | 'f64' | 'str'

    @property
    def two_plane(self) -> bool:
        return self.kind in ("i64", "f64", "str")


@dataclass(frozen=True)
class PredSig:
    col_id: int
    kind: str
    op: str          # '=', '!=', '<', '<=', '>', '>=' ('IN' expands to '='s)


@dataclass(frozen=True)
class AggSig:
    fn: str          # 'count' | 'sum' | 'min' | 'max'
    col_id: int | None
    kind: str | None


@dataclass(frozen=True)
class ScanSig:
    """Everything that shapes the compiled program."""

    B: int           # blocks in run
    R: int           # rows per block
    K: int           # blocks per window
    cols: tuple      # tuple[ColSig] — columns the program touches
    preds: tuple     # tuple[PredSig]
    aggs: tuple      # tuple[AggSig] — empty for row scans
    apply_preds: bool  # False: candidates only (multi-source scans)
    flat: bool = False  # every key group has exactly 1 version: the MVCC
                        # merge degenerates to elementwise masks (no
                        # segment ops / gathers) — the post-compaction
                        # fast path
    lookback: int = 0   # run's max versions per key group (0 = unknown/
                        # flat): small bounds unlock the shifted-mask
                        # resolve (ops.lookback_fold) instead of
                        # segmented scans


# -- the program ------------------------------------------------------------

def _window(arr, b0, K):
    """Slice K blocks starting at b0 and flatten the block axis."""
    sizes = (K,) + arr.shape[1:]
    starts = (b0,) + (0,) * (arr.ndim - 1)
    w = jax.lax.dynamic_slice(arr, starts, sizes)
    return w.reshape((sizes[0] * sizes[1],) + sizes[2:])


def _seg_max(vals, gid, n):
    return jax.ops.segment_max(vals, gid, num_segments=n,
                               indices_are_sorted=True)


def _seg_min(vals, gid, n):
    return jax.ops.segment_min(vals, gid, num_segments=n,
                               indices_are_sorted=True)


def _seg_sum(vals, gid, n):
    return jax.ops.segment_sum(vals, gid, num_segments=n,
                               indices_are_sorted=True)


def _u32(x):
    return x.astype(jnp.uint32)


def _limbs16(lo_u32, hi_u32):
    """Four 16-bit limbs of a biased u64 (hi*2^32 + lo), as int32."""
    return (
        (lo_u32 & jnp.uint32(0xFFFF)).astype(jnp.int32),
        (lo_u32 >> jnp.uint32(16)).astype(jnp.int32),
        (hi_u32 & jnp.uint32(0xFFFF)).astype(jnp.int32),
        (hi_u32 >> jnp.uint32(16)).astype(jnp.int32),
    )


def resolve_window(sig, run, b0, row_lo, row_hi,
                   read_hi, read_lo, rexp_hi, rexp_lo, pred_literals):
    """Resolve one K-block window to per-group MVCC state (traced).

    ``sig`` needs K, R, cols, preds, apply_preds (ScanSig or GatherSig).
    ``row_lo``/``row_hi`` are *window-local* row-index bounds. Returns a
    dict of per-group arrays (indexed by group id, length N, entries at
    gid >= num_groups are garbage):
      result        bool  — exists & in-range & predicates
      pre_pred      bool  — exists & in-range (before predicates)
      start_idx     i32   — first row of the group (window-local)
      col_idx/col_has/col_notnull  per touched column
      cmp_w/arith_w windowed column planes (per-row, window-local)
    """
    K, R = sig.K, sig.R
    N = K * R

    def wp(leaf):
        # Encoded leaves (ops.encodings) decode inline per window; plain
        # ndarrays take the dynamic-slice path _window always used.
        return encodings.wplane(leaf, b0, K, R)

    valid = wp(run["valid"])
    group_start = wp(run["group_start"])
    tomb = wp(run["tomb"])
    live = wp(run["live"])
    ht_hi = wp(run["ht_hi"])
    ht_lo = wp(run["ht_lo"])
    exp_hi = wp(run["exp_hi"])
    exp_lo = wp(run["exp_lo"])

    ridx = jnp.arange(N, dtype=jnp.int32)

    # 1. MVCC visibility at the read point.
    visible = valid & le2(ht_hi, ht_lo, read_hi, read_lo)
    expired = le2(exp_hi, exp_lo, rexp_hi, rexp_lo)

    if sig.flat:
        return _resolve_flat(sig, run, b0, row_lo, row_hi, pred_literals,
                             N, ridx, valid, tomb, live, visible, expired)

    gid = jnp.cumsum(group_start.astype(jnp.int32)) - 1
    num_groups = gid[-1] + 1

    # 2. Row-tombstone shadowing: newest visible tombstone per group.
    t_hi = _seg_max(jnp.where(visible & tomb, ht_hi, I32_MIN), gid, N)
    t_hi_r = t_hi[gid]
    t_lo = _seg_max(jnp.where(visible & tomb & (ht_hi == t_hi_r), ht_lo, I32_MIN),
                    gid, N)
    t_lo_r = t_lo[gid]
    has_tomb = t_hi_r != I32_MIN
    # <= (not <): a value at exactly the tombstone's ht is shadowed too,
    # matching merge.py (same-batch DELETE+write share one ht).
    shadowed = has_tomb & le2(ht_hi, ht_lo, t_hi_r, t_lo_r)
    alive = visible & ~tomb & ~shadowed

    # 3. Liveness (INSERT marker) per group.
    live_exists = _seg_max((alive & live & ~expired).astype(jnp.int32), gid, N) > 0

    # 4. Per-column latest visible version (first alive setter in ht-desc order).
    start_idx = _seg_min(ridx, gid, N)  # first row of each group
    col_idx = {}
    col_has = {}
    col_notnull = {}
    isnull_w = {}
    set_w = {}
    cmp_w = {}
    arith_w = {}
    for cs in sig.cols:
        c = run["cols"][cs.col_id]
        set_c = wp(c["set"])
        null_c = wp(c["isnull"])
        cand = alive & set_c
        first = _seg_min(jnp.where(cand, ridx, I32_MAX), gid, N)
        has = first != I32_MAX
        idx = jnp.clip(first, 0, N - 1)
        col_idx[cs.col_id] = idx
        col_has[cs.col_id] = has
        col_notnull[cs.col_id] = has & ~null_c[idx] & ~expired[idx]
        isnull_w[cs.col_id] = null_c
        set_w[cs.col_id] = set_c
        cmp_w[cs.col_id] = wp(c["cmp"])
        if "arith" in c:
            arith_w[cs.col_id] = wp(c["arith"])

    # 5. Row existence (liveness or any non-null column value).
    exists = live_exists
    for cs in sig.cols:
        exists = exists | col_notnull[cs.col_id]

    # 6. Key-range bounds as exact global row-index bounds (host-resolved).
    in_range = (start_idx >= row_lo) & (start_idx < row_hi)
    valid_group = _seg_max(valid.astype(jnp.int32), gid, N) > 0

    result = exists & in_range & valid_group

    # 7. Predicates on merged per-group values.
    pre_pred = result
    if sig.apply_preds:
        for i, ps in enumerate(sig.preds):
            lit = pred_literals[i]
            idx = col_idx[ps.col_id]
            notnull = col_notnull[ps.col_id]
            result = result & notnull & _eval_pred(
                ps, cmp_w.get(ps.col_id), arith_w.get(ps.col_id), idx, lit)

    return {
        "result": result,
        "pre_pred": pre_pred,
        "start_idx": start_idx,
        "num_groups": num_groups,
        "ridx": ridx,
        "col_idx": col_idx,
        "col_has": col_has,
        "col_notnull": col_notnull,
        "cmp_w": cmp_w,
        "arith_w": arith_w,
    }


def _resolve_flat(sig, run, b0, row_lo, row_hi, pred_literals,
                  N, ridx, valid, tomb, live, visible, expired):
    """Single-version-per-key resolve: every row is its own group, so
    tombstone shadowing, per-column latest-version selection, and the
    group-start machinery are all elementwise (no segment ops, no
    gathers). Produces the same output contract as the general path with
    num_groups == N and col_idx == ridx."""
    alive = visible & ~tomb
    live_exists = alive & live & ~expired
    col_idx = {}
    col_has = {}
    col_notnull = {}
    cmp_w = {}
    arith_w = {}

    def wp(leaf):
        return encodings.wplane(leaf, b0, sig.K, sig.R)

    for cs in sig.cols:
        c = run["cols"][cs.col_id]
        set_c = wp(c["set"])
        null_c = wp(c["isnull"])
        has = alive & set_c
        col_idx[cs.col_id] = ridx
        col_has[cs.col_id] = has
        col_notnull[cs.col_id] = has & ~null_c & ~expired
        cmp_w[cs.col_id] = wp(c["cmp"])
        if "arith" in c:
            arith_w[cs.col_id] = wp(c["arith"])

    exists = live_exists
    for cs in sig.cols:
        exists = exists | col_notnull[cs.col_id]

    in_range = (ridx >= row_lo) & (ridx < row_hi)
    result = exists & in_range & valid
    pre_pred = result
    if sig.apply_preds:
        for i, ps in enumerate(sig.preds):
            lit = pred_literals[i]
            result = result & col_notnull[ps.col_id] & _eval_pred(
                ps, cmp_w.get(ps.col_id), arith_w.get(ps.col_id), ridx, lit)

    return {
        "result": result,
        "pre_pred": pre_pred,
        "start_idx": ridx,
        "num_groups": jnp.int32(N),
        "ridx": ridx,
        "col_idx": col_idx,
        "col_has": col_has,
        "col_notnull": col_notnull,
        "cmp_w": cmp_w,
        "arith_w": arith_w,
    }


def scan_window(sig: ScanSig, run, b0, row_lo, row_hi,
                read_hi, read_lo, rexp_hi, rexp_lo, pred_literals):
    """The traced scan program. ``run`` is the device-array pytree
    (ops.device_run.DeviceRun.arrays); scalars are traced.

    Returns a dict:
      row scans:  result[N] bool (per group id), start_idx[N] i32,
                  num_groups i32
      aggregates: additionally 'agg<i>_*' partials per AggSig.
    """
    K, R = sig.K, sig.R
    N = K * R
    r = resolve_window(sig, run, b0, row_lo, row_hi,
                       read_hi, read_lo, rexp_hi, rexp_lo, pred_literals)
    result, start_idx = r["result"], r["start_idx"]
    out = {"result": result, "start_idx": start_idx,
           "num_groups": r["num_groups"]}

    # 8. Aggregate partials.
    block_of_group = start_idx // R  # in [0, K)
    for i, ag in enumerate(sig.aggs):
        out.update(_eval_agg(f"agg{i}", ag, result, r["col_idx"], r["col_has"],
                             r["col_notnull"], r["cmp_w"], r["arith_w"],
                             block_of_group, K, N))
    return out


def _eval_pred(ps: PredSig, cmp, arith, idx, lit):
    """Predicate mask over merged values. For 'str' AND 'f32', a SUPERSET
    mask (ties count as maybe-match; the host verifies): f32 rounding is
    monotone but not injective, so equal-after-rounding comparisons are
    ambiguous just like equal string prefixes."""
    if ps.kind == "f32":
        v = arith[idx]
        x = lit
        eq = v == x
        return {"=": eq, "!=": jnp.ones_like(eq),
                "<": v <= x, "<=": v <= x,
                ">": v >= x, ">=": v >= x}[ps.op]
    if ps.kind == "i32":
        v = cmp[idx, 0]
        x = lit
        return {"=": v == x, "!=": v != x, "<": v < x, "<=": v <= x,
                ">": v > x, ">=": v >= x}[ps.op]
    if ps.kind == "code":
        # Promoted string predicate on a dictionary-encoded column: the
        # sorted dict makes code order == value order, so the host
        # translated the literal to an int32 code bound and the compare
        # is EXACT (no superset verify) on the decoded code plane.
        v = cmp[idx, 2]
        x = lit
        return {"=": v == x, "!=": v != x, "<": v < x, "<=": v <= x,
                ">": v > x, ">=": v >= x}[ps.op]
    hi, lo = cmp[idx, 0], cmp[idx, 1]
    lhi, llo = lit[0], lit[1]
    eq = eq2(hi, lo, lhi, llo)
    lt = lt2(hi, lo, lhi, llo)
    if ps.kind in ("i64", "f64"):
        return {"=": eq, "!=": ~eq, "<": lt, "<=": lt | eq,
                ">": ~(lt | eq), ">=": ~lt}[ps.op]
    # strings: plane-equality is ambiguous -> superset semantics
    return {
        "=": eq,                # equal strings always plane-equal
        "!=": jnp.ones_like(eq),  # plane-diff => ne true; plane-eq => maybe
        "<": lt | eq,
        "<=": lt | eq,
        ">": ~lt,               # gt or plane-eq(maybe)
        ">=": ~lt,
    }[ps.op]


def _eval_agg(name, ag: AggSig, result, col_idx, col_has, col_notnull,
              cmp_w, arith_w, block_of_group, K, N):
    out = {}
    if ag.fn == "count":
        mask = result if ag.col_id is None else (result & col_notnull[ag.col_id])
        out[f"{name}_count"] = jnp.sum(mask.astype(jnp.int32))
        return out
    mask = result & col_notnull[ag.col_id]
    idx = col_idx[ag.col_id]
    if ag.fn == "sum":
        if ag.kind in ("f32", "f64"):
            v = jnp.where(mask, arith_w[ag.col_id][idx], jnp.float32(0))
            out[f"{name}_fsum"] = _seg_sum(v, block_of_group, K)
            out[f"{name}_n"] = jnp.sum(mask.astype(jnp.int32))
        elif ag.kind == "i32":
            u = _u32(cmp_w[ag.col_id][idx, 0]) ^ jnp.uint32(0x80000000)
            l0 = jnp.where(mask, (u & jnp.uint32(0xFFFF)).astype(jnp.int32), 0)
            l1 = jnp.where(mask, (u >> jnp.uint32(16)).astype(jnp.int32), 0)
            zeros = jnp.zeros_like(l0)
            limbs = jnp.stack([l0, l1, zeros, zeros], axis=-1)
            out[f"{name}_limbs"] = _seg_sum(limbs, block_of_group, K)
            out[f"{name}_n"] = jnp.sum(mask.astype(jnp.int32))
        else:  # i64
            hi_u = _u32(cmp_w[ag.col_id][idx, 0]) ^ jnp.uint32(0x80000000)
            lo_u = _u32(cmp_w[ag.col_id][idx, 1]) ^ jnp.uint32(0x80000000)
            l0, l1, l2, l3 = _limbs16(lo_u, hi_u)
            limbs = jnp.stack([jnp.where(mask, l, 0) for l in (l0, l1, l2, l3)],
                              axis=-1)
            out[f"{name}_limbs"] = _seg_sum(limbs, block_of_group, K)
            out[f"{name}_n"] = jnp.sum(mask.astype(jnp.int32))
        return out
    # min / max on ordered planes (exact); f32 on the arith plane.
    # (No sign-negation trick: -I32_MIN overflows int32.)
    is_max = ag.fn == "max"
    red = jnp.max if is_max else jnp.min
    if ag.kind == "f32":
        v = arith_w[ag.col_id][idx]
        fill = -jnp.inf if is_max else jnp.inf
        out[f"{name}_fext"] = red(jnp.where(mask, v, fill))
        out[f"{name}_n"] = jnp.sum(mask.astype(jnp.int32))
        return out
    ifill = I32_MIN if is_max else I32_MAX
    if ag.kind == "i32":
        v = cmp_w[ag.col_id][idx, 0]
        out[f"{name}_ext"] = red(jnp.where(mask, v, ifill))
        out[f"{name}_n"] = jnp.sum(mask.astype(jnp.int32))
        return out
    hi, lo = cmp_w[ag.col_id][idx, 0], cmp_w[ag.col_id][idx, 1]
    mhi = red(jnp.where(mask, hi, ifill))
    tie = mask & (hi == mhi)
    mlo = red(jnp.where(tie, lo, ifill))
    out[f"{name}_ext_hi"] = mhi
    out[f"{name}_ext_lo"] = mlo
    out[f"{name}_n"] = jnp.sum(mask.astype(jnp.int32))
    return out


@functools.lru_cache(maxsize=256)
@compile_contract("scan_window", max_compiles=256)
def compiled_scan(sig: ScanSig):
    """One compiled XLA program per static scan signature."""
    fn = functools.partial(scan_window, sig)
    return jax.jit(fn)
