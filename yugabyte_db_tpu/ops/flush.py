"""Device memtable flush: replay the apply-order op log into run planes.

Reference analog: the rocksdb flush building an SSTable off the memtable
iterator (src/yb/rocksdb/db/flush_job.cc) — here the "build" is one
device scatter. The host stages the memtable's op log as flat
apply-order planes (the same vectorized encoders the columnar build
uses), computes the flush sort permutation and block packing with
memcmp sort keys (exact whenever keys fit the 32-byte prefix planes),
and this kernel materializes the SORTED, BLOCK-PACKED device planes in
a single dispatch:

    out[dst[j]] = staged[perm[j]]

for every fixed-width plane at once. The outputs are already padded to
the DeviceRun block multiple, so the engine seeds them directly into
the residency cache — the freshly-flushed run is HBM-resident without
a second host->device upload, and the authoritative host planes are
read back from the very arrays the device will scan (byte-identical by
construction).

Division of labor (same reasoning as ops.compact): XLA's variadic sort
is catastrophically slow to compile for 10-word lexsorts, so the ORDER
is computed host-side with one stable argsort over memcmp byte keys;
the device does the data motion.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from yugabyte_db_tpu.utils.jitting import compile_contract


@compile_contract("replay_flush", max_compiles=64)
@functools.partial(jax.jit, static_argnames=("R",))
def replay_flush(staged, perm, dst, gs, is_real, exp_hi_default,
                 exp_lo_default, R: int):
    """Scatter staged apply-order planes into sorted padded run planes.

    ``staged``: {ht_hi, ht_lo, exp_hi, exp_lo: [m] i32; tomb, live: [m]
    bool; cols: {cid: {set, isnull: [m] bool, cmp: [m, P] i32,
    arith?: [m] f32}}} — apply-order rows, padded to a size bucket.
    ``perm[j]``: staged row index of sorted position j (pad entries 0).
    ``dst[j]``: flat output slot of sorted position j (pad entries out
    of range, dropped). ``gs[j]``: sorted-order group-start bit.
    ``is_real``: [Bp] bool, True for blocks the host run owns — padding
    blocks keep the DeviceRun padding encoding (valid False, group_start
    True, expiry 0) so a seeded payload is indistinguishable from a
    demand re-upload.

    Returns the DeviceRun.arrays structure (no key planes — keys stay
    host-side, as in every uploaded run).
    """
    Bp = is_real.shape[0]
    S = Bp * R

    def scat(init, vals):
        return init.at[dst].set(vals[perm], mode="drop")

    z_b = jnp.zeros((S,), jnp.bool_)
    z_i = jnp.zeros((S,), jnp.int32)
    real_rows = jnp.repeat(is_real, R)

    out = {
        "valid": z_b.at[dst].set(True, mode="drop").reshape(Bp, R),
        # Unfilled rows are each their own group (the _alloc contract).
        "group_start": jnp.ones((S,), jnp.bool_)
        .at[dst].set(gs, mode="drop").reshape(Bp, R),
        "tomb": scat(z_b, staged["tomb"]).reshape(Bp, R),
        "live": scat(z_b, staged["live"]).reshape(Bp, R),
        "ht_hi": scat(z_i, staged["ht_hi"]).reshape(Bp, R),
        "ht_lo": scat(z_i, staged["ht_lo"]).reshape(Bp, R),
        "exp_hi": scat(jnp.where(real_rows, exp_hi_default, 0),
                       staged["exp_hi"]).reshape(Bp, R),
        "exp_lo": scat(jnp.where(real_rows, exp_lo_default, 0),
                       staged["exp_lo"]).reshape(Bp, R),
        "cols": {},
    }
    for cid, col in staged["cols"].items():
        entry = {
            "set": scat(z_b, col["set"]).reshape(Bp, R),
            "isnull": scat(z_b, col["isnull"]).reshape(Bp, R),
        }
        if "codes" in col:
            # Dictionary-encoded string column (--tpu_plane_encoding):
            # scatter the staged row CODES and emit the encoded dict
            # leaf directly — the uncompressed prefix planes never
            # materialize in HBM. Unfilled/pad rows get the absent code
            # (last slot), which decodes to prefix planes (0, 0) —
            # byte-identical to the plain format's zeroed rows.
            absent = col["dhi"].shape[0] - 1
            codes = jnp.full((S,), absent, col["codes"].dtype)
            codes = codes.at[dst].set(col["codes"][perm], mode="drop")
            entry["cmp"] = {"dict": {"codes": codes.reshape(Bp, R),
                                     "dhi": col["dhi"],
                                     "dlo": col["dlo"]}}
        else:
            P_ = col["cmp"].shape[-1]
            entry["cmp"] = (jnp.zeros((S, P_), jnp.int32)
                            .at[dst].set(col["cmp"][perm], mode="drop")
                            .reshape(Bp, R, P_))
        if "arith" in col:
            entry["arith"] = scat(jnp.zeros((S,), jnp.float32),
                                  col["arith"]).reshape(Bp, R)
        out["cols"][cid] = entry
    return out


def flush_plane_nbytes(Bp: int, R: int, schema) -> int:
    """Predicted HBM footprint of the replayed planes — the budget gate
    the engine checks BEFORE staging an upload. Deliberately the PLAIN
    plane estimate even when --tpu_plane_encoding emits dict leaves:
    the flush dictionary sizes aren't known before staging, and a
    conservative upper bound only ever sends a borderline flush to the
    host build (which then demand-uploads the compressed form)."""
    per_slot = 4 * 1 + 4 * 4  # valid/group_start/tomb/live + ht/exp
    for c in schema.value_columns:
        planes = 2 if c.dtype.device_planes == 2 else 1
        per_slot += 2 * 1 + 4 * planes  # set/isnull + cmp
        if c.dtype.is_numeric:
            per_slot += 4  # arith f32
    return Bp * R * per_slot
