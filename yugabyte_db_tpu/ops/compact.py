"""Device compaction filter: vectorized history GC over a merged order.

Reference analog: DocDBCompactionFilter inside CompactionJob::Run — the
per-version retention decision (drop overwritten / TTL-expired /
history-GC'd versions) made while merging K sorted runs
(src/yb/rocksdb/db/compaction_job.cc:622,
src/yb/docdb/docdb_compaction_filter.cc).

Division of labor (measured): XLA's variadic sort compiles catastrophically
slowly for 10-key lexsorts, while numpy's np.lexsort is vectorized C — so
the engine computes the merge ORDER host-side (exact whenever keys fit the
32-byte prefix planes) and this kernel computes the RETENTION MASK over
the sorted union in one dispatch: visibility at the cutoff, tombstone
shadowing, per-column/liveness contributors, and equal-hybrid-time span
propagation — mirroring CpuStorageEngine._gc_versions exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from yugabyte_db_tpu.ops.scan import I32_MAX, le2


def _seg_min(vals, gid, n):
    return jax.ops.segment_min(vals, gid, num_segments=n,
                               indices_are_sorted=True)


def _seg_max(vals, gid, n):
    return jax.ops.segment_max(vals, gid, num_segments=n,
                               indices_are_sorted=True)


def gc_mask(num_cols: int, N: int, s, cutoff_planes):
    """Retention mask over the SORTED union (key asc, ht desc).

    ``s`` = {new_group, tomb, live: [N] bool; ht_hi, ht_lo, exp_hi,
    exp_lo: [N] i32; set_: [num_cols, N] bool}. Returns keep[N] bool.
    """
    ht_hi, ht_lo = s["ht_hi"], s["ht_lo"]
    gid = jnp.cumsum(s["new_group"].astype(jnp.int32)) - 1
    ridx = jnp.arange(N, dtype=jnp.int32)
    c_hi, c_lo, ce_hi, ce_lo = cutoff_planes

    # Visibility + tombstone shadowing AT THE CUTOFF.
    visible = le2(ht_hi, ht_lo, c_hi, c_lo)
    sentinel = jnp.int32(-2**31)
    t_hi = _seg_max(jnp.where(visible & s["tomb"], ht_hi, sentinel), gid, N)
    t_hi_r = t_hi[gid]
    t_lo = _seg_max(jnp.where(visible & s["tomb"] & (ht_hi == t_hi_r),
                              ht_lo, sentinel), gid, N)
    t_lo_r = t_lo[gid]
    has_tomb = t_hi_r != sentinel
    shadowed = has_tomb & le2(ht_hi, ht_lo, t_hi_r, t_lo_r)
    alive = visible & ~s["tomb"] & ~shadowed

    # Contributors at the cutoff: first alive setter per column (expiry
    # does NOT matter for contribution — an expired value still shadows),
    # plus the first alive NON-expired liveness.
    is_contrib = jnp.zeros((N,), jnp.bool_)
    for c in range(num_cols):
        set_c = s["set_"][c]
        first = _seg_min(jnp.where(alive & set_c, ridx, I32_MAX), gid, N)
        is_contrib = is_contrib | (first[gid] == ridx)
    expired = le2(s["exp_hi"], s["exp_lo"], ce_hi, ce_lo)
    lfirst = _seg_min(jnp.where(alive & s["live"] & ~expired, ridx,
                                I32_MAX), gid, N)
    is_contrib = is_contrib | (lfirst[gid] == ridx)

    # The CPU GC keys its contributing set by hybrid time: versions
    # sharing a contributor's ht are kept together. Equal-ht rows of a
    # group are adjacent in the sorted order — propagate over spans.
    prev_hi = jnp.concatenate([ht_hi[:1], ht_hi[:-1]])
    prev_lo = jnp.concatenate([ht_lo[:1], ht_lo[:-1]])
    new_span = s["new_group"] | (ht_hi != prev_hi) | (ht_lo != prev_lo)
    sid = jnp.cumsum(new_span.astype(jnp.int32)) - 1
    span_contrib = jax.ops.segment_max(is_contrib.astype(jnp.int32), sid,
                                       num_segments=N,
                                       indices_are_sorted=True)
    kept_contrib = span_contrib[sid] > 0

    newer = ~visible  # ht > cutoff: always retained
    return newer | (kept_contrib & ~le2(ht_hi, ht_lo, t_hi_r, t_lo_r))


@functools.lru_cache(maxsize=32)
def compiled_gc_mask(num_cols: int, N: int):
    return jax.jit(functools.partial(gc_mask, num_cols, N))
