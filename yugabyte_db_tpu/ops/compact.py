"""Device compaction filter: vectorized history GC over a merged order.

Reference analog: DocDBCompactionFilter inside CompactionJob::Run — the
per-version retention decision (drop overwritten / TTL-expired /
history-GC'd versions) made while merging K sorted runs
(src/yb/rocksdb/db/compaction_job.cc:622,
src/yb/docdb/docdb_compaction_filter.cc).

Division of labor (measured): XLA's variadic sort compiles catastrophically
slowly for 10-key lexsorts, while numpy's np.lexsort is vectorized C — so
the engine computes the merge ORDER host-side (exact whenever keys fit the
32-byte prefix planes) and this kernel computes the RETENTION MASK over
the sorted union in one dispatch: visibility at the cutoff, tombstone
shadowing, per-column/liveness contributors, and equal-hybrid-time span
propagation — mirroring CpuStorageEngine._gc_versions exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from yugabyte_db_tpu.ops import encodings
from yugabyte_db_tpu.ops.scan import I32_MAX, le2
from yugabyte_db_tpu.utils.jitting import compile_contract


def _seg_min(vals, gid, n):
    return jax.ops.segment_min(vals, gid, num_segments=n,
                               indices_are_sorted=True)


def _seg_max(vals, gid, n):
    return jax.ops.segment_max(vals, gid, num_segments=n,
                               indices_are_sorted=True)


def gc_mask(num_cols: int, N: int, s, cutoff_planes):
    """Retention mask over the SORTED union (key asc, ht desc).

    ``s`` = {new_group, tomb, live: [N] bool; ht_hi, ht_lo, exp_hi,
    exp_lo: [N] i32; set_: [num_cols, N] bool}. Returns keep[N] bool.
    """
    ht_hi, ht_lo = s["ht_hi"], s["ht_lo"]
    gid = jnp.cumsum(s["new_group"].astype(jnp.int32)) - 1
    ridx = jnp.arange(N, dtype=jnp.int32)
    c_hi, c_lo, ce_hi, ce_lo = cutoff_planes

    # Visibility + tombstone shadowing AT THE CUTOFF.
    visible = le2(ht_hi, ht_lo, c_hi, c_lo)
    sentinel = jnp.int32(-2**31)
    t_hi = _seg_max(jnp.where(visible & s["tomb"], ht_hi, sentinel), gid, N)
    t_hi_r = t_hi[gid]
    t_lo = _seg_max(jnp.where(visible & s["tomb"] & (ht_hi == t_hi_r),
                              ht_lo, sentinel), gid, N)
    t_lo_r = t_lo[gid]
    has_tomb = t_hi_r != sentinel
    shadowed = has_tomb & le2(ht_hi, ht_lo, t_hi_r, t_lo_r)
    alive = visible & ~s["tomb"] & ~shadowed

    # Contributors at the cutoff: first alive setter per column (expiry
    # does NOT matter for contribution — an expired value still shadows),
    # plus the first alive NON-expired liveness.
    is_contrib = jnp.zeros((N,), jnp.bool_)
    for c in range(num_cols):
        set_c = s["set_"][c]
        first = _seg_min(jnp.where(alive & set_c, ridx, I32_MAX), gid, N)
        is_contrib = is_contrib | (first[gid] == ridx)
    expired = le2(s["exp_hi"], s["exp_lo"], ce_hi, ce_lo)
    lfirst = _seg_min(jnp.where(alive & s["live"] & ~expired, ridx,
                                I32_MAX), gid, N)
    is_contrib = is_contrib | (lfirst[gid] == ridx)

    # The CPU GC keys its contributing set by hybrid time: versions
    # sharing a contributor's ht are kept together. Equal-ht rows of a
    # group are adjacent in the sorted order — propagate over spans.
    prev_hi = jnp.concatenate([ht_hi[:1], ht_hi[:-1]])
    prev_lo = jnp.concatenate([ht_lo[:1], ht_lo[:-1]])
    new_span = s["new_group"] | (ht_hi != prev_hi) | (ht_lo != prev_lo)
    sid = jnp.cumsum(new_span.astype(jnp.int32)) - 1
    span_contrib = jax.ops.segment_max(is_contrib.astype(jnp.int32), sid,
                                       num_segments=N,
                                       indices_are_sorted=True)
    kept_contrib = span_contrib[sid] > 0

    newer = ~visible  # ht > cutoff: always retained
    return newer | (kept_contrib & ~le2(ht_hi, ht_lo, t_hi_r, t_lo_r))


@functools.lru_cache(maxsize=32)
@compile_contract("gc_mask", max_compiles=32)
def compiled_gc_mask(num_cols: int, N: int):
    return jax.jit(functools.partial(gc_mask, num_cols, N))


# -- host-vectorized twin ----------------------------------------------------

def gc_mask_host(num_cols: int, s, cutoff_planes) -> "np.ndarray":
    """Numpy twin of gc_mask (reduceat segment reductions) for unions
    small enough that a device round trip costs more than the mask: on
    the tunnel link every dispatch pays a ~100ms fetch fence plus a
    ~4B/row index upload, while these ~15 vectorized passes measure
    ~50ms at a 0.5M-row union (scaling linearly — the crossover sits at
    a few million rows; storage.tpu_engine.HOST_GC_MASK_MAX). The
    device kernel is the route above it; both paths must return
    identical masks (pinned by the compaction oracle tests, which force
    each route)."""
    import numpy as np

    ht_hi, ht_lo = s["ht_hi"], s["ht_lo"]
    N = ht_hi.shape[0]
    c_hi, c_lo, ce_hi, ce_lo = (int(x) for x in cutoff_planes)

    def le2s(a_hi, a_lo, b_hi, b_lo):
        return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))

    gs_idx = np.flatnonzero(s["new_group"])
    sizes = np.diff(np.append(gs_idx, N))

    def seg_max(vals):
        return np.repeat(np.maximum.reduceat(vals, gs_idx), sizes)

    def seg_min(vals):
        return np.repeat(np.minimum.reduceat(vals, gs_idx), sizes)

    visible = le2s(ht_hi, ht_lo, c_hi, c_lo)
    sentinel = np.int32(-2**31)
    vt = visible & s["tomb"]
    t_hi_r = seg_max(np.where(vt, ht_hi, sentinel))
    t_lo_r = seg_max(np.where(vt & (ht_hi == t_hi_r), ht_lo, sentinel))
    has_tomb = t_hi_r != sentinel
    shadowed = has_tomb & le2s(ht_hi, ht_lo, t_hi_r, t_lo_r)
    alive = visible & ~s["tomb"] & ~shadowed

    ridx = np.arange(N, dtype=np.int64)
    imax = np.int64(np.iinfo(np.int64).max)
    is_contrib = np.zeros(N, dtype=bool)
    for c in range(num_cols):
        first = seg_min(np.where(alive & s["set_"][c], ridx, imax))
        is_contrib |= first == ridx
    expired = le2s(s["exp_hi"], s["exp_lo"], ce_hi, ce_lo)
    lfirst = seg_min(np.where(alive & s["live"] & ~expired, ridx, imax))
    is_contrib |= lfirst == ridx

    new_span = s["new_group"] | np.concatenate(
        [[True], (ht_hi[1:] != ht_hi[:-1]) | (ht_lo[1:] != ht_lo[:-1])])
    span_idx = np.flatnonzero(new_span)
    span_sizes = np.diff(np.append(span_idx, N))
    kept_contrib = np.repeat(
        np.maximum.reduceat(is_contrib.astype(np.int8), span_idx),
        span_sizes) > 0

    newer = ~visible
    return newer | (kept_contrib & ~le2s(ht_hi, ht_lo, t_hi_r, t_lo_r))


# -- resident-plane variant --------------------------------------------------

_PAD_ZLO = -(1 << 31)  # low plane of value 0 (bias-flipped)


@compile_contract("resident_gc_mask", max_compiles=64)
@jax.jit
def resident_gc_mask(runs_planes, idx, new_group, cutoff_planes):
    """gc_mask over the merge order WITHOUT shipping the union's planes:
    the runs' planes are already HBM-resident (ops.device_run), so the
    host uploads only the sorted row-index vector (idx[i] = flat index
    into the concatenation of the runs' flattened planes; -1 = padding,
    synthesized as hybrid-time-0 non-contributors) plus the new_group
    bits. Cuts per-compaction host->device traffic ~10x (measured: the
    upload WAS the compaction critical path on the tunnel link).

    runs_planes: tuple of {ht_hi, ht_lo, exp_hi, exp_lo, tomb, live:
    [B, R] device arrays; sets: tuple of per-column set planes}.
    """
    pads = idx < 0
    safe = jnp.maximum(idx, 0)

    def dec(r, leaf):
        # Encoded resident planes (--tpu_plane_encoding) decode inline;
        # tomb always carries block dims (bits or plain), giving the
        # run's (B, R) for block-dimension-free leaves (const).
        B, R = encodings.leaf_dims(r["tomb"])
        return encodings.decode_leaf(leaf, B, R).reshape(-1)

    def take(name, fill):
        cat = jnp.concatenate([dec(r, r[name]) for r in runs_planes])
        return jnp.where(pads, jnp.asarray(fill, cat.dtype), cat[safe])

    s = {
        "new_group": new_group,
        "ht_hi": take("ht_hi", 0),
        "ht_lo": take("ht_lo", _PAD_ZLO),
        "exp_hi": take("exp_hi", 0),
        "exp_lo": take("exp_lo", _PAD_ZLO),
        "tomb": take("tomb", False),
        "live": take("live", False),
    }
    num_cols = len(runs_planes[0]["sets"])
    sets = []
    for c in range(num_cols):
        cat = jnp.concatenate([dec(r, r["sets"][c])
                               for r in runs_planes])
        sets.append(jnp.where(pads, False, cat[safe]))
    s["set_"] = (jnp.stack(sets) if sets
                 else jnp.zeros((0, idx.shape[0]), jnp.bool_))
    return gc_mask(num_cols, idx.shape[0], s, cutoff_planes)
