"""Pallas TPU kernel: flat-run MVCC aggregate fold.

The hottest all-device loop — visibility resolution + predicate mask +
exact integer aggregation over a whole run — written as a Pallas grid
kernel (VMEM-tiled blocks over the plane arrays, scalar-prefetched read
point/bounds/literals, one int32 partial row per grid step). It computes
EXACTLY what ops.scan's flat path + ops.agg_fold compute for eligible
signatures: COUNT(*) / COUNT(col), exact SUM over int32/int64 columns
(16-bit limb partials), and MIN/MAX over int32/int64 ordered planes,
under device-exact i32/i64 predicates, on single-version-per-key runs.
The XLA path remains the default and the oracle; the flag
``tpu_engine_use_pallas`` routes eligible aggregate scans here
(tests pin both paths to identical results; interpret mode covers CPU).

Layout notes (pallas_guide.md): blocks are (8 tablet-blocks x R rows) so
the sublane dimension meets the (8, 128) int32 tile minimum and R (a
multiple of 128) fills lanes; the output is one (1, 128) partial row per
grid step — host-side numpy folds the tiny [G, 128] matrix.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from yugabyte_db_tpu.ops.scan import I32_MAX, I32_MIN, AggSig, PredSig
from yugabyte_db_tpu.utils.jitting import compile_contract

BLOCKS_PER_STEP = 8
OUT_LANES = 128

# per-aggregate slots in the partial row (after [count, scanned]):
#   count(col): 1 (masked count)
#   sum:        5 (4 limbs + n)
#   min/max:    3 (hi, lo, n)
_SLOTS = {"count": 1, "sum": 5, "min": 3, "max": 3}


def eligible(sig_flat: bool, aggs, preds) -> bool:
    """Kernel applicability: flat run, i32/i64 aggregates, i32/i64
    equality/range predicates."""
    if not sig_flat or not aggs:
        return False
    for ag in aggs:
        if ag.fn == "count":
            continue
        if ag.fn not in ("sum", "min", "max") or ag.kind not in ("i32",
                                                                 "i64"):
            return False
    return all(p.kind in ("i32", "i64") and p.op != "IN" for p in preds)


def _le2(a_hi, a_lo, b_hi, b_lo):
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


def _pred_mask(ps: PredSig, hi, lo, lit_hi, lit_lo):
    if ps.kind == "i32":
        v, x = hi, lit_hi
        return {"=": v == x, "!=": v != x, "<": v < x, "<=": v <= x,
                ">": v > x, ">=": v >= x}[ps.op]
    eq = (hi == lit_hi) & (lo == lit_lo)
    lt = (hi < lit_hi) | ((hi == lit_hi) & (lo < lit_lo))
    return {"=": eq, "!=": ~eq, "<": lt, "<=": lt | eq,
            ">": ~(lt | eq), ">=": ~lt}[ps.op]


def _scalar(x):
    return jnp.reshape(x.astype(jnp.int32), (1, 1))


def _kernel(aggs, preds, col_order, R, iparams_ref, *refs):
    """One grid step: resolve an (8 x R)-row slab, emit one partial row.

    refs layout: ht_hi, ht_lo, exp_hi, exp_lo, valid, tomb, live, then
    per column in col_order: set_, isnull, plane0[, plane1], and finally
    the output ref.
    """
    out_ref = refs[-1]
    ht_hi, ht_lo, exp_hi, exp_lo, valid8, tomb8, live8 = refs[:7]
    cols = {}
    i = 7
    for cid, two_plane in col_order:
        set_c = refs[i][:] != 0
        null_c = refs[i + 1][:] != 0
        p0 = refs[i + 2][:]
        p1 = refs[i + 3][:] if two_plane else None
        i += 3 + (1 if two_plane else 0)
        cols[cid] = (set_c, null_c, p0, p1)

    row_lo, row_hi = iparams_ref[0], iparams_ref[1]
    read_hi, read_lo = iparams_ref[2], iparams_ref[3]
    rexp_hi, rexp_lo = iparams_ref[4], iparams_ref[5]

    valid = valid8[:] != 0
    visible = valid & _le2(ht_hi[:], ht_lo[:], read_hi, read_lo)
    expired = _le2(exp_hi[:], exp_lo[:], rexp_hi, rexp_lo)
    alive = visible & (tomb8[:] == 0)
    live_exists = alive & (live8[:] != 0) & ~expired

    notnull = {}
    exists = live_exists
    for cid, (set_c, null_c, _p0, _p1) in cols.items():
        nn = alive & set_c & ~null_c & ~expired
        notnull[cid] = nn
        exists = exists | nn

    g = pl.program_id(0)
    sub = jax.lax.broadcasted_iota(jnp.int32, (BLOCKS_PER_STEP, R), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (BLOCKS_PER_STEP, R), 1)
    rowidx = (g * BLOCKS_PER_STEP + sub) * R + lane
    in_range = (rowidx >= row_lo) & (rowidx < row_hi)

    pre = exists & in_range & valid
    mask = pre
    li = 6
    for ps in preds:
        _s, _n, p0, p1 = cols[ps.col_id]
        lit_hi = iparams_ref[li]
        lit_lo = iparams_ref[li + 1] if ps.kind != "i32" else lit_hi
        li += 1 if ps.kind == "i32" else 2
        mask = mask & notnull[ps.col_id] & _pred_mask(ps, p0, p1, lit_hi,
                                                     lit_lo)

    parts = [_scalar(jnp.sum(mask.astype(jnp.int32))),
             _scalar(jnp.sum(pre.astype(jnp.int32)))]
    for ag in aggs:
        if ag.fn == "count":
            m = mask if ag.col_id is None else (mask & notnull[ag.col_id])
            parts.append(_scalar(jnp.sum(m.astype(jnp.int32))))
            continue
        m = mask & notnull[ag.col_id]
        _s, _n, p0, p1 = cols[ag.col_id]
        n = _scalar(jnp.sum(m.astype(jnp.int32)))
        if ag.fn == "sum":
            if ag.kind == "i32":
                u = p0.astype(jnp.uint32) ^ jnp.uint32(0x80000000)
                limbs = [(u & jnp.uint32(0xFFFF)).astype(jnp.int32),
                         (u >> jnp.uint32(16)).astype(jnp.int32),
                         jnp.zeros_like(p0), jnp.zeros_like(p0)]
            else:
                hi_u = p0.astype(jnp.uint32) ^ jnp.uint32(0x80000000)
                lo_u = p1.astype(jnp.uint32) ^ jnp.uint32(0x80000000)
                limbs = [(lo_u & jnp.uint32(0xFFFF)).astype(jnp.int32),
                         (lo_u >> jnp.uint32(16)).astype(jnp.int32),
                         (hi_u & jnp.uint32(0xFFFF)).astype(jnp.int32),
                         (hi_u >> jnp.uint32(16)).astype(jnp.int32)]
            for limb in limbs:
                parts.append(_scalar(jnp.sum(jnp.where(m, limb, 0))))
            parts.append(n)
        else:
            is_max = ag.fn == "max"
            red = jnp.max if is_max else jnp.min
            fill = I32_MIN if is_max else I32_MAX
            hi_src = p0
            mhi = red(jnp.where(m, hi_src, fill))
            if ag.kind == "i32":
                parts.append(_scalar(mhi))
                parts.append(_scalar(jnp.int32(0)))
            else:
                tie = m & (hi_src == mhi)
                mlo = red(jnp.where(tie, p1, fill))
                parts.append(_scalar(mhi))
                parts.append(_scalar(mlo))
            parts.append(n)
    row = jnp.concatenate(parts, axis=1)
    pad = OUT_LANES - row.shape[1]
    padded = jnp.concatenate(
        [row, jnp.zeros((1, pad), jnp.int32)], axis=1)
    # TPU block shapes need sublane-divisible dims: the output block is
    # (1, 8, 128) with the partial row broadcast across the 8 sublanes
    # (the host reads sublane 0)
    out_ref[:] = jnp.broadcast_to(padded, (8, OUT_LANES))[None]


@functools.lru_cache(maxsize=64)
@compile_contract("pallas_flat_aggregate", max_compiles=64)
def compiled_flat_aggregate(B: int, R: int, aggs: tuple, preds: tuple,
                            col_order: tuple, interpret: bool = False):
    """Build the pallas program for one static signature.

    col_order: tuple[(col_id, two_plane)] — the columns shipped, in ref
    order. Returns fn(plane_arrays_list, iparams) -> [G, 128] int32.
    """
    if B % BLOCKS_PER_STEP != 0:
        raise ValueError(f"B={B} not a multiple of {BLOCKS_PER_STEP}")
    grid = (B // BLOCKS_PER_STEP,)
    n_tensor = 7 + sum(3 + (1 if tp else 0) for _cid, tp in col_order)
    # with scalar prefetch, index maps receive (grid idx, scalar ref)
    block = pl.BlockSpec((BLOCKS_PER_STEP, R),
                         lambda g, _sref: (g, 0))
    kernel = functools.partial(_kernel, aggs, preds, col_order, R)

    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[block] * n_tensor,
        out_specs=pl.BlockSpec((1, 8, OUT_LANES),
                               lambda g, _sref: (g, 0, 0)),
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((grid[0], 8, OUT_LANES),
                                       jnp.int32),
        interpret=interpret,
    )

    def fn(tensors, iparams):
        return call(iparams, *tensors)

    return jax.jit(fn)


def gather_tensors(dev_arrays, col_order):
    """The plane arrays in kernel ref order. Bool planes ship as int32:
    v5e mosaic restricts sub-32-bit compares and int8 tiles need 32
    sublanes (the block here has 8). Compressed runs
    (--tpu_plane_encoding) materialize decoded planes here: the pallas
    refs are raw tiled arrays, so the decoded tensors live as a cached
    side-car on the run's residency entry instead of decoding in-kernel."""
    from yugabyte_db_tpu.ops import encodings

    if encodings.tree_encoded(dev_arrays):
        dev_arrays = jax.jit(encodings.decode_run)(dev_arrays)

    def b2i(a):
        return a.astype(jnp.int32)

    out = [dev_arrays["ht_hi"], dev_arrays["ht_lo"],
           dev_arrays["exp_hi"], dev_arrays["exp_lo"],
           b2i(dev_arrays["valid"]), b2i(dev_arrays["tomb"]),
           b2i(dev_arrays["live"])]
    for cid, two_plane in col_order:
        c = dev_arrays["cols"][cid]
        out.append(b2i(c["set"]))
        out.append(b2i(c["isnull"]))
        out.append(c["cmp"][:, :, 0])
        if two_plane:
            out.append(c["cmp"][:, :, 1])
    return out


def combine_partials(partials: np.ndarray, aggs) -> tuple:
    """[G, 8, 128] int32 partial rows (sublane 0 carries the data) ->
    (count, scanned, per-agg value)."""
    partials = partials[:, 0, :]
    count = int(partials[:, 0].sum())
    scanned = int(partials[:, 1].sum())
    vals = []
    off = 2
    for ag in aggs:
        if ag.fn == "count":
            vals.append(int(partials[:, off].sum()))
            off += 1
            continue
        if ag.fn == "sum":
            limbs = partials[:, off:off + 4].astype(object).sum(axis=0)
            n = int(partials[:, off + 4].sum())
            off += 5
            u = sum(int(d) << (16 * k) for k, d in enumerate(limbs))
            if ag.kind == "i32":
                vals.append(u - n * (1 << 31) if n else None)
            else:
                vals.append(u - n * (1 << 63) if n else None)
            continue
        his = partials[:, off]
        los = partials[:, off + 1]
        ns = partials[:, off + 2]
        off += 3
        live = ns > 0
        if not live.any():
            vals.append(None)
            continue
        pairs = list(zip(his[live].tolist(), los[live].tolist()))
        best = max(pairs) if ag.fn == "max" else min(pairs)
        if ag.kind == "i32":
            vals.append(best[0])
        else:
            from yugabyte_db_tpu.utils import planes as P

            vals.append(int(P.ordered_planes_to_i64(
                np.array([best[0]], np.int32),
                np.array([best[1]], np.int32))[0]))
    return count, scanned, vals
