"""Integration harnesses: in-process clusters for tests and tools.

Reference analog: src/yb/integration-tests/ — MiniCluster
(mini_cluster.h:92-106) runs real masters + tservers in one process;
ExternalMiniCluster adds kill/restart. Here LocalTransport isolation plays
the kill role, and the socket transport runs the same daemons over real
loopback TCP.
"""

from yugabyte_db_tpu.integration.mini_cluster import MiniCluster

__all__ = ["MiniCluster"]
