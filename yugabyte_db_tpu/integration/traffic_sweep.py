"""Sustained-traffic replay harness: seeded mixed-protocol traffic
against a live mini-cluster while tablets split, leaders move, and
followers roll — invariants and latency SLOs checked per round.

Reference analog: the sustained-workload integration tests of
src/yb/integration-tests (tablet-split-itest.cc driving splits under
load, load_balancer-test.cc asserting leader moves) crossed with the
YCSB/TPC-H workload shapes the reference benchmarks against.

The generator is OPEN-LOOP and fully seeded: one ``random.Random(seed)``
drives the protocol mix, the zipfian key choice, and every written
value, so any failing sweep replays byte-for-byte from its seed
(``python -m yugabyte_db_tpu.integration.traffic_sweep <seed>``).

Protocol mix (zipfian hot keys, exponent 0.99):

==========  ==============================================================
``ycsb_a``  50/50 point read / upsert (YCSB workload A: update-heavy).
``ycsb_b``  95/5 point read / upsert (YCSB workload B: read-mostly).
``ycsb_e``  Short paged range scans (LIMIT 10) with 5% inserts
            (YCSB workload E: scan-heavy).
``tpch``    Aggregate pushdown shaped like TPC-H Q1 (sum/count/avg over
            the whole table) and Q6 (sum under a range predicate).
``redis``   RESP SET/GET through the in-process Redis service (its own
            ``redis`` table, the port-6379 proxy path).
==========  ==============================================================

Mid-stream cluster events, one catalog entry per round:

- **Round 0** — the first seed tablet is split through the
  ``master.split_tablet`` RPC from a background thread while the op
  loop keeps running (the seal -> fork -> seed -> commit protocol races
  live traffic; writes re-route per-row, reads re-plan from refreshed
  locations).
- **Round 1** — the second seed tablet splits the same way while a
  FOLLOWER-heavy tserver is stopped and restarted mid-round (rolling
  restart under load: bootstrap replay + catch-up while the split's
  child tablets elect leaders).
- **Round 2** — every traffic-table leader is piled onto one tserver
  (stepdown skew), then forced ``master.rebalance`` passes walk the
  spread back under 2, one leader move per pass.

Invariants after every round (fault-sweep contract):

1. **No acked write lost** — every acknowledged SQL and Redis write is
   visible at its exact value; writes whose ack was lost to a restart
   hold either the old or attempted value, never anything else.
2. **No leaked residency pins** — ``hbm_cache().pinned_bytes() == 0``
   once quiesced (split forks/seeds must unwind their pins).
3. **MemTracker baseline** — the device subtree returns to its anchor
   (re-anchored after each committed split: child-tablet residency is
   legitimate; anything above it is a leak).

Final checks: at least ``min_splits`` splits and one leader move
actually happened mid-stream; the post-split full scan and the Q1/Q6
aggregates are byte-identical to a no-split CPU-oracle replay of the
same seed (the oracle dict IS that replay: the same seeded op stream
applied to a plain dict); per-protocol p50/p99 latency SLOs hold.
"""

from __future__ import annotations

import bisect
import json
import random
import threading
import time

from yugabyte_db_tpu.client.client import TabletOpFailed
from yugabyte_db_tpu.client.session import YBSession
from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.residency import hbm_cache
from yugabyte_db_tpu.storage.scan_spec import AggSpec, Predicate, ScanSpec
from yugabyte_db_tpu.utils.memtracker import root_tracker
from yugabyte_db_tpu.utils.metrics import (count_swallowed,
                                           observe_request_latency)
from yugabyte_db_tpu.utils.status import TabletSplit

PROTOCOLS = ("ycsb_a", "ycsb_b", "ycsb_e", "tpch", "redis")

# Cumulative protocol mix (rng.random() thresholds): A 30%, B 25%,
# E 15%, TPC-H 10%, Redis 20%.
_MIX = (("ycsb_a", 0.30), ("ycsb_b", 0.55), ("ycsb_e", 0.70),
        ("tpch", 0.80), ("redis", 1.00))

# Per-protocol p99 ceilings (seconds). Generous for CI: an op that
# lands in a split's seal->commit window legitimately spins on 50ms
# re-plan sleeps until the commit swap, and on a loaded CI box the
# whole seal->seed->commit protocol can take several seconds — these
# bound tail damage, not steady-state latency.
SLO_P99_S = {"ycsb_a": 10.0, "ycsb_b": 10.0, "ycsb_e": 20.0,
             "tpch": 20.0, "redis": 10.0}
SLO_P50_S = {p: 2.0 for p in PROTOCOLS}

ABSENT = object()


def _percentile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, int(q * len(s)))
    return s[idx]


class _Zipf:
    """Seeded zipfian sampler over ``n`` ranks (exponent ~0.99): the
    YCSB hot-key distribution, so splits land on genuinely skewed
    traffic rather than uniform keys."""

    def __init__(self, n: int, theta: float = 0.99):
        acc, self._cdf = 0.0, []
        for rank in range(1, n + 1):
            acc += 1.0 / rank ** theta
            self._cdf.append(acc)
        self._total = acc

    def sample(self, rng: random.Random) -> int:
        return bisect.bisect_left(self._cdf, rng.random() * self._total)


class TrafficSweep:
    """One seeded sweep: a MiniCluster with a TPU-engine traffic table
    plus the Redis service, a mixed open-loop workload, one cluster
    event per round, invariants + SLOs after each. ``run()`` returns
    the TRAFFIC_METRICS summary dict or raises AssertionError with
    every violation (prefixed by the seed)."""

    def __init__(self, data_root: str, seed: int, rounds: int = 3,
                 ops_per_round: int = 60, keyspace: int = 96,
                 num_tservers: int = 3, num_tablets: int = 2,
                 min_splits: int = 2):
        self.data_root = data_root
        self.seed = seed
        self.rounds = rounds
        self.ops_per_round = ops_per_round
        self.keys = [f"u{i:05d}" for i in range(keyspace)]
        self.num_tservers = num_tservers
        self.num_tablets = num_tablets
        self.min_splits = min_splits
        self.rng = random.Random(seed)
        self.zipf = _Zipf(keyspace)
        # SQL oracle: key -> last acked value; ambiguous: key -> set of
        # acceptable values while an ack was lost (fault-sweep contract).
        self.oracle: dict[str, object] = {}
        self.ambiguous: dict[str, set] = {}
        # Redis oracle (its own keyspace in the redis table).
        self.r_oracle: dict[str, object] = {}
        self.r_ambiguous: dict[str, set] = {}
        self._next_value = 0
        self.latencies: dict[str, list[float]] = {p: [] for p in PROTOCOLS}
        self.ops_done: dict[str, int] = {p: 0 for p in PROTOCOLS}
        # Ops that timed out client-side (split stalled past the
        # re-plan deadline by a concurrent restart, or every replica
        # of a tablet unreachable). Bounded in _final_checks.
        self.aborted: dict[str, int] = {p: 0 for p in PROTOCOLS}
        self.splits: list[dict] = []
        self.leader_moves: list[dict] = []
        self.errors: list[str] = []
        self.mc: MiniCluster | None = None
        self.client = None
        self.table = None
        self.redis = None

    # -- lifecycle -----------------------------------------------------------

    def setup(self) -> None:
        from yugabyte_db_tpu.yql.redis.server import RedisServiceImpl

        self.mc = MiniCluster(
            self.data_root, num_tservers=self.num_tservers,
            engine_options={"breaker_cooldown_s": 0.05,
                            "breaker_failure_threshold": 1}).start()
        self.mc.wait_tservers_registered()
        self.client = self.mc.client()
        self.client.create_table("traffic", [
            ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
            ColumnSchema("v", DataType.INT64)],
            num_tablets=self.num_tablets, engine="tpu")
        self.table = self.client.open_table("traffic")
        self.redis = RedisServiceImpl(self.mc.client("traffic-redis"),
                                      num_tablets=2)
        # Pre-fill so the first split has a populated median to cut at.
        s = YBSession(self.client)
        for k in self.keys:
            v = self._bump_value()
            s.insert(self.table, {"k": k, "v": v})
            self.oracle[k] = v
        s.flush()
        self._flush_tablets()
        self._scan_cluster()  # warm the device path
        self._anchor_baseline()
        # The two seed tablets, in partition order: the rounds split
        # them one per round while traffic runs.
        locs = self.client.meta_cache.locations("traffic", refresh=True)
        self.seed_tablets = [t.tablet_id for t in locs.tablets]

    def teardown(self) -> None:
        if self.mc is not None:
            self.mc.shutdown()
            self.mc = None

    def run(self) -> dict:
        self.setup()
        try:
            t0 = time.monotonic()
            for rnd in range(self.rounds):
                self._run_round(rnd)
                self.errors.extend(
                    f"round {rnd} (seed {self.seed}): {e}"
                    for e in self.check_invariants())
            self._traffic_s = time.monotonic() - t0
            self.errors.extend(f"final (seed {self.seed}): {e}"
                               for e in self._final_checks())
            if self.errors:
                raise AssertionError(
                    "traffic sweep invariants violated:\n  "
                    + "\n  ".join(self.errors))
            return self._metrics()
        finally:
            self.teardown()

    # -- rounds --------------------------------------------------------------

    def _run_round(self, rnd: int) -> None:
        splitter = None
        event_at = self.ops_per_round // 3
        restart_at = (2 * self.ops_per_round) // 3
        victim = None
        for i in range(self.ops_per_round):
            if i == event_at:
                if rnd < min(2, len(self.seed_tablets)):
                    splitter = self._fire_split(self.seed_tablets[rnd])
                elif rnd == 2:
                    self._skew_and_rebalance()
            if rnd == 1 and i == restart_at:
                victim = self._stop_follower_heavy()
            self._one_op()
        if victim is not None:
            self.mc.restart_tserver(victim)
            self.mc.wait_tservers_registered()
        if splitter is not None:
            splitter.join(timeout=60.0)
            # Child tablets bring their own (legitimate) device
            # residency: re-anchor so the baseline check measures
            # leaks, not the split.
            self._anchor_baseline()

    def _fire_split(self, tablet_id: str) -> threading.Thread:
        """Split ``tablet_id`` through the admin RPC from a background
        thread — the protocol races the op loop's live traffic."""

        def run():
            try:
                resp = self.client.master_rpc(
                    "master.split_tablet",
                    {"table": "traffic", "tablet_id": tablet_id,
                     "timeout": 45.0}, timeout_s=55.0)
            except Exception as e:  # noqa: BLE001 — surfaced as a failure
                self.errors.append(f"split {tablet_id} died: {e!r}")
                return
            if resp.get("code") != "ok":
                self.errors.append(f"split {tablet_id} failed: {resp}")
                return
            self.splits.append({"parent": tablet_id,
                                "children": resp.get("children", [])})

        t = threading.Thread(target=run, name=f"split-{tablet_id}",
                             daemon=True)
        t.start()
        return t

    def _stop_follower_heavy(self) -> str:
        """Stop the tserver holding the FEWEST leaders (a follower-heavy
        roll: quorum holds, in-flight ops retry through live leaders)."""
        counts = {
            uuid: sum(1 for p in ts.tablet_manager.peers()
                      if p.is_leader())
            for uuid, ts in self.mc.tservers.items()}
        victim = min(counts, key=counts.get)
        self.mc.stop_tserver(victim)
        return victim

    def _skew_and_rebalance(self) -> None:
        """Pile every traffic-table leader onto one tserver, then let
        forced balancer passes walk the spread back under 2 — each pass
        moves at most one leader (the churn bound)."""
        target = self.mc.tserver_uuids[0]
        locs = self.client.meta_cache.locations("traffic", refresh=True)
        for t in locs.tablets:
            leader = t.leader
            if leader == target or target not in t.replicas:
                continue
            try:
                resp = self.client.transport.send(
                    leader or t.replicas[0], "ts.transfer_leadership",
                    {"tablet_id": t.tablet_id, "target": target},
                    timeout=5.0)
                if resp.get("code") != "ok":
                    count_swallowed("traffic.skew_transfer",
                                    resp.get("code"))
            except Exception as e:  # noqa: BLE001 — skew is best-effort
                count_swallowed("traffic.skew_transfer", e)
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline:
            resp = self.client.master_rpc("master.rebalance", {},
                                          timeout_s=10.0)
            move = resp.get("move")
            if move:
                self.leader_moves.append(move)
            elif self.leader_moves:
                return  # balanced: spread walked back under 2
            # Pace to the heartbeat interval either way: the balancer's
            # skew input is heartbeat-fed, so a tight loop would keep
            # re-moving against a stale count.
            time.sleep(0.3)
        if not self.leader_moves:
            self.errors.append("rebalance made no leader move")

    # -- one op --------------------------------------------------------------

    def _one_op(self) -> None:
        r = self.rng.random()
        for proto, ceil in _MIX:
            if r < ceil:
                break
        t0 = time.monotonic()
        try:
            getattr(self, "_op_" + proto)()
        except (TabletOpFailed, TabletSplit) as e:
            # Client-visible timeout: a restart landing mid-split can
            # stall the seal->commit window past the re-plan deadline,
            # and a real client's op times out. Reads return nothing
            # to check; writes that got this far never reached flush
            # (flush failures are already recorded as ambiguous by the
            # op itself). Count it — SLOs measure completed ops, and
            # _final_checks bounds the abort fraction so a systemic
            # outage still fails the sweep.
            self.aborted[proto] += 1
            count_swallowed("traffic.op_aborted", e)
            return
        dt = time.monotonic() - t0
        self.latencies[proto].append(dt)
        self.ops_done[proto] += 1
        observe_request_latency(proto, dt)

    def _zkey(self) -> str:
        return self.keys[self.zipf.sample(self.rng)]

    def _op_ycsb_a(self) -> None:
        self._kv_op(read_ratio=0.5)

    def _op_ycsb_b(self) -> None:
        self._kv_op(read_ratio=0.95)

    def _kv_op(self, read_ratio: float) -> None:
        k = self._zkey()
        if self.rng.random() < read_ratio:
            row = YBSession(self.client).get(self.table, {"k": k})
            actual = row[1] if row else ABSENT
            acceptable = self.ambiguous.get(k) or {
                self.oracle.get(k, ABSENT)}
            if actual not in acceptable:
                self.errors.append(
                    f"read {k} = "
                    f"{'ABSENT' if actual is ABSENT else actual}, "
                    f"acceptable {sorted(map(str, acceptable))}")
            return
        v = self._bump_value()
        s = YBSession(self.client)
        s.insert(self.table, {"k": k, "v": v})
        try:
            s.flush()
        except Exception:  # noqa: BLE001 — ack lost; outcome ambiguous
            self.ambiguous[k] = {self._current(k), v}
            return
        self.oracle[k] = v
        self.ambiguous.pop(k, None)

    def _op_ycsb_e(self) -> None:
        if self.rng.random() < 0.05:
            self._kv_op(read_ratio=0.0)
            return
        res = YBSession(self.client).scan(
            self.table, ScanSpec(projection=["k", "v"], limit=10))
        if not res.rows:
            self.errors.append("ycsb_e: empty first page on a "
                               "pre-filled table")

    def _op_tpch(self) -> None:
        spec = self._tpch_spec(self.rng.random() < 0.5)
        res = YBSession(self.client).scan(self.table, spec)
        if not res.rows:
            self.errors.append("tpch: aggregate returned no row")

    def _tpch_spec(self, q1: bool) -> ScanSpec:
        if q1:  # Q1 shape: full-table sum/count/avg
            return ScanSpec(aggregates=[
                AggSpec("sum", "v"), AggSpec("count", None),
                AggSpec("avg", "v")])
        # Q6 shape: sum under a selective range predicate
        return ScanSpec(
            predicates=[Predicate("v", ">=", self._next_value // 2)],
            aggregates=[AggSpec("sum", "v"), AggSpec("count", None)])

    def _op_redis(self) -> None:
        k = "r" + self._zkey()
        if self.rng.random() < 0.5:
            reply = self.redis.handle([b"GET", k.encode()])
            actual = self._resp_bulk(reply)
            acceptable = self.r_ambiguous.get(k) or {
                self.r_oracle.get(k, ABSENT)}
            if actual not in acceptable:
                self.errors.append(
                    f"redis GET {k} = {actual!r}, acceptable "
                    f"{sorted(map(str, acceptable))}")
            return
        v = str(self._bump_value())
        try:
            reply = self.redis.handle([b"SET", k.encode(), v.encode()])
        except (TabletOpFailed, TabletSplit):
            # The SET may or may not have applied before the timeout —
            # record the ambiguity, then let _one_op count the abort.
            self.r_ambiguous[k] = {self._r_current(k), v}
            raise
        if reply.startswith(b"+OK"):
            self.r_oracle[k] = v
            self.r_ambiguous.pop(k, None)
        else:
            self.r_ambiguous[k] = {self._r_current(k), v}

    @staticmethod
    def _resp_bulk(reply: bytes):
        """Decode a RESP bulk-string reply (``$-1`` -> ABSENT)."""
        if reply.startswith(b"$-1"):
            return ABSENT
        if not reply.startswith(b"$"):
            return f"<resp {reply[:40]!r}>"
        body = reply.split(b"\r\n", 1)[1]
        return body[: int(reply[1:reply.index(b"\r")])].decode()

    def _current(self, k: str):
        amb = self.ambiguous.get(k)
        return next(iter(amb)) if amb else self.oracle.get(k, ABSENT)

    def _r_current(self, k: str):
        amb = self.r_ambiguous.get(k)
        return next(iter(amb)) if amb else self.r_oracle.get(k, ABSENT)

    def _bump_value(self) -> int:
        self._next_value += 1
        return self._next_value

    # -- cluster access ------------------------------------------------------

    def _scan_cluster(self) -> dict:
        res = YBSession(self.client).scan(
            self.table, ScanSpec(projection=["k", "v"]))
        return dict(res.rows)

    def _flush_tablets(self) -> None:
        for ts in self.mc.tservers.values():
            for peer in ts.tablet_manager.peers():
                peer.flush()

    def _quiesce_device(self) -> None:
        for ts in self.mc.tservers.values():
            for peer in ts.tablet_manager.peers():
                eng = peer.tablet.engine
                if hasattr(eng, "_drop_overlay_cache"):
                    eng._drop_overlay_cache()
            if hasattr(ts, "mesh_scan"):
                ts.mesh_scan.drop_stacks()
        hbm_cache().evict_unpinned()

    def _anchor_baseline(self) -> None:
        self._quiesce_device()
        self._device_baseline = root_tracker().child("device").consumption

    # -- invariants ----------------------------------------------------------

    def check_invariants(self) -> list[str]:
        errs = []
        errs.extend(self.check_acked_writes())
        errs.extend(self.check_residency_pins())
        errs.extend(self.check_memtracker_baseline())
        return errs

    def check_acked_writes(self) -> list[str]:
        got = self._scan_cluster()
        errs = []
        for k in self.keys:
            actual = got.get(k, ABSENT)
            acceptable = self.ambiguous.get(k) or {
                self.oracle.get(k, ABSENT)}
            if actual not in acceptable:
                errs.append(
                    f"acked write lost: {k} = "
                    f"{'ABSENT' if actual is ABSENT else actual}")
        for k in got:
            if k not in self.keys:
                errs.append(f"phantom row {k!r}")
        for k, v in self.r_oracle.items():
            if k in self.r_ambiguous:
                continue
            actual = self._resp_bulk(self.redis.handle([b"GET",
                                                        k.encode()]))
            if actual != v:
                errs.append(f"redis acked write lost: {k} = {actual!r}, "
                            f"want {v!r}")
        return errs

    def check_residency_pins(self) -> list[str]:
        self._quiesce_device()
        pinned = hbm_cache().pinned_bytes()
        external = self._external_bytes()
        if pinned > external:
            return [f"leaked residency pins: {pinned} pinned bytes "
                    f"({external} external)"]
        return []

    def _external_bytes(self) -> int:
        cache = hbm_cache()
        with cache._lock:
            return sum(e.total_bytes
                       for pool in cache._pools.values()
                       for e in pool.values() if e.external)

    def check_memtracker_baseline(self) -> list[str]:
        self._quiesce_device()
        dev = root_tracker().child("device").consumption
        if dev != self._device_baseline:
            return [f"device MemTracker not back to baseline: {dev} "
                    f"(baseline {self._device_baseline})"]
        return []

    # -- final checks --------------------------------------------------------

    def _final_checks(self) -> list[str]:
        errs = []
        if len(self.splits) < self.min_splits:
            errs.append(f"only {len(self.splits)} splits fired "
                        f"(want >= {self.min_splits})")
        if self.rounds >= 3 and not self.leader_moves:
            errs.append("no leader move happened mid-stream")
        total = sum(self.ops_done.values())
        aborted = sum(self.aborted.values())
        if aborted > max(2, (total + aborted) // 5):
            errs.append(f"{aborted}/{total + aborted} ops aborted "
                        "(client-visible timeouts) — systemic, not a "
                        "split stall")
        errs.extend(self._check_oracle_identity())
        errs.extend(self._check_slos())
        return errs

    def _check_oracle_identity(self) -> list[str]:
        """Post-split results must be byte-identical to the no-split
        CPU-oracle replay of the same seed. The oracle dict IS that
        replay (the same seeded op stream applied to a plain dict), so:
        re-fix any ack-ambiguous key with a fresh acked write, then
        byte-compare the full scan AND the Q1/Q6 aggregates against
        oracle-computed answers."""
        errs = []
        for k in sorted(self.ambiguous):
            v = self._bump_value()
            s = YBSession(self.client)
            s.insert(self.table, {"k": k, "v": v})
            try:
                s.flush()
            except Exception as e:  # noqa: BLE001
                return [f"could not re-fix ambiguous key {k}: {e!r}"]
            self.oracle[k] = v
            self.ambiguous.pop(k, None)
        got = sorted(self._scan_cluster().items())
        want = sorted((k, v) for k, v in self.oracle.items()
                      if v is not ABSENT)
        if repr(got).encode() != repr(want).encode():
            miss = [k for k, v in want if dict(got).get(k) != v]
            errs.append(
                f"post-split scan diverged from CPU-oracle replay: "
                f"{len(got)} rows vs {len(want)} "
                f"(first mismatches {miss[:5]})")
        vals = [v for _k, v in want]
        q1 = YBSession(self.client).scan(
            self.table, self._tpch_q1()).rows
        q1_want = [(sum(vals), len(vals), sum(vals) / len(vals))]
        if repr(q1).encode() != repr(q1_want).encode():
            errs.append(f"Q1 aggregate diverged: {q1} vs oracle "
                        f"{q1_want}")
        cut = self._next_value // 2
        q6 = YBSession(self.client).scan(
            self.table, self._tpch_q6(cut)).rows
        hit = [v for v in vals if v >= cut]
        q6_want = [(sum(hit) if hit else None, len(hit))]
        if repr(q6).encode() != repr(q6_want).encode():
            errs.append(f"Q6 aggregate diverged: {q6} vs oracle "
                        f"{q6_want}")
        return errs

    @staticmethod
    def _tpch_q1() -> ScanSpec:
        return ScanSpec(aggregates=[AggSpec("sum", "v"),
                                    AggSpec("count", None),
                                    AggSpec("avg", "v")])

    @staticmethod
    def _tpch_q6(cut: int) -> ScanSpec:
        return ScanSpec(predicates=[Predicate("v", ">=", cut)],
                        aggregates=[AggSpec("sum", "v"),
                                    AggSpec("count", None)])

    def _check_slos(self) -> list[str]:
        errs = []
        for proto, samples in self.latencies.items():
            if not samples:
                continue
            p50 = _percentile(samples, 0.50)
            p99 = _percentile(samples, 0.99)
            if p50 > SLO_P50_S[proto]:
                errs.append(f"{proto} p50 {p50:.3f}s > SLO "
                            f"{SLO_P50_S[proto]}s")
            if p99 > SLO_P99_S[proto]:
                errs.append(f"{proto} p99 {p99:.3f}s > SLO "
                            f"{SLO_P99_S[proto]}s")
        return errs

    # -- reporting -----------------------------------------------------------

    def _metrics(self) -> dict:
        dur = max(getattr(self, "_traffic_s", 0.0), 1e-9)
        protos = {}
        for proto, samples in self.latencies.items():
            protos[proto] = {
                "ops": self.ops_done[proto],
                "ops_per_sec": round(self.ops_done[proto] / dur, 2),
                "p50_ms": round(_percentile(samples, 0.50) * 1e3, 3),
                "p99_ms": round(_percentile(samples, 0.99) * 1e3, 3),
            }
        return {"seed": self.seed, "rounds": self.rounds,
                "traffic_s": round(dur, 3),
                "ops_per_sec": round(sum(self.ops_done.values()) / dur, 2),
                "protocols": protos,
                "splits_fired": len(self.splits),
                "split_lineage": self.splits,
                "leader_moves": len(self.leader_moves),
                "aborted_ops": sum(self.aborted.values()),
                "keys": len(self.oracle) + len(self.r_oracle)}


def run_sweep(data_root: str, seed: int, **kwargs) -> dict:
    """Run one seeded traffic sweep; returns its TRAFFIC_METRICS dict."""
    return TrafficSweep(data_root, seed, **kwargs).run()


if __name__ == "__main__":  # replay a failing seed: python -m ... <seed>
    import sys
    import tempfile

    with tempfile.TemporaryDirectory() as root:
        out = run_sweep(root, int(sys.argv[1]) if len(sys.argv) > 1
                        else 1234)
        print("TRAFFIC_METRICS " + json.dumps(out, sort_keys=True))
