"""Randomized fault-sweep harness: seeded faults against a live
mini-cluster workload, invariants checked after every round.

Reference analog: the randomized kill-testing loop of
src/yb/integration-tests (ExternalMiniClusterITest crash-point sweeps)
crossed with the fault-injection flags of util/fault_injection.h — a
seeded RNG drives both the workload and the fault schedule, so any
failing sweep replays byte-for-byte from its seed.

Each round fires one fault from the catalog mid-workload:

==================  =======================================================
``wal_sync``        ``fault.wal_sync_failed`` armed once: the next WAL
                    group-commit raises; the write's outcome is ambiguous
                    (appended-but-unsynced entries may still replicate).
``respond_dropped`` ``fault.ts_write_respond_failed`` armed once: the
                    write APPLIES but the response reports failure; the
                    client retry must dedup (exactly-once).
``leader_crash``    The tserver hosting the most leaders is stopped and
                    restarted (bootstrap replay); in-flight ops fail over.
``device_dispatch`` ``fault.tpu_dispatch`` armed once: the next device
                    dispatch faults; the circuit breaker must re-serve
                    from the host byte-identically and later recover.
``hbm_eviction``    ``hbm_cache().evict_unpinned()`` hammered from a side
                    thread while scans run (mid-scan eviction pressure).
``commit_ack_crash`` ``fault.raft_apply_stall`` held while one write is
                    acked at COMMIT time (pipelined apply still queued),
                    then the leader crashes before applying; after
                    restart the acked write must survive WAL replay and
                    every peer's apply lag must drain back to 0.
``chip_loss``       A mesh chip drops out mid paged row scan
                    (``fault.mesh_dispatch`` armed between two pages of
                    a mesh-served LIMIT scan): the MeshScanService
                    releases every stacked placement, the request
                    bounces to the per-tablet host path, and the full
                    host re-serve must be byte-identical to the mesh
                    serve taken before the loss. Per-device pins unwind
                    to zero (the ``device/sharded`` MemTracker subtree
                    reads 0 after the fault).
==================  =======================================================

Invariants after every round (each returns a list of error strings):

1. **No acked write lost** — every acknowledged write is visible at its
   exact value; writes whose ack was lost to a fault may hold either the
   old or the attempted value (never anything else).
2. **Engine diff** — for every TPU-engine leader, the device scan path
   and the host (CPU) serve path return byte-identical rows; the
   breaker must be recovered (``yb_engine_degraded == 0``) first.
3. **No leaked residency pins** — ``hbm_cache().pinned_bytes() == 0``
   once no scan is in flight. With the resource witness live
   (``--resource-witness-out`` / ``--pin_witness``) a violation names
   the acquire site and thread of every outstanding pin.
4. **MemTracker baseline** — after evicting every unpinned entry the
   device subtree's consumption returns to the post-setup baseline
   (a leaked pin or unaccounted upload shows up here).

The harness also asserts its injection ledger against the
``yb_faults_fired{name=...}`` process metric — the fault points
themselves count fires, so a fault that silently failed to arm (or
fired twice) is caught rather than trusted.
"""

from __future__ import annotations

import random
import threading
import time

from yugabyte_db_tpu.client.session import YBSession
from yugabyte_db_tpu.integration.mini_cluster import MiniCluster
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage.breaker import degraded
from yugabyte_db_tpu.storage.residency import hbm_cache
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.utils.fault_injection import (arm_fault_once,
                                                   clear_faults)
from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.memtracker import root_tracker
from yugabyte_db_tpu.utils.metrics import faults_fired

FAULT_CATALOG = ("wal_sync", "respond_dropped", "leader_crash",
                 "device_dispatch", "hbm_eviction", "commit_ack_crash",
                 "chip_loss")

# Catalog entries backed by a maybe_fault() point (armed one-shot and
# asserted against the yb_faults_fired metric).
ARMED_FLAG = {
    "wal_sync": "fault.wal_sync_failed",
    "respond_dropped": "fault.ts_write_respond_failed",
    "device_dispatch": "fault.tpu_dispatch",
}

# Catalog entries whose handler arms AND reaches the fault point itself
# (the round's trailing op/scan cannot be relied on to hit it); still
# asserted against yb_faults_fired like the ARMED_FLAG entries.
HANDLER_FLAG = {
    "chip_loss": "fault.mesh_dispatch",
}

# "the row is absent" in the oracle / acceptable-value sets.
ABSENT = object()


class FaultSweep:
    """One seeded sweep: a MiniCluster with a TPU-engine table, a
    keyed write/scan workload, one fault per round, invariants after
    each. ``run()`` returns a summary dict or raises AssertionError
    with every violated invariant (prefixed by the seed, so the report
    alone is enough to replay)."""

    def __init__(self, data_root: str, seed: int, rounds: int = 5,
                 ops_per_round: int = 16,
                 faults: tuple = FAULT_CATALOG,
                 schedule: tuple | None = None,
                 num_tservers: int = 3, num_tablets: int = 2,
                 keyspace: int = 48, witness_out: str | None = None,
                 compile_witness_out: str | None = None,
                 resource_witness_out: str | None = None):
        self.data_root = data_root
        self.seed = seed
        self.rounds = len(schedule) if schedule is not None else rounds
        self.ops_per_round = ops_per_round
        self.faults = tuple(faults)
        # Explicit per-round fault names (deterministic coverage: one
        # round per catalog entry); None = rng-chosen from ``faults``.
        self.schedule = tuple(schedule) if schedule is not None else None
        self.num_tservers = num_tservers
        self.num_tablets = num_tablets
        self.keys = [f"k{i:04d}" for i in range(keyspace)]
        self.rng = random.Random(seed)
        # key -> last acked value (ABSENT = acked delete / never written)
        self.oracle: dict[str, object] = {}
        # key -> set of acceptable values while the last write's ack was
        # lost to a fault (old value or attempted value, until a later
        # acked write re-fixes it)
        self.ambiguous: dict[str, set] = {}
        self._next_value = 0
        self.fired_ledger: dict[str, int] = {}
        self.errors: list[str] = []
        self.mc: MiniCluster | None = None
        self.client = None
        self.table = None
        # Dump lock-witness observations here after the sweep (also
        # honors the --lock_witness flag without a path, for ad-hoc
        # runs; the dump is meant for yb-lint --witness-check).
        self.witness_out = witness_out
        # Same contract for the compile witness (utils/jitting.py):
        # per-entry XLA compile counts, honoring --compile_witness.
        self.compile_witness_out = compile_witness_out
        # And for the resource witness (utils/resources.py): pin
        # acquire/release attribution + holds-across-blocking, honoring
        # --pin_witness. With the witness live, the no-leaked-pins
        # invariant names the exact acquire site of every leak.
        self.resource_witness_out = resource_witness_out

    # -- lifecycle -----------------------------------------------------------

    def setup(self) -> None:
        FLAGS.set("fault.seed", self.seed, force=True)
        self._fired_base = {n: faults_fired(f)
                            for n, f in {**ARMED_FLAG,
                                         **HANDLER_FLAG}.items()}
        self.mc = MiniCluster(
            self.data_root, num_tservers=self.num_tservers,
            # A fast breaker so degrade -> half-open probe -> recover
            # fits inside one round.
            engine_options={"breaker_cooldown_s": 0.05,
                            "breaker_failure_threshold": 1}).start()
        self.mc.wait_tservers_registered()
        self.client = self.mc.client()
        self.client.create_table("sweep", [
            ColumnSchema("k", DataType.STRING, ColumnKind.HASH),
            ColumnSchema("v", DataType.INT64)],
            num_tablets=self.num_tablets, engine="tpu")
        self.table = self.client.open_table("sweep")
        # Pre-fill + flush so the device path has runs to scan.
        s = YBSession(self.client)
        for k in self.keys[: len(self.keys) // 2]:
            v = self._bump_value()
            s.insert(self.table, {"k": k, "v": v})
            self.oracle[k] = v
        s.flush()
        self._flush_tablets()
        self._scan_cluster()  # warm the device path
        self._quiesce_device()
        self._device_baseline = root_tracker().child("device").consumption

    def teardown(self) -> None:
        clear_faults()
        FLAGS.set("fault.seed", 0, force=True)
        if self.mc is not None:
            self.mc.shutdown()
            self.mc = None

    def run(self) -> dict:
        from yugabyte_db_tpu.utils import jitting, locking, resources

        # Enable BEFORE setup so every lock the cluster creates is
        # ownership-tracked from birth.
        wit = self.witness_out is not None or bool(
            FLAGS.get("lock_witness"))
        if wit:
            locking.enable_lock_witness()
        # Likewise before the setup scans: warmup compiles are part of
        # each entry's budget.
        cwit = self.compile_witness_out is not None or bool(
            FLAGS.get("compile_witness"))
        if cwit:
            jitting.enable_compile_witness()
        # And before setup for the resource witness: the pre-fill pins
        # and every guard lock the cluster constructs must be owned.
        rwit = self.resource_witness_out is not None or bool(
            FLAGS.get("pin_witness"))
        if rwit:
            resources.enable_resource_witness()
        self.setup()
        try:
            for rnd in range(self.rounds):
                fault = (self.schedule[rnd] if self.schedule is not None
                         else self.faults[self.rng.randrange(
                             len(self.faults))])
                self._run_round(rnd, fault)
                self.errors.extend(
                    f"round {rnd} ({fault}, seed {self.seed}): {e}"
                    for e in self.check_invariants())
            self.errors.extend(
                f"final (seed {self.seed}): {e}"
                for e in self._check_fired_ledger())
            if self.errors:
                raise AssertionError(
                    "fault sweep invariants violated:\n  "
                    + "\n  ".join(self.errors))
            return {"seed": self.seed, "rounds": self.rounds,
                    "faults_fired": dict(self.fired_ledger),
                    "keys": len(self.oracle)}
        finally:
            self.teardown()
            if wit:
                if self.witness_out is not None:
                    locking.dump_lock_witness(self.witness_out)
                locking.disable_lock_witness()
            if cwit:
                if self.compile_witness_out is not None:
                    jitting.dump_compile_witness(self.compile_witness_out)
                jitting.disable_compile_witness()
            if rwit:
                if self.resource_witness_out is not None:
                    resources.dump_resource_witness(
                        self.resource_witness_out)
                resources.disable_resource_witness()

    # -- one round -----------------------------------------------------------

    def _run_round(self, rnd: int, fault: str) -> None:
        fire_at = self.rng.randrange(self.ops_per_round)
        evictor = None
        for i in range(self.ops_per_round):
            if i == fire_at:
                evictor = self._fire(fault)
            self._one_op()
            if i % 5 == 4:
                self._scan_cluster()
        # Ensure every armed fault point is actually reached this round:
        # a write (WAL sync + response path) and a scan (device dispatch)
        # both run after the arm point.
        self._one_op(kind="insert")
        self._scan_cluster()
        if evictor is not None:
            evictor.join(timeout=5.0)

    def _fire(self, fault: str) -> threading.Thread | None:
        flag = ARMED_FLAG.get(fault)
        if flag is not None:
            arm_fault_once(flag)
            self.fired_ledger[fault] = self.fired_ledger.get(fault, 0) + 1
            return None
        if fault == "leader_crash":
            self._crash_and_restart_leader()
            return None
        if fault == "commit_ack_crash":
            self._commit_ack_crash()
            return None
        if fault == "chip_loss":
            self._chip_loss()
            return None
        if fault == "hbm_eviction":
            # Eviction pressure racing the scans the round keeps issuing.
            def pound():
                try:
                    for _ in range(20):
                        hbm_cache().evict_unpinned()
                        time.sleep(0.002)
                except Exception as e:  # noqa: BLE001 — surfaced as a failure
                    self.errors.append(f"evictor thread died: {e!r}")

            t = threading.Thread(target=pound, name="sweep-evictor",
                                 daemon=True)
            t.start()
            return t
        raise ValueError(f"unknown fault {fault!r}")

    def _commit_ack_crash(self) -> None:
        """The pipelined-apply durability round: hold
        ``fault.raft_apply_stall`` so commit-time acks go out while
        every apply stays queued, take one acked write inside that
        window, then crash the leader BEFORE anything applies. The
        acked write must come back from WAL replay (checked by
        check_acked_writes via the round's scans), and once the stall
        clears every peer's apply lag (the yb_apply_lag_ops gauge
        source: commit_index - applied_index) must drain to 0."""
        stall_base = faults_fired("fault.raft_apply_stall")
        FLAGS.set("fault.raft_apply_stall", 1.0, force=True)
        try:
            # Acked at commit; apply is stalled cluster-wide, so the
            # ack/apply window is provably open when the leader dies.
            self._one_op(kind="insert")
            counts = {
                uuid: sum(1 for p in ts.tablet_manager.peers()
                          if p.is_leader())
                for uuid, ts in self.mc.tservers.items()}
            victim = max(counts, key=counts.get)
            self.mc.stop_tserver(victim)
        finally:
            FLAGS.set("fault.raft_apply_stall", 0.0, force=True)
        if faults_fired("fault.raft_apply_stall") <= stall_base:
            self.errors.append(
                "commit_ack_crash: fault.raft_apply_stall never fired "
                "(apply was not stalled during the ack window)")
        self.mc.restart_tserver(victim)
        self.mc.wait_tservers_registered()
        # A current-term entry drags the stalled old-term entries to
        # commit on the new leader, then every queue must drain.
        self._one_op(kind="insert")
        self._await_apply_drain()

    def _await_apply_drain(self, timeout_s: float = 10.0) -> None:
        deadline = time.monotonic() + timeout_s
        lag = {}
        while time.monotonic() < deadline:
            lag = {}
            for uuid, ts in self.mc.tservers.items():
                for peer in ts.tablet_manager.peers():
                    rs = peer.raft.stats()
                    d = rs["commit_index"] - rs["applied_index"]
                    if d > 0:
                        lag[f"{uuid}/{peer.tablet_id}"] = d
            if not lag:
                return
            time.sleep(0.05)
        self.errors.append(
            f"commit_ack_crash: apply lag never drained to 0: {lag}")

    def _crash_and_restart_leader(self) -> None:
        counts = {
            uuid: sum(1 for p in ts.tablet_manager.peers()
                      if p.is_leader())
            for uuid, ts in self.mc.tservers.items()}
        victim = max(counts, key=counts.get)
        self.mc.stop_tserver(victim)
        try:
            self._one_op()          # ops fail over to the new leader
        finally:
            self.mc.restart_tserver(victim)
        self.mc.wait_tservers_registered()

    def _chip_loss(self) -> None:
        """The multi-chip availability round: a mesh chip drops out
        between two pages of a mesh-served LIMIT row scan
        (``fault.mesh_dispatch`` fires at the next dispatch). The
        MeshScanService must release every stacked placement — the
        ``device/sharded`` MemTracker subtree reads 0 and the stack
        cache empties — and the full host re-serve must be
        byte-identical to the mesh serve taken before the loss.

        Mesh eligibility needs a single run and an empty memtable, so
        the round flushes + compacts first; that legitimately moves
        device residency, so the MemTracker baseline is re-anchored
        BEFORE the stack is built — the end-of-round invariant then
        measures the chip loss itself, not the flush."""
        self._flush_tablets()
        for ts in self.mc.tservers.values():
            for peer in ts.tablet_manager.peers():
                peer.compact()
        self._quiesce_device()
        self._device_baseline = root_tracker().child("device").consumption

        def tpu_leaders(ts):
            return [p for p in ts.tablet_manager.peers()
                    if p.is_leader()
                    and hasattr(p.tablet.engine, "_serve_host_batch")]

        ts = max(self.mc.tservers.values(),
                 key=lambda t: len(tpu_leaders(t)))
        peers = tpu_leaders(ts)
        if not peers:
            self.errors.append("chip_loss: no TPU leader peers to scan")
            return
        read_ht = min(p.read_time().value for p in peers)
        full = ScanSpec(read_ht=read_ht, projection=["k", "v"])
        paged = ScanSpec(read_ht=read_ht, projection=["k", "v"], limit=8)
        mesh_full = ts.mesh_scan.rows(peers, full)
        first = ts.mesh_scan.rows(peers, paged)
        if mesh_full is None or first is None:
            self.errors.append(
                "chip_loss: mesh path ineligible after flush+compact")
            return
        arm_fault_once("fault.mesh_dispatch")
        self.fired_ledger["chip_loss"] = \
            self.fired_ledger.get("chip_loss", 0) + 1
        lost = ts.mesh_scan.rows(peers, paged, resume=first.resume_key)
        if lost is not None:
            self.errors.append(
                "chip_loss: dispatch served despite the lost chip")
        sharded = root_tracker().child("device").child(
            "sharded").consumption
        if sharded != 0:
            self.errors.append(
                f"chip_loss: {sharded} stacked bytes survived the "
                "lost chip")
        if ts.mesh_scan._stacks:
            self.errors.append("chip_loss: stack cache not emptied")
        host_rows = []
        for p in peers:
            host_rows.extend(
                p.tablet.engine._serve_host_batch([full])[0].rows)
        if mesh_full.rows != host_rows:
            self.errors.append(
                f"chip_loss: host re-serve diverged ({len(host_rows)} "
                f"rows vs mesh {len(mesh_full.rows)})")

    def _one_op(self, kind: str | None = None) -> None:
        k = self.keys[self.rng.randrange(len(self.keys))]
        if kind is None:
            kind = "delete" if self.rng.random() < 0.15 else "insert"
        value = ABSENT if kind == "delete" else self._bump_value()
        s = YBSession(self.client)
        if kind == "delete":
            s.delete(self.table, {"k": k})
        else:
            s.insert(self.table, {"k": k, "v": value})
        try:
            s.flush()
        except Exception:  # noqa: BLE001 — ack lost; outcome ambiguous
            self.ambiguous[k] = {self._current(k), value}
            return
        self.oracle[k] = value
        self.ambiguous.pop(k, None)

    def _current(self, k: str):
        amb = self.ambiguous.get(k)
        if amb:
            # Still unresolved from an earlier lost ack: any previously
            # acceptable value remains acceptable.
            return next(iter(amb))
        return self.oracle.get(k, ABSENT)

    def _bump_value(self) -> int:
        self._next_value += 1
        return self._next_value

    # -- cluster access ------------------------------------------------------

    def _scan_cluster(self) -> dict:
        res = YBSession(self.client).scan(
            self.table, ScanSpec(projection=["k", "v"]))
        return dict(res.rows)

    def _tpu_leader_engines(self):
        for ts in self.mc.tservers.values():
            for peer in ts.tablet_manager.peers():
                if peer.is_leader() and \
                        hasattr(peer.tablet.engine, "_serve_host_batch"):
                    yield peer

    def _flush_tablets(self) -> None:
        for ts in self.mc.tservers.values():
            for peer in ts.tablet_manager.peers():
                peer.flush()

    # -- invariants ----------------------------------------------------------

    def check_invariants(self) -> list[str]:
        errs = []
        errs.extend(self.check_acked_writes())
        errs.extend(self.check_engine_diff())
        errs.extend(self.check_residency_pins())
        errs.extend(self.check_memtracker_baseline())
        return errs

    def check_acked_writes(self) -> list[str]:
        got = self._scan_cluster()
        errs = []
        for k in self.keys:
            actual = got.get(k, ABSENT)
            acceptable = self.ambiguous.get(k)
            if acceptable is None:
                acceptable = {self.oracle.get(k, ABSENT)}
            if actual not in acceptable:
                want = sorted("ABSENT" if v is ABSENT else str(v)
                              for v in acceptable)
                errs.append(
                    f"acked write lost: {k} = "
                    f"{'ABSENT' if actual is ABSENT else actual}, "
                    f"acceptable {want}")
        for k in got:
            if k not in self.keys:
                errs.append(f"phantom row {k!r}")
        return errs

    def check_engine_diff(self) -> list[str]:
        errs = []
        for peer in list(self._tpu_leader_engines()):
            eng = peer.tablet.engine
            spec = ScanSpec(read_ht=peer.read_time().value,
                            projection=["k", "v"])
            self._await_breaker_recovery(eng, spec)
            device = eng.scan_batch([spec])[0]
            host = eng._serve_host_batch([spec])[0]
            if (device.rows, device.resume_key) != (host.rows,
                                                    host.resume_key):
                errs.append(
                    f"engine diff on {peer.tablet_id}: device "
                    f"{len(device.rows)} rows vs host {len(host.rows)}")
        if degraded():
            errs.append(
                "breaker still degraded after recovery probes: "
                f"{[b.name for b in degraded()]}")
        return errs

    def _await_breaker_recovery(self, eng, spec,
                                timeout_s: float = 5.0) -> None:
        """Probe the breaker back to closed: after the cooldown, one
        successful half-open dispatch restores the device path."""
        deadline = time.monotonic() + timeout_s
        while eng.breaker.is_degraded and time.monotonic() < deadline:
            eng.scan_batch([spec])
            time.sleep(0.02)

    def _quiesce_device(self) -> None:
        """Release every legitimate pin holder: the cached delta
        overlays (which pin their primary run while cached), the mesh
        services' stacked placements (rebuilt on the next eligible
        scan), and all unpinned residency. Whatever stays pinned
        afterward is a leak."""
        for ts in self.mc.tservers.values():
            for peer in ts.tablet_manager.peers():
                eng = peer.tablet.engine
                if hasattr(eng, "_drop_overlay_cache"):
                    eng._drop_overlay_cache()
            if hasattr(ts, "mesh_scan"):
                ts.mesh_scan.drop_stacks()
        hbm_cache().evict_unpinned()

    def check_residency_pins(self) -> list[str]:
        self._quiesce_device()
        pinned = hbm_cache().pinned_bytes()
        external = self._external_bytes()
        if pinned > external:
            msg = (f"leaked residency pins: {pinned} pinned bytes "
                   f"({external} external)")
            # With the resource witness live, name the culprits: the
            # acquire site and thread of every pin still outstanding.
            from yugabyte_db_tpu.utils import resources
            if resources.resource_witness_enabled():
                leaks = resources.witness().outstanding()
                if leaks:
                    msg += "".join(
                        f"; {r['key']} acquired at {r['site']} "
                        f"on {r['thread']}" for r in leaks)
            return [msg]
        return []

    def _external_bytes(self) -> int:
        cache = hbm_cache()
        with cache._lock:
            return sum(e.total_bytes
                       for pool in cache._pools.values()
                       for e in pool.values() if e.external)

    def check_memtracker_baseline(self) -> list[str]:
        self._quiesce_device()
        dev = root_tracker().child("device").consumption
        if dev != self._device_baseline:
            return [f"device MemTracker not back to baseline: {dev} "
                    f"(baseline {self._device_baseline})"]
        return []

    def _check_fired_ledger(self) -> list[str]:
        errs = []
        for name, count in self.fired_ledger.items():
            flag = ARMED_FLAG.get(name) or HANDLER_FLAG[name]
            fired = faults_fired(flag) - self._fired_base[name]
            if fired != count:
                errs.append(
                    f"yb_faults_fired{{name={flag}}} = {fired}, "
                    f"harness armed {count}")
        return errs


def run_sweep(data_root: str, seed: int, rounds: int = 5,
              ops_per_round: int = 16,
              faults: tuple = FAULT_CATALOG, **kwargs) -> dict:
    """Run one seeded sweep; returns its summary dict (see FaultSweep)."""
    return FaultSweep(data_root, seed, rounds=rounds,
                      ops_per_round=ops_per_round, faults=faults,
                      **kwargs).run()


if __name__ == "__main__":  # replay a failing seed: python -m ... <seed>
    # With --witness-out PATH the replay records lock-witness
    # observations, with --compile-witness-out PATH per-jit-entry
    # compile counts, and with --resource-witness-out PATH pin/hold
    # attribution — all three dumps feed yb-lint --witness-check.
    import sys
    import tempfile

    argv = list(sys.argv[1:])
    wout = cwout = rwout = None
    if "--witness-out" in argv:
        i = argv.index("--witness-out")
        wout = argv[i + 1]
        del argv[i:i + 2]
    if "--compile-witness-out" in argv:
        i = argv.index("--compile-witness-out")
        cwout = argv[i + 1]
        del argv[i:i + 2]
    if "--resource-witness-out" in argv:
        i = argv.index("--resource-witness-out")
        rwout = argv[i + 1]
        del argv[i:i + 2]
    with tempfile.TemporaryDirectory() as root:
        print(run_sweep(root, int(argv[0]) if argv else 1234,
                        witness_out=wout, compile_witness_out=cwout,
                        resource_witness_out=rwout))
