"""MiniCluster: N masters + M tservers, one process.

Reference analog: src/yb/integration-tests/mini_cluster.{h,cc}. Two
transports: "local" (in-process, with partition/isolate fault injection —
the ExternalMiniCluster kill-testing role) and "socket" (real loopback TCP
through the rpc layer, one Messenger per daemon).
"""

from __future__ import annotations

import os
import time

from yugabyte_db_tpu.client import YBClient
from yugabyte_db_tpu.consensus.raft import RaftOptions
from yugabyte_db_tpu.consensus.transport import LocalTransport
from yugabyte_db_tpu.master.master import Master
from yugabyte_db_tpu.tserver.tablet_server import TabletServer

FAST_RAFT = RaftOptions(election_timeout_s=0.2, heartbeat_interval_s=0.04,
                        lease_s=0.5, rpc_timeout_s=1.0)


class MiniCluster:
    def __init__(self, data_root: str, num_masters: int = 1,
                 num_tservers: int = 3, transport: str = "local",
                 raft_opts: RaftOptions = FAST_RAFT, fsync: bool = False,
                 engine_options: dict | None = None,
                 ts_unresponsive_timeout_s: float = 2.0,
                 heartbeat_interval_s: float = 0.2,
                 ts_cloud_info: dict | None = None):
        self.data_root = data_root
        self.raft_opts = raft_opts
        self.fsync = fsync
        self.engine_options = engine_options
        self.heartbeat_interval_s = heartbeat_interval_s
        self.ts_unresponsive_timeout_s = ts_unresponsive_timeout_s
        # uuid -> {"cloud","region","zone"} labels (zone-aware placement)
        self.ts_cloud_info = ts_cloud_info or {}
        self.master_uuids = [f"m-{i}" for i in range(num_masters)]
        self.tserver_uuids = [f"ts-{i}" for i in range(num_tservers)]
        self.masters: dict[str, Master] = {}
        self.tservers: dict[str, TabletServer] = {}
        self._messengers: dict[str, object] = {}
        self.transport_kind = transport
        if transport == "local":
            self.transport = LocalTransport()
        elif transport == "socket":
            from yugabyte_db_tpu.rpc import SocketTransport
            self.transport = SocketTransport()
        else:
            raise ValueError(transport)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "MiniCluster":
        for uuid in self.master_uuids:
            self.start_master(uuid)
        for uuid in self.tserver_uuids:
            self.start_tserver(uuid)
        return self

    def _node_transport(self, uuid: str):
        if self.transport_kind == "local":
            return self.transport.bind(uuid)
        return self.transport

    def _wire_handler(self, uuid: str, handler) -> tuple | None:
        if self.transport_kind == "local":
            self.transport.register(uuid, handler)
            return None
        from yugabyte_db_tpu.rpc import Messenger
        m = Messenger(uuid)
        host, port = m.listen("127.0.0.1", 0, handler)
        self.transport.set_address(uuid, host, port)
        self._messengers[uuid] = m
        return (host, port)

    def start_master(self, uuid: str) -> Master:
        master = Master(uuid, os.path.join(self.data_root, uuid),
                        self._node_transport(uuid), self.master_uuids,
                        raft_opts=self.raft_opts, fsync=self.fsync,
                        ts_unresponsive_timeout_s=self.ts_unresponsive_timeout_s,
                        balance_interval_s=0.3)
        master.advertised_addr = self._wire_handler(uuid, master.handle)
        self.masters[uuid] = master
        master.start()
        return master

    def start_tserver(self, uuid: str) -> TabletServer:
        ts = TabletServer(uuid, os.path.join(self.data_root, uuid),
                          self._node_transport(uuid), self.master_uuids,
                          raft_opts=self.raft_opts,
                          engine_options=self.engine_options,
                          fsync=self.fsync,
                          heartbeat_interval_s=self.heartbeat_interval_s,
                          cloud_info=self.ts_cloud_info.get(uuid))
        ts.advertised_addr = self._wire_handler(uuid, ts.handle)
        self.tservers[uuid] = ts
        ts.start()
        return ts

    def stop_tserver(self, uuid: str) -> None:
        """Stop a tserver (the ExternalMiniCluster 'kill')."""
        if self.transport_kind == "local":
            self.transport.unregister(uuid)
        else:
            m = self._messengers.pop(uuid, None)
            if m is not None:
                m.shutdown()
        ts = self.tservers.pop(uuid, None)
        if ts is not None:
            ts.shutdown()

    def restart_tserver(self, uuid: str) -> TabletServer:
        return self.start_tserver(uuid)

    def shutdown(self) -> None:
        for uuid in list(self.tservers):
            self.stop_tserver(uuid)
        for uuid, master in list(self.masters.items()):
            if self.transport_kind == "local":
                self.transport.unregister(uuid)
            else:
                m = self._messengers.pop(uuid, None)
                if m is not None:
                    m.shutdown()
            master.shutdown()
        self.masters.clear()
        if self.transport_kind == "socket":
            self.transport.close()

    # -- helpers ------------------------------------------------------------
    def client(self, name: str = "client",
               cloud_info: dict | None = None) -> YBClient:
        if self.transport_kind == "local":
            return YBClient(self.transport.bind(name), self.master_uuids,
                            cloud_info=cloud_info)
        return YBClient(self.transport, self.master_uuids,
                        cloud_info=cloud_info)

    def start_webservers(self) -> dict:
        """Start an embedded HTTP server (metrics/varz/tablets) on every
        daemon; returns {uuid: (host, port)}."""
        addrs = {}
        for uuid, m in self.masters.items():
            addrs[uuid] = m.start_webserver()
        for uuid, ts in self.tservers.items():
            addrs[uuid] = ts.start_webserver()
        self.web_addrs = addrs
        return addrs

    def start_cql_server(self, host: str = "127.0.0.1", port: int = 0,
                         **cluster_kwargs):
        """Start a CQL native-protocol proxy over this cluster (the
        reference shape: the tserver process spawns the CQL server on
        port 9042, tablet_server_main.cc:211). Returns (server, (host,
        port)); caller shuts the server down."""
        from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
        from yugabyte_db_tpu.yql.cql.server import CQLServer

        server = CQLServer(ClientCluster(self.client("cql-proxy"),
                                         **cluster_kwargs))
        addr = server.listen(host, port)
        return server, addr

    def start_pg_server(self, host: str = "127.0.0.1", port: int = 0,
                        **cluster_kwargs):
        """Start a PostgreSQL wire-protocol frontend over this cluster
        (the reference shape: the tserver spawns the SQL frontend on port
        5433, tablet_server_main.cc:160). Returns (server, (host, port));
        caller shuts the server down."""
        from yugabyte_db_tpu.yql.cql.client_cluster import ClientCluster
        from yugabyte_db_tpu.yql.pgsql.wire import PgServer

        server = PgServer(ClientCluster(self.client("pg-proxy"),
                                        **cluster_kwargs))
        addr = server.listen(host, port)
        return server, addr

    def leader_master(self, timeout_s: float = 10.0) -> Master:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            for m in self.masters.values():
                if m.is_leader():
                    return m
            time.sleep(0.02)
        raise TimeoutError("no master leader")

    def wait_tservers_registered(self, n: int | None = None,
                                 timeout_s: float = 10.0) -> None:
        want = n if n is not None else len(self.tservers)
        master = self.leader_master(timeout_s)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if len(master.ts_manager.live_tservers()) >= want:
                return
            time.sleep(0.02)
        raise TimeoutError(
            f"{len(master.ts_manager.live_tservers())}/{want} tservers")
