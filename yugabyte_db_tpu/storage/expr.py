"""Scalar expressions pushed down into aggregates.

Reference analog: the pushed-down PgsqlExpressionPB trees evaluated per
row inside the scan (QLExprExecutor, src/yb/common/ql_expr.h:158) — the
TPC-H Q1/Q6 shapes ``sum(l_extendedprice * (1 - l_discount))`` live here.

Device strategy: money-like values are SCALED INTEGERS (cents), so a
product expression is exact integer arithmetic. The device evaluates
``col * f1 [* f2]`` where each factor is a small-range integer expression
(constants ± INT8/INT16 columns, statically bounded < 2^14); per-row
products decompose into 16-bit limbs that ride the existing exact
limb-sum machinery (ops.agg_fold). The host path (CPU engine) evaluates
the same tree in arbitrary-precision Python ints — the oracle.
"""

from __future__ import annotations

from dataclasses import dataclass

from yugabyte_db_tpu.models.datatypes import DataType


@dataclass(frozen=True)
class Col:
    name: str


@dataclass(frozen=True)
class Const:
    value: int | float  # float constants are host-evaluated only


@dataclass(frozen=True)
class BinOp:
    op: str          # '+', '-', '*'
    left: "Expr"
    right: "Expr"


Expr = Col | Const | BinOp


def eval_expr(expr, get_value):
    """Host evaluation (exact python ints; None is contagious like SQL)."""
    if isinstance(expr, Col):
        return get_value(expr.name)
    if isinstance(expr, Const):
        return expr.value
    left = eval_expr(expr.left, get_value)
    right = eval_expr(expr.right, get_value)
    if left is None or right is None:
        return None
    if expr.op == "+":
        return left + right
    if expr.op == "-":
        return left - right
    if expr.op == "*":
        return left * right
    raise ValueError(f"bad op {expr.op}")


def columns_of(expr) -> set[str]:
    if isinstance(expr, Col):
        return {expr.name}
    if isinstance(expr, Const):
        return set()
    return columns_of(expr.left) | columns_of(expr.right)


def bounds(expr, dtype_of) -> tuple[int, int]:
    """Static [lo, hi] interval of an integer expression from column
    dtype ranges (drives the device small-factor eligibility check)."""
    if isinstance(expr, Const):
        if not isinstance(expr.value, int) or isinstance(expr.value, bool):
            # float constants: host-only (the device factor encoding is
            # exact integer limbs) — reject so lower_product falls back
            raise ValueError(f"non-integer constant {expr.value!r}")
        return expr.value, expr.value
    if isinstance(expr, Col):
        dt = dtype_of(expr.name)
        if dt == DataType.BOOL:
            return 0, 1
        if not dt.is_integer:
            raise ValueError(f"non-integer column {expr.name} in expr")
        bits = dt.np_dtype.itemsize * 8
        return -(1 << (bits - 1)), (1 << (bits - 1)) - 1
    llo, lhi = bounds(expr.left, dtype_of)
    rlo, rhi = bounds(expr.right, dtype_of)
    if expr.op == "+":
        return llo + rlo, lhi + rhi
    if expr.op == "-":
        return llo - rhi, lhi - rlo
    cands = (llo * rlo, llo * rhi, lhi * rlo, lhi * rhi)
    return min(cands), max(cands)


def lower_product(expr, dtype_of):
    """Decompose an expression into (base column, [small factor exprs])
    for the device path: base * f1 * f2 ... where the base is one wide
    integer column and every factor's static bound fits |f| < 2^14 and
    references only narrow (INT8/INT16/BOOL) columns.

    Returns (base_name, factors) or None when not device-lowerable."""
    factors = []
    base = None

    def walk(e):
        nonlocal base
        if isinstance(e, BinOp) and e.op == "*":
            walk(e.left)
            walk(e.right)
            return
        if isinstance(e, Col) and dtype_of(e.name).is_integer and \
                dtype_of(e.name).np_dtype.itemsize >= 4:
            if base is not None:
                raise ValueError("two wide columns")
            base = e.name
            return
        factors.append(e)

    try:
        walk(expr)
    except ValueError:
        return None
    if base is None:
        # No wide base: a bare narrow column/constant product still works
        # with base=None handled by the caller (treated as factor-only).
        return None
    for f in factors:
        try:
            lo, hi = bounds(f, dtype_of)
        except ValueError:
            return None
        if max(abs(lo), abs(hi)) >= (1 << 14):
            return None
        for cname in columns_of(f):
            if dtype_of(cname).np_dtype.itemsize > 2 and \
                    dtype_of(cname) != DataType.BOOL:
                return None
    if len(factors) > 2:
        return None
    return base, factors
