"""The storage-engine seam: the pluggable boundary the query layer scans through.

Reference analog: common::YQLStorageIf (src/yb/common/ql_storage_interface.h:31)
— the only interface the query execution layer uses to read a tablet, with
the engine selected where the tablet injects its storage
(src/yb/tablet/tablet.h:648). Here the seam also carries writes (the
reference applies writes through rocksdb::DB::Write below the same tablet).

Engines:
- ``cpu``: exact Python/numpy engine — the correctness oracle and the
  baseline the TPU engine is benchmarked against.
- ``tpu``: columnar HBM-resident data plane driven by JAX/Pallas kernels
  (the ``tablet_storage_engine=tpu`` option of the north star).
"""

from __future__ import annotations

import abc

from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec


class StorageEngine(abc.ABC):
    """Per-tablet storage: an LSM of MVCC row versions behind a scan API."""

    def __init__(self, schema: Schema, options: dict | None = None):
        from yugabyte_db_tpu.utils.memtracker import root_tracker

        self.schema = schema
        self.options = dict(options or {})
        # Hierarchical memory accounting: root -> memstore -> this engine
        # (reference: the MemTracker tree + the shared memstore budget,
        # mem_tracker.h / docdb_rocksdb_util.cc:437 memory_monitor).
        self.mem_tracker = root_tracker().child("memstore").child(
            self.options.get("tracker_name", f"engine-{id(self):x}"))
        self._tracked_bytes = 0
        # Engines with a device dispatch path install a CircuitBreaker
        # (storage/breaker.py) here; None = pure-host engine, nothing to
        # quarantine. /healthz and yb_engine_degraded read the breaker
        # registry, not this attribute.
        self.breaker = None

    def _track_memstore(self) -> None:
        """Sync this engine's tracker with its memtable size. Crossing
        the GLOBAL memstore budget flushes this engine only when it is
        (one of) the LARGEST memstore consumers — flushing whichever
        writer merely noticed would storm tiny flushes while the real
        offender stays resident (the reference's memory monitor also
        picks the largest memstore). An over-budget engine that never
        writes again keeps its memory until its own next apply/flush."""
        from yugabyte_db_tpu.utils.flags import FLAGS

        mem = getattr(self, "memtable", None)
        current = 0 if mem is None else mem.approx_bytes
        delta = current - self._tracked_bytes
        if delta:
            self.mem_tracker.consume(delta)
            self._tracked_bytes = current
        parent = self.mem_tracker.parent
        if current and parent is not None and \
                parent.consumption > FLAGS.get("global_memstore_limit_bytes"):
            with parent._lock:
                largest = max((c.consumption
                               for c in parent._children.values()),
                              default=0)
            if current >= largest:
                self.flush()
                self._track_memstore()  # memtable swapped: release to 0

    # -- writes ------------------------------------------------------------
    @abc.abstractmethod
    def apply(self, rows: list[RowVersion]) -> None:
        """Apply committed row versions (the Raft-apply stage calls this)."""

    def apply_block(self, block: bytes) -> None:
        """Apply an encoded row block (storage.rowblock layout) — the
        native write path's zero-materialization ingest. The default
        decodes and delegates; engines with a block-aware memtable
        override it."""
        from yugabyte_db_tpu.storage import rowblock

        self.apply(rowblock.rows_from_block(block))

    # -- reads -------------------------------------------------------------
    @abc.abstractmethod
    def scan(self, spec: ScanSpec) -> ScanResult:
        """MVCC scan/aggregate at spec.read_ht over [lower, upper)."""

    def scan_batch(self, specs: list[ScanSpec],
                   deadline=None) -> list[ScanResult]:
        """Execute many scans. Engines with an accelerator data plane
        override this to pipeline device dispatches (one host↔device
        round-trip for the whole batch) — the analog of the reference
        serving hundreds of concurrent YCSB clients per tserver.
        ``deadline`` (utils.retry.Deadline) is the RPC edge's propagated
        budget: the batch aborts with Code.TIMED_OUT instead of serving
        results nobody is waiting for."""
        out = []
        for s in specs:
            if deadline is not None:
                deadline.check("scan_batch")
            out.append(self.scan(s))
        return out

    def scan_batch_wire(self, specs: list[ScanSpec], fmt: str = "cql",
                        deadline=None):
        """Execute many scans and return each result as serialized
        protocol bytes (host_page.WirePage): fmt "cql" = CQL binary
        cells, "pg" = PG text DataRow messages. This base implementation
        scans then serializes in Python (models.wirefmt — the format
        definition); the TPU engine overrides the LIMIT-page path with
        the native wire page server, which emits the same bytes straight
        from plane buffers. Reference contract: rows serialize once into
        rows_data (src/yb/common/ql_rowblock.h:66) and the YQL frontends
        forward bytes."""
        from yugabyte_db_tpu.storage.host_page import wire_from_result

        return [wire_from_result(self, r, fmt)
                for r in self.scan_batch(specs, deadline=deadline)]

    def point_serve(self, keys: list[bytes], read_ht: int, col_id: int):
        """Batch point-value lookup for the native request-batch serving
        path: one value column of each full-doc-key row, straight from
        the native memtable. Returns ``None`` when this engine cannot
        answer the batch definitively (sorted runs on disk, non-native
        memtable, spilled rows) — the caller falls back to the general
        read path. Otherwise a list aligned with ``keys`` whose entries
        are payload ``bytes``, ``None`` (absent row / NULL column), or
        ``False`` (value not natively servable: fall back per key)."""
        if getattr(self, "runs", None):
            return None
        lookup = getattr(getattr(self, "memtable", None),
                         "point_lookup", None)
        if lookup is None:
            return None
        return lookup(keys, read_ht, col_id)

    # -- lifecycle ---------------------------------------------------------
    @abc.abstractmethod
    def flush(self) -> None:
        """Persist the memtable as a new sorted run."""

    @abc.abstractmethod
    def compact(self, history_cutoff_ht: int = 0) -> None:
        """Merge all sorted runs into one, GCing history older than cutoff."""

    @abc.abstractmethod
    def stats(self) -> dict:
        """Observability counters (runs, rows, bytes, versions)."""

    def restore_entries(self, entries) -> None:
        """Replace ALL engine content (memtable + runs + persisted files)
        with the given (key, versions) entries — the snapshot-restore
        primitive. Subclasses rebuild their run representations."""
        raise NotImplementedError

    def alter_schema(self, new_schema: Schema) -> None:
        """Adopt an evolved schema (ALTER TABLE). Key columns never
        change; value columns may be added (NULL for existing rows),
        dropped (values become invisible; ids are never reused), or
        renamed (ids are stable, so data is untouched)."""
        self.schema = new_schema

    def maybe_compact(self, history_cutoff_ht: int = 0) -> bool:
        """Universal-compaction trigger: compact when run count reaches the
        threshold (reference: universal style with num_levels=1,
        docdb_rocksdb_util.cc:476-482)."""
        from yugabyte_db_tpu.utils.flags import FLAGS

        trigger = self.options.get("compaction_trigger",
                                   FLAGS.get("compaction_trigger"))
        if self.stats().get("num_runs", 0) >= trigger:
            self.compact(history_cutoff_ht)
            return True
        return False

    def close(self) -> None:
        self.mem_tracker.detach()


_ENGINES: dict[str, type] = {}


def register_engine(name: str, cls: type) -> None:
    _ENGINES[name] = cls


def make_engine(name: str, schema: Schema, options: dict | None = None) -> StorageEngine:
    """Factory behind the ``tablet_storage_engine`` option."""
    if name == "tpu" and name not in _ENGINES:
        # Lazy: importing the TPU engine pulls in jax; CPU-only paths
        # (tools, tests of the host layers) shouldn't pay for it.
        import yugabyte_db_tpu.storage.tpu_engine  # noqa: F401
    if name not in _ENGINES:
        raise ValueError(f"unknown storage engine {name!r}; have {sorted(_ENGINES)}")
    return _ENGINES[name](schema, options)
