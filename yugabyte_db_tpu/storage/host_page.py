"""Host page-cache path: small LIMIT pages served from the columnar
host mirror instead of a device round trip.

Why this exists (the latency story): a LIMIT-k page is *result-bound* —
it returns ~k rows no matter how large the table. On the serving
topology the device link charges a full fetch cycle per synchronous
page and the result bytes ride a narrow D2H pipe, so the roofline
choice for a k≈100-row page is the host mirror of the run, which the
engine already holds (ColumnarRun keeps every plane as numpy — the
build/compaction input). This mirrors the reference serving short
scans/point gets from the RocksDB block cache rather than re-reading
SSTables (src/yb/rocksdb/table/block_based_table_reader.cc); the device
remains the engine for throughput-bound work: aggregates, wide scans,
compaction.

Semantics are an exact host transcription of the device *flat* resolve
(ops/scan.py:_resolve_flat): MVCC visibility at the read point,
tombstones, TTL expiry, liveness/column existence, and device-exact
predicates — eligibility is restricted to exactly the cases where the
device path itself is exact (single source, flat run, i32/i64/f64
value-column predicates), so results are bit-identical to both the
device path and the CPU oracle (engine-diff tests enforce it).

The core data structure is a per-(run, read point, predicates) **match
index**: one vectorized pass computes the row-exists and
predicate-match masks for the whole run, and ``np.nonzero`` turns them
into sorted global-row-index arrays. A page is then two
``searchsorted`` calls + a bounded slice — O(log n + k) — and many
pages amortize one shared decode (scan_batch groups same-structure
pages and decodes their union with one vectorized pass per column).
"""

from __future__ import annotations

import threading

import numpy as np

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec
from yugabyte_db_tpu.utils import planes as P

# Kinds whose plane comparisons are exact on host (mirrors the device
# "exact" predicate set; str/f32 are superset-only and stay on the
# verify paths).
_EXACT_KINDS = ("i32", "i64", "f64")

MAX_PAGE_LIMIT = 4096   # larger scans go to the device gather path
_MASK_CACHE_ENTRIES = 8  # distinct (read point, predicates) per run


def _le2(a_hi, a_lo, b_hi, b_lo):
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


class HostPageIndex:
    """Lazily-built host mirror views + match-index cache for one run."""

    def __init__(self, crun):
        self.crun = crun
        n = crun.B * crun.R
        # reshape(-1) of C-contiguous [B, R] arrays: views, not copies.
        self.valid = crun.valid.reshape(n)
        self.tomb = crun.tomb.reshape(n)
        self.live = crun.live.reshape(n)
        self.ht_hi = crun.ht_hi.reshape(n)
        self.ht_lo = crun.ht_lo.reshape(n)
        self.exp_hi = crun.exp_hi.reshape(n)
        self.exp_lo = crun.exp_lo.reshape(n)
        self.cols = {}
        for cid, col in crun.cols.items():
            self.cols[cid] = (
                col.set_.reshape(n), col.isnull.reshape(n),
                col.cmp_planes.reshape(n, col.cmp_planes.shape[-1]))
        self._lock = threading.Lock()
        self._masks: dict = {}
        self._colspec_cache: dict = {}  # native emit specs (serve_pages)
        self._ht_bounds = None

    _TIMELESS = ("timeless",)

    def cache_planes(self, read_planes):
        """Collapse the mask-cache key for 'current' reads: every read
        point at or beyond the run's last commit and before its first
        expiry sees identical masks, so a server whose read hybrid time
        advances with every write (the steady state) reuses ONE cached
        entry instead of recomputing full-run masks per read point.
        Reference analog: RocksDB serves such reads from the same block
        cache entries regardless of snapshot sequence number."""
        if self._ht_bounds is None:
            v = self.valid
            if v.any():
                hh, hl = self.ht_hi[v], self.ht_lo[v]
                mh = int(hh.max())
                commit = (mh, int(hl[hh == mh].max()))
                eh, el = self.exp_hi[v], self.exp_lo[v]
                xh = int(eh.min())
                expiry = (xh, int(el[eh == xh].min()))
            else:
                commit = (2**31 - 1, 2**31 - 1)  # never canonicalize
                expiry = (-2**31, -2**31)
            self._ht_bounds = (commit, expiry)
        commit, expiry = self._ht_bounds
        r_hi, r_lo, e_hi, e_lo = read_planes
        if commit <= (r_hi, r_lo) and expiry > (e_hi, e_lo):
            return self._TIMELESS
        return read_planes

    def masks(self, read_planes, pred_items, cache_planes=None):
        """(match_idx, exists_idx, notnull{cid}) for one read point +
        predicate list; cached. ``pred_items`` is a hashable tuple of
        (cid, kind, op, literal-encoding). ``cache_planes`` overrides
        the cache key (see cache_planes())."""
        key = (read_planes if cache_planes is None else cache_planes,
               pred_items)
        with self._lock:
            hit = self._masks.get(key)
            if hit is not None:
                return hit
        r_hi, r_lo, e_hi, e_lo = read_planes
        visible = self.valid & _le2(self.ht_hi, self.ht_lo, r_hi, r_lo)
        expired = _le2(self.exp_hi, self.exp_lo, e_hi, e_lo)
        alive = visible & ~self.tomb
        not_expired = ~expired
        exists = alive & self.live & not_expired
        notnull = {}
        for cid, (set_f, isnull_f, _cmp) in self.cols.items():
            nn = alive & set_f & ~isnull_f & not_expired
            notnull[cid] = nn
            exists = exists | nn
        result = exists
        for cid, kind, op, lit in pred_items:
            result = result & notnull[cid] & self._pred_mask(cid, kind,
                                                             op, lit)
        entry = (np.nonzero(result)[0], np.nonzero(exists)[0], notnull)
        with self._lock:
            if len(self._masks) >= _MASK_CACHE_ENTRIES:
                self._masks.pop(next(iter(self._masks)))
            self._masks[key] = entry
        return entry

    def _pred_mask(self, cid, kind, op, lit):
        cmp = self.cols[cid][2]
        if kind == "i32":
            v, x = cmp[:, 0], lit
            return {"=": v == x, "!=": v != x, "<": v < x, "<=": v <= x,
                    ">": v > x, ">=": v >= x}[op]
        hi, lo = cmp[:, 0], cmp[:, 1]
        lhi, llo = lit
        eq = (hi == lhi) & (lo == llo)
        lt = (hi < lhi) | ((hi == lhi) & (lo < llo))
        return {"=": eq, "!=": ~eq, "<": lt, "<=": lt | eq,
                ">": ~(lt | eq), ">=": ~lt}[op]


def encode_pred_items(engine, preds):
    """Predicates -> hashable (cid, kind, op, literal-encoding) tuple, or
    None when any predicate isn't host-exact (caller falls back)."""
    items = []
    for p in preds:
        cid = engine._name_to_id.get(p.column)
        if cid is None:
            return None
        kind = engine._kinds[cid]
        if kind not in _EXACT_KINDS or p.op == "IN":
            return None
        if kind == "i32":
            lit = int(p.value)
        elif kind == "i64":
            hi, lo = P.i64_to_ordered_planes(
                np.array([int(p.value)], dtype=np.int64))
            lit = (int(hi[0]), int(lo[0]))
        else:  # f64
            hi, lo = P.f64_to_ordered_planes(
                np.array([p.value], dtype=np.float64))
            lit = (int(hi[0]), int(lo[0]))
        items.append((cid, kind, p.op, lit))
    return tuple(items)


class HostPage:
    """One planned page: the index slice is computed at plan time (pure
    host work, batch-vectorized in plan_pages); decode happens batched
    at finish time."""

    __slots__ = ("engine", "trun", "spec", "sel", "scanned", "hit_limit",
                 "notnull", "struct_key")

    def __init__(self, engine, trun, spec, sel, scanned, hit_limit,
                 notnull):
        self.engine = engine
        self.trun = trun
        self.spec = spec
        self.sel = sel
        self.scanned = scanned
        self.hit_limit = hit_limit
        self.notnull = notnull
        self.struct_key = (id(trun), tuple(spec.projection or ()))

    def result(self, rows, columns=None) -> ScanResult:
        crun = self.trun.crun
        if columns is None:
            columns = list(self.spec.projection
                           or (c.name for c in self.engine.schema.columns))
        resume = (crun.key_at(int(self.sel[-1])) + b"\x00"
                  if self.hit_limit else None)
        return ScanResult(columns, rows, resume, self.scanned)


def plan_pages(engine, items):
    """Plan many pages at once: items is [(trun, spec, pred_items)];
    pages sharing (run, read point, predicates) — the common server
    shape — resolve their range bounds with ONE vectorized searchsorted
    over the shared match index. Returns [HostPage] in items order."""
    out = [None] * len(items)
    groups: dict = {}
    for i, (trun, spec, pred_items) in enumerate(items):
        idx = trun.host_index
        if idx is None:
            idx = trun.host_index = HostPageIndex(trun.crun)
        read_planes = engine._read_plane_ints(spec)
        crp = idx.cache_planes(read_planes)
        key = (id(trun), crp, pred_items)
        g = groups.get(key)
        if g is None:
            g = groups[key] = (trun, read_planes, crp, pred_items, [])
        g[4].append((i, spec))
    for trun, read_planes, crp, pred_items, members in groups.values():
        crun = trun.crun
        idx = trun.host_index
        match_idx, exists_idx, notnull = idx.masks(read_planes, pred_items,
                                                   cache_planes=crp)
        n_rows = crun.total_rows()
        row_los = [crun.lower_row(s.lower) for _i, s in members]
        i0s = match_idx.searchsorted(np.array(row_los, dtype=np.int64))
        for (i, spec), row_lo, i0 in zip(members, row_los, i0s.tolist()):
            if spec.upper:
                row_hi = crun.upper_row(spec.upper)
                i1 = int(match_idx.searchsorted(row_hi))
            else:
                row_hi = n_rows
                i1 = len(match_idx)
            limit = spec.limit
            take = min(i1 - i0, limit) if limit is not None else (i1 - i0)
            sel = match_idx[i0:i0 + take]
            hit_limit = limit is not None and take >= limit and take > 0
            # Work statistic: existing rows examined through the last
            # consumed row (whole range when nothing matched).
            hi_row = int(sel[-1]) + 1 if take > 0 else row_hi
            scanned = int(exists_idx.searchsorted(hi_row) -
                          exists_idx.searchsorted(row_lo))
            out[i] = HostPage(engine, trun, spec, sel, scanned, hit_limit,
                              notnull)
    return out


def decode_pages(engine, pages: list[HostPage]) -> list[ScanResult]:
    """Decode a group of same-structure pages with ONE vectorized pass
    per projected column over the union of their selected rows."""
    if not pages:
        return []
    trun = pages[0].trun
    crun = trun.crun
    notnull = pages[0].notnull
    projection = (pages[0].spec.projection
                  or [c.name for c in engine.schema.columns])
    counts = [len(p.sel) for p in pages]
    parts = [p.sel for p in pages if len(p.sel)]
    if parts:
        sel = np.concatenate(parts) if len(parts) > 1 else parts[0]
        key_col_pos = {c.name: i
                       for i, c in enumerate(engine.schema.key_columns)}
        kv_cols = None
        if any(nm in key_col_pos for nm in projection):
            kv_cols = crun.key_col_arrays(
                None if crun.kv_ready
                else np.unique(sel // crun.R).tolist())
        cols_out = []
        for nm in projection:
            if nm in key_col_pos:
                cols_out.append(kv_cols[key_col_pos[nm]][sel].tolist())
            else:
                cols_out.append(
                    _decode_value_col(engine, trun, nm, sel, notnull))
        rows_all = list(zip(*cols_out))
    else:
        rows_all = []
    cols_list = list(projection)
    out = []
    off = 0
    for p, n in zip(pages, counts):
        # NOTE: results share one columns list per group; callers treat
        # ScanResult.columns as read-only (every engine path does).
        out.append(p.result(rows_all[off:off + n], cols_list))
        off += n
    return out


# -- native page server -------------------------------------------------

try:
    from yugabyte_db_tpu.native import yb_wp as _native
except Exception:  # noqa: BLE001 — pure-Python fallback
    _native = None
if _native is not None and not hasattr(_native, "serve_page"):
    _native = None  # stale extension build


def _native_key_ctx(trun):
    """(blob, offsets i64, valid_rows i64) for C binary search over the
    run's keys — built once per run."""
    ctx = getattr(trun, "_page_key_ctx", None)
    if ctx is None:
        crun = trun.crun
        keys: list[bytes] = []
        rows = []
        for b in range(crun.B):
            nv = crun.blocks[b].num_valid
            if nv:
                keys.extend(crun.row_keys[b, :nv].tolist())
                rows.append(np.arange(b * crun.R, b * crun.R + nv,
                                      dtype=np.int64))
        offsets = np.zeros(len(keys) + 1, dtype=np.int64)
        if keys:
            np.cumsum(np.fromiter(map(len, keys), np.int64, len(keys)),
                      out=offsets[1:])
        blob = b"".join(keys)
        valid_rows = (np.concatenate(rows) if rows
                      else np.zeros(0, np.int64))
        ctx = trun._page_key_ctx = (blob, offsets, valid_rows)
    return ctx


def _native_obj_col(engine, trun, cid):
    """Global-row-indexed value list for a str/f32 column (exact host
    payloads) — built once per (run, column)."""
    cache = getattr(trun, "_page_obj_cols", None)
    if cache is None:
        cache = trun._page_obj_cols = {}
    vals = cache.get(cid)
    if vals is None:
        crun = trun.crun
        vals = [None] * (crun.B * crun.R)
        for b in range(crun.B):
            nv = crun.blocks[b].num_valid
            rvs = crun.row_versions[b]
            base = b * crun.R
            for r in range(nv):
                rv = rvs[r]
                if rv is not None:
                    vals[base + r] = rv.columns.get(cid)
        cache[cid] = vals
    return vals


# Runs above this size don't eagerly materialize the O(run) object
# lists the native emitter needs for key/str/f32 columns — those pages
# take the per-touched-block numpy path instead (the documented
# small-page latency property).
NATIVE_PAGE_OBJ_MAX = 2_000_000


def _native_colspecs(engine, trun, projection, notnull):
    """Per-column emit specs for yb_wp.serve_page, or None when this
    projection would require an eager O(run) object materialization on
    a run too large to pay it (caller falls back to plan/decode)."""
    key_col_pos = {c.name: i
                   for i, c in enumerate(engine.schema.key_columns)}
    crun = trun.crun
    big = crun.B * crun.R > NATIVE_PAGE_OBJ_MAX
    kv_lists = None
    specs = []
    for nm in projection:
        if nm in key_col_pos:
            if kv_lists is None:
                kv_cache = getattr(trun, "_page_kv_lists", None)
                if kv_cache is None:
                    if big:
                        return None
                    kv_cache = trun._page_kv_lists = [
                        a.tolist()
                        for a in trun.crun.key_col_arrays(None)]
                kv_lists = kv_cache
            specs.append(("obj", kv_lists[key_col_pos[nm]]))
            continue
        cid = engine._name_to_id[nm]
        kind = engine._kinds[cid]
        nn = notnull[cid]
        if kind in ("str", "f32"):
            if big and cid not in getattr(trun, "_page_obj_cols", {}):
                return None
            specs.append(("objnn", _native_obj_col(engine, trun, cid), nn))
        elif kind in ("i64", "f64"):
            specs.append((kind, trun.host_index.cols[cid][2], nn))
        elif engine._dtypes[cid] == DataType.BOOL:
            specs.append(("bool", trun.host_index.cols[cid][2][:, 0], nn))
        else:
            specs.append(("i32", trun.host_index.cols[cid][2][:, 0], nn))
    return tuple(specs)


def serve_pages(engine, items):
    """Serve many pages through the native page server (yb_wp.serve_page:
    C binary search over the run's key blob + direct row emission from
    the plane buffers). items is [(trun, spec, pred_items)]; falls back
    to the vectorized-numpy plan/decode pipeline when the extension is
    unavailable. Returns [ScanResult] in items order."""
    if _native is None:
        planned = plan_pages(engine, items)
        groups: dict = {}
        for i, pg in enumerate(planned):
            groups.setdefault(pg.struct_key, []).append((i, pg))
        out = [None] * len(items)
        for members in groups.values():
            decoded = decode_pages(engine, [pg for _i, pg in members])
            for (i, _pg), res in zip(members, decoded):
                out[i] = res
        return out

    out = [None] * len(items)
    cs_cache: dict = {}
    batch_groups: dict = {}

    def ctx_for(trun, spec, pred_items):
        idx = trun.host_index
        if idx is None:
            idx = trun.host_index = HostPageIndex(trun.crun)
        read_planes = engine._read_plane_ints(spec)
        crp = idx.cache_planes(read_planes)
        masks = idx.masks(read_planes, pred_items, cache_planes=crp)
        projection = tuple(spec.projection
                           or (c.name for c in engine.schema.columns))
        ck = (id(trun), crp, pred_items, projection)
        cached = cs_cache.get(ck)
        if cached is None:
            with idx._lock:
                cached = idx._colspec_cache.get(ck)
            if cached is None:
                specs = _native_colspecs(engine, trun, projection,
                                         masks[2])
                cached = ((list(projection), specs)
                          if specs is not None else None)
                with idx._lock:
                    if len(idx._colspec_cache) >= 2 * _MASK_CACHE_ENTRIES:
                        idx._colspec_cache.pop(
                            next(iter(idx._colspec_cache)))
                    idx._colspec_cache[ck] = cached
            cs_cache[ck] = cached
        return ck, masks, cached

    fallback: list = []
    for i, (trun, spec, pred_items) in enumerate(items):
        ck, masks, cached = ctx_for(trun, spec, pred_items)
        if cached is None:  # too-big eager materialization: numpy path
            fallback.append((i, (trun, spec, pred_items)))
            continue
        if not spec.upper and spec.limit is not None:
            # The server shape (forward LIMIT page, no upper bound):
            # group for ONE amortized native call per structure.
            g = batch_groups.get(ck + (spec.limit,))
            if g is None:
                g = batch_groups[ck + (spec.limit,)] = (
                    trun, masks, cached, spec.limit, [], [])
            g[4].append(i)
            g[5].append(spec.lower)
            continue
        cols_list, colspecs = cached
        match_idx, exists_idx, _nn = masks
        blob, offsets, valid_rows = _native_key_ctx(trun)
        rows, scanned, resume = _native.serve_page(
            blob, offsets, valid_rows, match_idx, exists_idx, colspecs,
            spec.lower, spec.upper or b"",
            -1 if spec.limit is None else spec.limit)
        out[i] = ScanResult(cols_list, rows, resume, scanned)

    for trun, masks, cached, limit, idxs, lowers in batch_groups.values():
        cols_list, colspecs = cached
        match_idx, exists_idx, _nn = masks
        blob, offsets, valid_rows = _native_key_ctx(trun)
        served = _native.serve_page_batch(
            blob, offsets, valid_rows, match_idx, exists_idx, colspecs,
            lowers, limit)
        for i, (rows, scanned, resume) in zip(idxs, served):
            out[i] = ScanResult(cols_list, rows, resume, scanned)
    if fallback:
        planned = plan_pages(engine, [it for _i, it in fallback])
        groups: dict = {}
        for (i, _it), pg in zip(fallback, planned):
            groups.setdefault(pg.struct_key, []).append((i, pg))
        for members in groups.values():
            decoded = decode_pages(engine, [pg for _i, pg in members])
            for (i, _pg), res in zip(members, decoded):
                out[i] = res
    return out


# -- native wire pages ---------------------------------------------------
#
# Result pages serialized straight to protocol bytes (CQL cells / PG
# DataRow messages) by native/writeplane.cc's WireEmit — the hot path
# never constructs a Python value object per cell. Plane-resident types
# (ints, doubles, bools) encode inline in C; varlen/f32 payloads and key
# columns ride per-run pre-encoded blobs (one-time O(run) cost, like the
# reference encoding each SSTable block once). Reference contract:
# QLRowBlock::Serialize rows_data (src/yb/common/ql_rowblock.h:66),
# forwarded untouched by the CQL service (cql_processor.cc).

WIRE_CQL = 0
WIRE_PG = 1


class WirePage:
    """One serialized result page (scan_batch_wire output)."""

    __slots__ = ("columns", "data", "nrows", "resume", "scanned",
                 "read_ht")

    def __init__(self, columns, data, nrows, resume, scanned,
                 read_ht=None):
        self.columns = columns
        self.data = data
        self.nrows = nrows
        self.resume = resume
        self.scanned = scanned
        self.read_ht = read_ht


def _wire_blob_cache(trun):
    cache = getattr(trun, "_wire_blobs", None)
    if cache is None:
        cache = trun._wire_blobs = {}
    return cache


def _encode_blob(values, enc):
    """Value list -> (offsets int64[n+1], payload blob). None -> empty
    payload (the nn mask gates NULL at emit time; key columns are never
    None on valid rows)."""
    enc_vals = [b"" if v is None else enc(v) for v in values]
    offsets = np.zeros(len(enc_vals) + 1, dtype=np.int64)
    if enc_vals:
        np.cumsum(np.fromiter(map(len, enc_vals), np.int64,
                              len(enc_vals)), out=offsets[1:])
    return offsets, b"".join(enc_vals)


def _key_wire_blob(engine, trun, pos, fmt):
    """Pre-encoded payload blob for key column `pos` (per run+fmt)."""
    cache = _wire_blob_cache(trun)
    hit = cache.get(("key", pos, fmt))
    if hit is not None:
        return hit
    crun = trun.crun
    if crun.B * crun.R > NATIVE_PAGE_OBJ_MAX:
        return None
    from yugabyte_db_tpu.models import wirefmt

    dt = engine.schema.key_columns[pos].dtype
    vals = crun.key_col_arrays(None)[pos].tolist()
    if fmt == WIRE_CQL:
        w = wirefmt.CQL_INT_WIDTH.get(dt)
        if w is not None:
            # Vectorized: big-endian fixed-width ints straight to bytes.
            arr = np.array([0 if v is None else v for v in vals],
                           dtype=np.int64)
            blob = arr.astype({1: ">i1", 2: ">i2", 4: ">i4",
                               8: ">i8"}[w]).tobytes()
            offsets = np.arange(len(vals) + 1, dtype=np.int64) * w
            entry = (offsets, blob)
        else:
            entry = _encode_blob(vals, lambda v: wirefmt.cql_cell(dt, v)
                                 or b"")
    else:
        entry = _encode_blob(vals, wirefmt.pg_text)
    cache[("key", pos, fmt)] = entry
    return entry


def _obj_wire_blob(engine, trun, cid, fmt):
    """Pre-encoded payload blob for a host-payload value column."""
    cache = _wire_blob_cache(trun)
    hit = cache.get(("val", cid, fmt))
    if hit is not None:
        return hit
    crun = trun.crun
    if crun.B * crun.R > NATIVE_PAGE_OBJ_MAX:
        return None
    from yugabyte_db_tpu.models import wirefmt

    dt = engine._dtypes[cid]
    vals = _native_obj_col(engine, trun, cid)
    if fmt == WIRE_CQL:
        entry = _encode_blob(vals, lambda v: wirefmt.cql_cell(dt, v)
                             or b"")
    else:
        entry = _encode_blob(vals, wirefmt.pg_text)
    cache[("val", cid, fmt)] = entry
    return entry


def _native_wirespecs(engine, trun, projection, notnull, fmt):
    """Per-column wire emit specs for yb_wp.serve_page_wire_batch, or
    None when this projection can't be wire-served natively (caller
    falls back to rows + Python serialization)."""
    from yugabyte_db_tpu.models.wirefmt import CQL_INT_WIDTH

    key_col_pos = {c.name: i
                   for i, c in enumerate(engine.schema.key_columns)}
    hi_cols = trun.host_index.cols
    specs = []
    for nm in projection:
        if nm in key_col_pos:
            kb = _key_wire_blob(engine, trun, key_col_pos[nm], fmt)
            if kb is None:
                return None
            specs.append(("wblob", kb[0], kb[1]))
            continue
        cid = engine._name_to_id.get(nm)
        if cid is None:
            return None
        kind = engine._kinds[cid]
        dt = engine._dtypes[cid]
        nn = notnull[cid]
        if kind == "i64":
            specs.append(("wi64", hi_cols[cid][2], nn))
        elif kind == "f64":
            if fmt == WIRE_CQL:
                specs.append(("wf64", hi_cols[cid][2], nn))
            else:  # PG text floats: repr parity via pre-encoded payloads
                ob = _obj_wire_blob(engine, trun, cid, fmt)
                if ob is None:
                    return None
                specs.append(("wblob", ob[0], ob[1], nn))
        elif dt == DataType.BOOL:
            specs.append(("wbool", hi_cols[cid][2], nn))
        elif kind == "i32":
            w = CQL_INT_WIDTH.get(dt)
            if fmt == WIRE_CQL and w is None:
                return None
            specs.append(("wi32", hi_cols[cid][2], nn, w or 4))
        else:  # str / f32 / opaque payloads
            ob = _obj_wire_blob(engine, trun, cid, fmt)
            if ob is None:
                return None
            specs.append(("wblob", ob[0], ob[1], nn))
    return tuple(specs)


def serve_pages_wire(engine, items, fmt):
    """Serve pages as wire bytes: items is [(trun, spec, pred_items)];
    returns [WirePage | None] in items order (None = not natively
    servable; caller falls back). Pages sharing (run, read point,
    predicates, projection, limit) ride ONE native call."""
    out = [None] * len(items)
    if _native is None or not hasattr(_native, "serve_page_wire_batch"):
        return out
    groups: dict = {}
    cs_cache: dict = {}
    for i, (trun, spec, pred_items) in enumerate(items):
        idx = trun.host_index
        if idx is None:
            idx = trun.host_index = HostPageIndex(trun.crun)
        read_planes = engine._read_plane_ints(spec)
        crp = idx.cache_planes(read_planes)
        projection = tuple(spec.projection
                           or (c.name for c in engine.schema.columns))
        ck = (id(trun), crp, pred_items, projection, fmt,
              spec.limit)
        g = groups.get(ck)
        if g is None:
            cached = cs_cache.get(ck)
            if cached is None:
                with idx._lock:
                    cached = idx._colspec_cache.get(ck)
                if cached is None:
                    masks = idx.masks(read_planes, pred_items,
                                      cache_planes=crp)
                    specs = _native_wirespecs(engine, trun, projection,
                                              masks[2], fmt)
                    cached = ((list(projection), specs, masks)
                              if specs is not None else False)
                    with idx._lock:
                        if len(idx._colspec_cache) >= \
                                2 * _MASK_CACHE_ENTRIES:
                            idx._colspec_cache.pop(
                                next(iter(idx._colspec_cache)))
                        idx._colspec_cache[ck] = cached
                cs_cache[ck] = cached
            if cached is False:
                continue  # not wire-servable: leave None
            g = groups[ck] = (trun, cached, [], [], [])
        g[2].append(i)
        g[3].append(spec.lower)
        g[4].append(spec.upper or b"")
    for trun, (cols_list, wirespecs, masks), idxs, lowers, uppers \
            in groups.values():
        match_idx, exists_idx, _nn = masks
        blob, offsets, valid_rows = _native_key_ctx(trun)
        ulist = uppers if any(uppers) else None
        limit = items[idxs[0]][1].limit
        served = _native.serve_page_wire_batch(
            blob, offsets, valid_rows, match_idx, exists_idx, wirespecs,
            lowers, ulist, -1 if limit is None else limit, fmt)
        for i, (data, nrows, scanned, resume) in zip(idxs, served):
            out[i] = WirePage(cols_list, data, nrows, resume, scanned)
    return out


def wire_from_result(engine, res: ScanResult, fmt) -> WirePage:
    """ScanResult -> WirePage via the Python serializer (the fallback
    twin of the native emitter; models.wirefmt defines the bytes)."""
    from yugabyte_db_tpu.models import wirefmt

    fmt_name = "cql" if fmt in (WIRE_CQL, "cql") else "pg"
    by_name = {c.name: c.dtype for c in engine.schema.columns}
    dts = []
    for i, nm in enumerate(res.columns):
        dt = by_name.get(nm)
        if dt is None:  # computed column (aggregate): infer from values
            dt = DataType.INT64
            for row in res.rows:
                v = row[i]
                if v is None:
                    continue
                dt = (DataType.BOOL if isinstance(v, bool)
                      else DataType.INT64 if isinstance(v, int)
                      else DataType.DOUBLE if isinstance(v, float)
                      else DataType.BINARY
                      if isinstance(v, (bytes, bytearray))
                      else DataType.STRING)
                break
        dts.append(dt)
    data = wirefmt.serialize_rows(fmt_name, dts, res.rows)
    return WirePage(list(res.columns), data, len(res.rows),
                    res.resume_key, res.rows_scanned)


def _decode_value_col(engine, trun, name, sel, notnull):
    crun = trun.crun
    cid = engine._name_to_id[name]
    kind = engine._kinds[cid]
    nn = notnull[cid][sel]
    if kind in ("str", "f32"):
        # Exact payloads live host-side on the RowVersion (flat run: the
        # row IS the single setter) — same source the device path uses.
        R = crun.R
        out = []
        for i, g in enumerate(sel.tolist()):
            if not nn[i]:
                out.append(None)
                continue
            b, r = divmod(g, R)
            out.append(crun.row_versions[b][r].columns[cid])
        return out
    cmp = trun.host_index.cols[cid][2]
    if kind == "i32":
        raw = cmp[sel, 0].tolist()
    elif kind == "i64":
        raw = P.ordered_planes_to_i64(cmp[sel, 0], cmp[sel, 1]).tolist()
    else:  # f64
        raw = P.ordered_planes_to_f64(cmp[sel, 0], cmp[sel, 1]).tolist()
    dt = engine._dtypes[cid]
    if dt == DataType.BOOL:
        return [bool(v) if n else None for v, n in zip(raw, nn.tolist())]
    if nn.all():
        return raw
    for i in np.nonzero(~nn)[0].tolist():
        raw[i] = None
    return raw
