"""Circuit breaker for the device (TPU) dispatch path.

The north star puts the TPU storage engine behind the DocDB boundary
while Raft/txn/RPC stay on CPU — so a device-side failure (dispatch
error, native module fault, HBM exhaustion) must degrade the tablet to
host-path serving, never take it down. This module is the containment
state machine:

    CLOSED ──(failure_threshold consecutive faults)──> OPEN
    OPEN ──(cooldown elapsed)──> HALF_OPEN (exactly one probe admitted)
    HALF_OPEN ──probe succeeds──> CLOSED
    HALF_OPEN ──probe fails────> OPEN (fresh cooldown)

Reference analog: the reference quarantines a misbehaving path by flag
(e.g. rocksdb's background-error mode setting the DB read-only) and
recovers by operator action; the breaker automates the quarantine and
the recovery probe, which is what an unattended device link needs.

Degraded state is observable process-wide: ``yb_engine_degraded`` on
the process registry counts breakers currently NOT closed, and
``degraded()`` feeds every daemon's ``/healthz``.

This module deliberately imports no device framework — it only decides
whether the protected path may run; the engine supplies the host
fallback.
"""

from __future__ import annotations

import threading
import time
import weakref

from yugabyte_db_tpu.utils.locking import guarded_by

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_REGISTRY_LOCK = threading.Lock()
_BREAKERS: "weakref.WeakSet[CircuitBreaker]" = weakref.WeakSet()
_GAUGE_WIRED = False


def _wire_gauge_locked() -> None:
    """Register the ``yb_engine_degraded`` callback gauge once (count of
    breakers currently not CLOSED — 0 means every device path is
    healthy)."""
    global _GAUGE_WIRED
    if _GAUGE_WIRED:
        return
    from yugabyte_db_tpu.utils.metrics import process_registry

    process_registry().entity().gauge(
        "yb_engine_degraded", lambda: len(degraded()))
    _GAUGE_WIRED = True


def register(breaker: "CircuitBreaker") -> None:
    with _REGISTRY_LOCK:
        _BREAKERS.add(breaker)
        _wire_gauge_locked()


def degraded() -> list["CircuitBreaker"]:
    """Breakers currently quarantining their protected path (state is
    sampled without forcing OPEN->HALF_OPEN transitions)."""
    with _REGISTRY_LOCK:
        breakers = list(_BREAKERS)
    return [b for b in breakers if b.state != CLOSED]


def health_report() -> dict:
    """The /healthz fragment: overall status plus one entry per
    degraded breaker."""
    bad = degraded()
    if not bad:
        return {"status": "ok"}
    return {"status": "degraded",
            "degraded": [{"breaker": b.name, "state": b.state,
                          "failures": b.consecutive_failures,
                          "last_error": repr(b.last_error)}
                         for b in bad]}


@guarded_by("_lock", "_state", "_opened_at", "_probe_inflight",
            "consecutive_failures", "trips", "last_error")
class CircuitBreaker:
    """closed -> open -> half-open (single probe) state machine.

    ``allow()`` gates the protected path; ``record_success()`` /
    ``record_failure()`` report the outcome of an admitted call. All
    transitions happen under one lock; ``clock`` is injectable so tests
    don't sleep through cooldowns."""

    def __init__(self, name: str, failure_threshold: int = 3,
                 cooldown_s: float = 1.0, clock=time.monotonic):
        self.name = name
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._opened_at = 0.0
        self._probe_inflight = False
        self.consecutive_failures = 0
        self.trips = 0          # CLOSED/HALF_OPEN -> OPEN transitions
        self.last_error: BaseException | None = None
        register(self)

    # -- gating ---------------------------------------------------------------
    def allow(self) -> bool:
        """May the protected path run now? CLOSED: yes. OPEN: no, until
        the cooldown elapses — then the breaker moves to HALF_OPEN and
        admits exactly one probe; further calls stay on the fallback
        until the probe reports."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                self._state = HALF_OPEN
                self._probe_inflight = True
                return True
            # HALF_OPEN: one probe at a time.
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    # -- outcome reporting ----------------------------------------------------
    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._state = CLOSED
                self.last_error = None

    def record_failure(self, exc: BaseException | None = None) -> None:
        with self._lock:
            self.last_error = exc
            self.consecutive_failures += 1
            if self._state == HALF_OPEN:
                # Failed probe: quarantine again for a fresh cooldown.
                self._probe_inflight = False
                self._state = OPEN
                self._opened_at = self._clock()
                self.trips += 1
            elif (self._state == CLOSED
                    and self.consecutive_failures >= self.failure_threshold):
                self._state = OPEN
                self._opened_at = self._clock()
                self.trips += 1

    def trip(self, exc: BaseException | None = None) -> None:
        """Open immediately regardless of the threshold (a fault the
        caller knows is structural, e.g. the native module is gone)."""
        with self._lock:
            self.last_error = exc
            self.consecutive_failures = max(self.consecutive_failures,
                                            self.failure_threshold)
            if self._state != OPEN:
                self._state = OPEN
                self.trips += 1
            self._probe_inflight = False
            self._opened_at = self._clock()

    def reset(self) -> None:
        """Back to pristine CLOSED (tests / operator action)."""
        with self._lock:
            self._state = CLOSED
            self.consecutive_failures = 0
            self._probe_inflight = False
            self.last_error = None

    # -- introspection --------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def is_degraded(self) -> bool:
        return self.state != CLOSED

    def stats(self) -> dict:
        with self._lock:
            return {"name": self.name, "state": self._state,
                    "consecutive_failures": self.consecutive_failures,
                    "trips": self.trips,
                    "last_error": repr(self.last_error)
                    if self.last_error else None}

    def __repr__(self) -> str:
        return f"CircuitBreaker({self.name!r}, state={self.state})"
