"""The LSM storage engine: memtable, sorted runs, compaction, engines.

Reference analog: src/yb/rocksdb (the forked storage engine) + the storage
half of src/yb/docdb. Differences by design (TPU-first):

- Data blocks are columnar (SoA planes sized for HBM tiling), not row-wise
  prefix-delta byte blocks (reference block_builder.cc:29-46).
- MVCC versions are (key, commit_ht) plane pairs sorted (key asc, ht desc);
  there is no per-instance WAL (the tablet's Raft log is the WAL, matching
  the reference's disabled-rocksdb-WAL design, docdb_rocksdb_util.cc:430).
- Compaction is a device sort-merge over columnar runs rather than a k-way
  heap merge of byte iterators (reference compaction_job.cc:622).

The pluggable seam (reference: common::YQLStorageIf,
src/yb/common/ql_storage_interface.h:31) is storage.engine.StorageEngine,
with CpuStorageEngine (exact oracle + baseline, the InMemDocDbState pattern
from src/yb/docdb/in_mem_docdb.cc) and TpuStorageEngine (device data plane).
"""

from yugabyte_db_tpu.storage.row_version import RowVersion, MAX_HT
from yugabyte_db_tpu.storage.scan_spec import Predicate, ScanSpec, ScanResult, AggSpec
from yugabyte_db_tpu.storage.engine import StorageEngine, make_engine
from yugabyte_db_tpu.storage.cpu_engine import CpuStorageEngine
